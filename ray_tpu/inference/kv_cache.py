"""Paged KV cache management: the host-side block allocator.

vLLM-style paging (PAPERS.md: TPU serving stacks win by packing many
requests into one fixed-shape KV cache): the device holds
``[n_layers, num_blocks, block_size, n_kv_heads, head_dim]`` K and V
tensors (``models.llama.init_paged_kv_cache``); this module owns the
*accounting* — which request holds which block ids, what is free, and
when a new request must wait in the admission queue instead.

Block id 0 is reserved as the NULL block: padding positions in the
fixed-shape prefill/decode steps write their trash there, so it is never
handed to a request. Block ids are layer-agnostic — one id covers
``block_size`` token positions in every layer at once, so the allocator
deals in tokens, not layer-tokens.

Prefix caching (the warm-TTFT tentpole): a FULL block whose token
content has been completely written is immutable from then on — decode
only ever writes positions past it. The manager therefore indexes full
blocks by a *chain digest* (hash of the block's tokens chained with the
previous block's digest — the path-compressed radix tree of vLLM's
automatic prefix caching, stored flat because every node is uniquely
named by its prefix digest). A new request whose prompt prefix matches
cached digests ACQUIRES those blocks (refcounted sharing instead of
re-prefilling) and only the uncached tail goes through prefill.

Copy-on-write covers the one case where a sharer must write into a
shared block: a *full-prompt* hit still needs the last prompt token's
logits, so the final hit block is duplicated device-side
(``model_runner.copy_blocks``) and a 1-token prefill recomputes just
that position into the private copy — the shared original stays
immutable for every other reader.

Eviction: blocks whose refcount drops to zero stay cached (they cost
nothing until the pool is short) on an LRU list; allocation drains the
free list first, then reclaims the oldest unreferenced cached block.
``free_blocks``/``used_blocks`` count cached-but-unreferenced capacity
as free — it is reclaimable at zero cost, and admission control must
see it that way or a warm cache would wedge the queue.

Pure host-side python with no jax dependency: unit-testable without an
accelerator, and cheap enough to run under the engine lock.
"""

from __future__ import annotations

import hashlib
import struct
import threading
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Tuple

#: digest width for the chain hash. 16 bytes: block-content collisions
#: would silently serve wrong KV, so this is sized for "never", not for
#: compactness — the gossip digest truncates to 8-byte ints instead.
_DIGEST_SIZE = 16


def _chain_digest(prev: bytes, block_tokens) -> bytes:
    """Digest naming the prefix that ends with ``block_tokens``."""
    h = hashlib.blake2b(prev, digest_size=_DIGEST_SIZE)
    h.update(struct.pack(f"<{len(block_tokens)}q", *block_tokens))
    return h.digest()


def prefix_block_hashes(tokens, block_size: int) -> List[int]:
    """Chain digests of every FULL block of ``tokens``, truncated to
    signed 64-bit ints — the compact form replicas gossip to routers and
    routers recompute per request for affinity scoring. Must stay in
    lockstep with the manager's internal chain (same function, truncated
    view), or affinity would never match."""
    out: List[int] = []
    prev = b""
    for end in range(block_size, len(tokens) + 1, block_size):
        prev = _chain_digest(prev, tokens[end - block_size : end])
        out.append(struct.unpack("<q", prev[:8])[0])
    return out


class PagedBlockManager:
    """Allocation / free / eviction accounting for the shared block pool."""

    def __init__(
        self,
        num_blocks: int,
        block_size: int,
        *,
        prefix_cache_enabled: bool = False,
        prefix_cache_max_blocks: int = 0,
    ):
        if num_blocks < 2:
            raise ValueError("need >= 2 blocks (block 0 is the null block)")
        if block_size < 1:
            raise ValueError("block_size must be >= 1")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.prefix_cache_enabled = prefix_cache_enabled
        #: cap on indexed blocks (0 = bounded only by the pool itself)
        self.prefix_cache_max_blocks = prefix_cache_max_blocks
        # block 0 = null: never allocated
        self._free: deque = deque(range(1, num_blocks))
        self._owned: Dict[str, List[int]] = {}
        #: block -> number of requests referencing it (shared prefix
        #: blocks count every sharer; COW sources count their pin)
        self._ref: Dict[int, int] = {}
        #: block -> chain digest, for FULL (immutable) cached blocks
        self._block_hash: Dict[int, bytes] = {}
        #: chain digest -> block (the flat radix index). Ordered by
        #: RECENCY OF USE (insertion + move-to-end on every hit): the
        #: gossip digest truncates to the most recent entries, and a hot
        #: shared system prompt must stay inside that window no matter
        #: how long ago it was first indexed.
        self._index: "OrderedDict[bytes, int]" = OrderedDict()
        #: chain digest -> PARENT chain digest (b"" at the root): the
        #: radix-path structure the flat index erases. Gossip export
        #: walks these so every exported digest ships with its whole
        #: ancestor spine — a consecutive-prefix matcher (the router)
        #: can't use an orphan digest whose ancestors were truncated out.
        self._parent: Dict[bytes, bytes] = {}
        #: unreferenced cached blocks, oldest first (block -> digest)
        self._lru: "OrderedDict[int, bytes]" = OrderedDict()
        #: request -> COW source blocks pinned until the device copy ran
        self._cow_src: Dict[str, List[int]] = {}
        #: chain digest -> acquire_prefix hit count (the popularity
        #: signal the spill-vs-drop policy reads at eviction)
        self._hits: Dict[bytes, int] = {}
        #: spill-vs-drop policy hook: ``fn(digest, block, hits) -> bool``
        #: consulted at EVERY indexed-block eviction (allocation-pressure
        #: LRU reclaim and the register cap-eviction — one policy point,
        #: not two divergent code paths). True = the block's content was
        #: spilled somewhere recoverable (the cluster KV tier), False =
        #: dropped. Runs under the manager lock on the step thread: the
        #: hook may read the device (the content dies with the return)
        #: but MUST NOT re-enter locked manager methods or block on IO.
        self._spill_hook = None
        self._lock = threading.Lock()
        # lifetime accounting (engine /metrics + stats())
        self.total_allocs = 0
        self.total_frees = 0
        self.total_evictions = 0
        self.prefix_queries_total = 0
        self.prefix_hits_total = 0
        self.prefix_tokens_saved_total = 0
        self.cow_copies_total = 0
        self.prefix_evictions_total = 0
        #: books-balance split of prefix_evictions_total: every evicted
        #: indexed block is EXACTLY one of spilled (content preserved in
        #: the tier) or dropped (gone) — evictions == spilled + dropped
        self.prefix_spilled_total = 0
        self.prefix_dropped_total = 0

    def set_spill_hook(self, hook) -> None:
        """Install the spill-vs-drop policy (see ``_spill_hook``)."""
        with self._lock:
            self._spill_hook = hook

    # -- capacity ---------------------------------------------------------
    @property
    def usable_blocks(self) -> int:
        return self.num_blocks - 1  # minus the null block

    @property
    def free_blocks(self) -> int:
        """Immediately-allocatable capacity: the free list plus cached
        blocks no live request references (reclaimed on demand)."""
        with self._lock:
            return len(self._free) + len(self._lru)

    @property
    def cached_blocks(self) -> int:
        """Unreferenced blocks held only by the prefix cache."""
        with self._lock:
            return len(self._lru)

    @property
    def used_blocks(self) -> int:
        return self.usable_blocks - self.free_blocks

    def utilization(self) -> float:
        return self.used_blocks / max(1, self.usable_blocks)

    def blocks_for_tokens(self, num_tokens: int) -> int:
        return max(1, -(-num_tokens // self.block_size))  # ceil

    # -- allocation -------------------------------------------------------
    def owned(self, request_id: str) -> List[int]:
        with self._lock:
            return list(self._owned.get(request_id, ()))

    def _evict_indexed_locked(self, blk: int, digest: bytes) -> None:
        """Retire one indexed block from the radix structure — the ONE
        spill-vs-drop policy decision point (ISSUE 17's unlocking
        refactor: LRU eviction and the kv_transfer export path used to
        be unrelated, so KV pressure silently destroyed reusable state).
        The hook sees the block while its device content is still valid
        and decides: spill (preserve in the tier) or drop."""
        del self._index[digest]
        del self._block_hash[blk]
        self._parent.pop(digest, None)
        hits = self._hits.pop(digest, 0)
        self.prefix_evictions_total += 1
        spilled = False
        if self._spill_hook is not None:
            try:
                spilled = bool(self._spill_hook(digest, blk, hits))
            except Exception:
                spilled = False  # a broken policy degrades to drop
        if spilled:
            self.prefix_spilled_total += 1
        else:
            self.prefix_dropped_total += 1

    def _take_block_locked(self) -> Optional[int]:
        """One free block, reclaiming the LRU cached block if needed."""
        if self._free:
            return self._free.popleft()
        if self._lru:
            blk, digest = self._lru.popitem(last=False)
            self._evict_indexed_locked(blk, digest)
            return blk
        return None

    def _release_block_locked(self, blk: int) -> None:
        """Drop one reference; park the block on the LRU (still cached)
        or the free list once nobody references it."""
        n = self._ref.get(blk, 1) - 1
        if n > 0:
            self._ref[blk] = n
            return
        self._ref.pop(blk, None)
        digest = self._block_hash.get(blk)
        if digest is not None:
            self._lru[blk] = digest
            self._lru.move_to_end(blk)
        else:
            self._free.append(blk)

    def can_grow_to(self, request_id: str, num_tokens: int) -> bool:
        """Whether the pool can extend ``request_id`` to cover
        ``num_tokens`` total positions (no allocation happens)."""
        need = self.blocks_for_tokens(num_tokens)
        with self._lock:
            have = len(self._owned.get(request_id, ()))
            return need - have <= len(self._free) + len(self._lru)

    def grow_to(self, request_id: str, num_tokens: int) -> bool:
        """Extend the request's block list to cover ``num_tokens`` total
        positions. All-or-nothing: returns False (nothing allocated) when
        the free pool can't cover the extension."""
        need = self.blocks_for_tokens(num_tokens)
        with self._lock:
            blocks = self._owned.setdefault(request_id, [])
            missing = need - len(blocks)
            if missing <= 0:
                return True
            if missing > len(self._free) + len(self._lru):
                if not blocks:
                    self._owned.pop(request_id, None)
                return False
            for _ in range(missing):
                blk = self._take_block_locked()
                blocks.append(blk)
                self._ref[blk] = 1
            self.total_allocs += missing
            return True

    def trim_to(self, request_id: str, num_tokens: int) -> int:
        """Shrink the request's block list back to exactly cover
        ``num_tokens`` total positions — the speculative-decode rollback:
        blocks grown for rejected draft positions are handed back before
        any other request could observe them. Refcount-aware like
        :meth:`free` (a trimmed block some other holder still references
        just drops this request's pin), though in the speculative path
        trimmed tails are always freshly grown (ref==1, never indexed)
        so they go straight back to the free list. Returns the number of
        block references released."""
        keep = self.blocks_for_tokens(num_tokens)
        with self._lock:
            blocks = self._owned.get(request_id)
            if not blocks or len(blocks) <= keep:
                return 0
            released = 0
            while len(blocks) > keep:
                self._release_block_locked(blocks.pop())
                released += 1
            self.total_frees += released
            return released

    def free(self, request_id: str) -> int:
        """Release every block the request holds (refcount-aware: shared
        blocks survive for their other holders). Returns the number of
        block references released."""
        with self._lock:
            blocks = self._owned.pop(request_id, [])
            for blk in blocks:
                self._release_block_locked(blk)
            # a pending COW that never executed releases its source pin
            for blk in self._cow_src.pop(request_id, ()):
                self._release_block_locked(blk)
            self.total_frees += len(blocks)
            return len(blocks)

    def evict(self, request_id: str) -> int:
        """Free-with-attitude: same as :meth:`free` but counted as a
        preemption eviction (the scheduler took the blocks away; the
        request re-prefills on readmission)."""
        n = self.free(request_id)
        if n:
            self.total_evictions += 1
        return n

    # -- prefix cache -----------------------------------------------------
    def acquire_prefix(
        self, request_id: str, tokens
    ) -> Tuple[int, List[Tuple[int, int]]]:
        """Attach cached blocks covering the longest indexed prefix of
        ``tokens`` to ``request_id``; returns ``(cached_tokens,
        cow_pairs)``. The request's prefill then starts at
        ``cached_tokens`` instead of 0.

        A FULL-prompt hit keeps the final block shared but pairs it with
        a freshly allocated private copy target: ``cow_pairs`` =
        ``[(src, dst)]`` for the engine to execute device-side before
        the 1-token tail prefill writes position ``len(tokens)-1`` into
        ``dst``. The source stays refcount-pinned until
        :meth:`cow_copied` (or :meth:`free`) — without the pin, another
        admission in the same scheduling pass could reclaim it before
        the copy ran. Does NOT bump the hit counters — the scheduler
        commits them via :meth:`note_prefix_hit` only once admission
        (block growth for the tail) actually succeeds, so a stuck queue
        head retrying every tick doesn't inflate the stats.
        """
        if not self.prefix_cache_enabled:
            return 0, []
        with self._lock:
            if self._owned.get(request_id):
                return 0, []  # mid-flight request: table already live
            hits: List[int] = []
            prev = b""
            bs = self.block_size
            for end in range(bs, len(tokens) + 1, bs):
                prev = _chain_digest(prev, tokens[end - bs : end])
                blk = self._index.get(prev)
                if blk is None:
                    break
                # refresh use-recency so hot prefixes stay in the
                # truncated gossip digest window
                self._index.move_to_end(prev)
                # popularity signal for the eviction-time spill-vs-drop
                # decision ("spill popular, drop cold")
                self._hits[prev] = self._hits.get(prev, 0) + 1
                hits.append(blk)
            if not hits:
                return 0, []
            # pin every hit FIRST: an unreferenced hit sits on the LRU
            # and a subsequent allocation in this same pass could
            # otherwise reclaim it out from under us
            for blk in hits:
                self._ref[blk] = self._ref.get(blk, 0) + 1
                self._lru.pop(blk, None)
            cow: List[Tuple[int, int]] = []
            cached_tokens = len(hits) * bs
            if cached_tokens >= len(tokens):
                # full-prompt hit: the first sampled token needs the last
                # prompt token's logits, so ONE token must still prefill
                # — and its K/V write lands inside the final (shared)
                # block. COW that block to a private copy; the tail
                # prefill recomputes position len-1 into the copy.
                dst = self._take_block_locked()
                if dst is None:
                    # pool dry: fall back to recomputing the last block
                    self._release_block_locked(hits.pop())
                    cached_tokens -= bs
                else:
                    src = hits[-1]
                    hits[-1] = dst
                    self._ref[dst] = 1
                    # src keeps the pin taken above, now owned by the
                    # pending-copy record instead of the block table
                    self._cow_src.setdefault(request_id, []).append(src)
                    cow.append((src, dst))
                    self.cow_copies_total += 1
                    self.total_allocs += 1
                    cached_tokens = len(tokens) - 1
            if not hits:
                return 0, []
            self._owned[request_id] = hits
            return cached_tokens, cow

    def note_prefix_hit(self, cached_tokens: int) -> None:
        """Commit hit accounting once the request actually ADMITTED —
        one query per admission, not per acquire attempt (a queue head
        stuck behind block pressure re-acquires every scheduler tick and
        would otherwise drown the hit rate in retry noise). No-op with
        the cache disabled: queries_total must read as "admissions with
        the cache ON", not tick up under a 0.0 hit rate."""
        if not self.prefix_cache_enabled:
            return
        with self._lock:
            self.prefix_queries_total += 1
            if cached_tokens <= 0:
                return
            self.prefix_hits_total += 1
            self.prefix_tokens_saved_total += cached_tokens

    def cow_copied(self, request_id: str) -> None:
        """The engine executed the pending device copies: release the
        source pins (the private copies live in the block table now)."""
        with self._lock:
            for blk in self._cow_src.pop(request_id, ()):
                self._release_block_locked(blk)

    def register_prefix(self, request_id: str, tokens) -> int:
        """Index the request's fully-written blocks: ``tokens`` must be
        the positions whose K/V are actually in the cache (the prompt at
        prefill completion; prompt+generated-minus-one at finish — the
        final sampled token's K/V is never written). Full blocks are
        immutable from here on, so indexing them is safe for any future
        reader. Returns how many new blocks were indexed."""
        if not self.prefix_cache_enabled:
            return 0
        with self._lock:
            blocks = self._owned.get(request_id)
            if not blocks:
                return 0
            bs = self.block_size
            n_full = min(len(tokens) // bs, len(blocks))
            added = 0
            prev = b""
            for i in range(n_full):
                parent = prev
                prev = _chain_digest(prev, tokens[i * bs : (i + 1) * bs])
                blk = blocks[i]
                if blk in self._block_hash:
                    continue  # already indexed (e.g. acquired via a hit)
                if prev in self._index:
                    continue  # another block already serves this prefix
                if self.prefix_cache_max_blocks > 0 and (
                    len(self._index) >= self.prefix_cache_max_blocks
                ):
                    if not self._lru:
                        break  # cap reached, nothing evictable
                    old_blk, old_digest = self._lru.popitem(last=False)
                    self._evict_indexed_locked(old_blk, old_digest)
                    self._free.append(old_blk)
                self._block_hash[blk] = prev
                self._index[prev] = blk
                self._parent[prev] = parent
                added += 1
            return added

    def prefix_digest(self, max_entries: int = 256) -> List[int]:
        """Compact cache summary for router gossip: a bounded
        RADIX-PATH export instead of the old flat recent-N slice.

        The router's affinity scorer matches consecutively from block 0
        and stops at the first miss, so an exported digest is only
        usable when its entire ancestor chain is exported with it. The
        flat MRU slice broke exactly that once the index outgrew the
        budget: it kept the N most-recently-used blocks as arbitrary
        points, truncating the ancestors a deep hot path needs. Here we
        walk the index MRU-first and export whole root-anchored SPINES
        (each digest plus every ancestor still indexed), skipping spines
        that don't fit the remaining budget or whose chain is broken by
        eviction (their descendants can never match anyway) — so with
        >10k indexed blocks the gossip covers the hottest complete
        paths, not a useless frontier of orphans.

        Truncation contract (unchanged): entries are the first 8 bytes
        of the 16-byte chain digest as signed 64-bit ints. A router-side
        collision is a FALSE POSITIVE ONLY — it routes a request to a
        replica that turns out cold, costing one suboptimal placement;
        correctness never depends on this digest (the engine re-derives
        full 16-byte digests at admission)."""
        out: List[bytes] = []
        with self._lock:
            seen = set()
            for digest in reversed(self._index):
                if len(out) >= max_entries:
                    break
                if digest in seen:
                    continue  # already exported as an ancestor
                spine: List[bytes] = []
                d = digest
                complete = True
                while d:
                    if d in seen:
                        break  # ancestors already in the export
                    if d not in self._index:
                        complete = False  # evicted mid-chain: orphan path
                        break
                    spine.append(d)
                    d = self._parent.get(d, b"")
                if not complete or len(out) + len(spine) > max_entries:
                    continue
                seen.update(spine)
                out.extend(spine)
        return [struct.unpack("<q", d[:8])[0] for d in out]

    # -- KV-cache migration (disaggregated serving) -----------------------
    def reserve_import(self, num_blocks: int) -> Optional[List[int]]:
        """Allocate blocks for migrated KV content, each pinned (ref=1)
        until :meth:`commit_import` or :meth:`abort_import` — the device
        scatter runs between reserve and commit, and an unpinned block
        could be reclaimed out from under it. Returns None (nothing
        taken) when the pool can't cover the import — the caller falls
        back to a plain prefill instead of wedging admission."""
        with self._lock:
            if num_blocks <= 0:
                return []
            if num_blocks > len(self._free) + len(self._lru):
                return None
            out: List[int] = []
            for _ in range(num_blocks):
                blk = self._take_block_locked()
                self._ref[blk] = 1
                out.append(blk)
            self.total_allocs += num_blocks
            return out

    def commit_import(self, blocks: List[int], tokens) -> int:
        """Index scattered import blocks in the radix structure so later
        admissions (the migrated request first of all) acquire them as
        prefix hits. Block i must hold the K/V of
        ``tokens[i*bs:(i+1)*bs]`` — the chain digest is recomputed here
        from the tokens, never trusted from the wire. Blocks whose
        prefix another local block already serves are redundant copies:
        released straight back to the free list. Every committed block
        drops its import pin and parks cached-unreferenced (LRU), i.e.
        imported KV costs nothing until someone uses or evicts it.
        Returns the number of blocks actually indexed."""
        bs = self.block_size
        n = min(len(blocks), len(tokens) // bs)
        added = 0
        with self._lock:
            prev = b""
            for i in range(n):
                parent = prev
                prev = _chain_digest(prev, tokens[i * bs : (i + 1) * bs])
                blk = blocks[i]
                if prev in self._index or blk in self._block_hash:
                    # an equivalent block is already indexed locally:
                    # drop the imported copy (no digest -> free list)
                    self._release_block_locked(blk)
                    continue
                self._block_hash[blk] = prev
                self._index[prev] = blk
                self._parent[prev] = parent
                # pin released WITH the digest set: lands on the LRU as
                # a cached-unreferenced block
                self._release_block_locked(blk)
                added += 1
            # surplus reserve (shouldn't happen: caller sizes exactly)
            for blk in blocks[n:]:
                self._release_block_locked(blk)
        return added

    def abort_import(self, blocks: List[int]) -> None:
        """Scatter failed: return reserved (never-indexed) blocks."""
        with self._lock:
            for blk in blocks:
                self._release_block_locked(blk)

    def prefix_stats(self) -> Dict[str, float]:
        with self._lock:
            indexed = len(self._index)
            cached_free = len(self._lru)
            queries = self.prefix_queries_total
            hits = self.prefix_hits_total
        return {
            "enabled": self.prefix_cache_enabled,
            "indexed_blocks": indexed,
            "cached_unreferenced_blocks": cached_free,
            # queries = ADMISSIONS with the cache enabled (see
            # note_prefix_hit), so hit_rate reads as "fraction of
            # admitted requests that reused cached blocks"
            "queries_total": queries,
            "hits_total": hits,
            "hit_rate": hits / queries if queries else 0.0,
            "tokens_saved_total": self.prefix_tokens_saved_total,
            "cow_copies_total": self.cow_copies_total,
            "evictions_total": self.prefix_evictions_total,
            # spill-vs-drop books: evictions == spilled + dropped, always
            "spilled_total": self.prefix_spilled_total,
            "dropped_total": self.prefix_dropped_total,
        }

    # -- introspection ----------------------------------------------------
    def refcount(self, block_id: int) -> int:
        with self._lock:
            return self._ref.get(block_id, 0)

    def table_row(self, request_id: str, max_blocks: int) -> List[int]:
        """The request's block-table row, right-padded with the null
        block to the fixed ``max_blocks`` width the jitted steps expect."""
        blocks = self.owned(request_id)
        if len(blocks) > max_blocks:
            raise ValueError(
                f"request {request_id!r} holds {len(blocks)} blocks > "
                f"max_blocks_per_seq {max_blocks}"
            )
        return blocks + [0] * (max_blocks - len(blocks))

    def stats(self) -> Dict[str, float]:
        with self._lock:
            free = len(self._free) + len(self._lru)
            cached = len(self._lru)
            holders = len(self._owned)
        used = self.usable_blocks - free
        return {
            "num_blocks": self.num_blocks,
            "block_size": self.block_size,
            "used_blocks": used,
            "free_blocks": free,
            "prefix_cached_blocks": cached,
            "holders": holders,
            "utilization": used / max(1, self.usable_blocks),
            "total_allocs": self.total_allocs,
            "total_frees": self.total_frees,
            "total_evictions": self.total_evictions,
        }
