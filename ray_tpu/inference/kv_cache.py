"""Paged KV cache management: the host-side block allocator.

vLLM-style paging (PAPERS.md: TPU serving stacks win by packing many
requests into one fixed-shape KV cache): the device holds
``[n_layers, num_blocks, block_size, n_kv_heads, head_dim]`` K and V
tensors (``models.llama.init_paged_kv_cache``); this module owns the
*accounting* — which request holds which block ids, what is free, and
when a new request must wait in the admission queue instead.

Block id 0 is reserved as the NULL block: padding positions in the
fixed-shape prefill/decode steps write their trash there, so it is never
handed to a request. Block ids are layer-agnostic — one id covers
``block_size`` token positions in every layer at once, so the allocator
deals in tokens, not layer-tokens.

Pure host-side python with no jax dependency: unit-testable without an
accelerator, and cheap enough to run under the engine lock.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, List, Optional


class PagedBlockManager:
    """Allocation / free / eviction accounting for the shared block pool."""

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError("need >= 2 blocks (block 0 is the null block)")
        if block_size < 1:
            raise ValueError("block_size must be >= 1")
        self.num_blocks = num_blocks
        self.block_size = block_size
        # block 0 = null: never allocated
        self._free: deque = deque(range(1, num_blocks))
        self._owned: Dict[str, List[int]] = {}
        self._lock = threading.Lock()
        # lifetime accounting (engine /metrics + stats())
        self.total_allocs = 0
        self.total_frees = 0
        self.total_evictions = 0

    # -- capacity ---------------------------------------------------------
    @property
    def usable_blocks(self) -> int:
        return self.num_blocks - 1  # minus the null block

    @property
    def free_blocks(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def used_blocks(self) -> int:
        return self.usable_blocks - self.free_blocks

    def utilization(self) -> float:
        return self.used_blocks / max(1, self.usable_blocks)

    def blocks_for_tokens(self, num_tokens: int) -> int:
        return max(1, -(-num_tokens // self.block_size))  # ceil

    # -- allocation -------------------------------------------------------
    def owned(self, request_id: str) -> List[int]:
        with self._lock:
            return list(self._owned.get(request_id, ()))

    def can_grow_to(self, request_id: str, num_tokens: int) -> bool:
        """Whether the pool can extend ``request_id`` to cover
        ``num_tokens`` total positions (no allocation happens)."""
        need = self.blocks_for_tokens(num_tokens)
        with self._lock:
            have = len(self._owned.get(request_id, ()))
            return need - have <= len(self._free)

    def grow_to(self, request_id: str, num_tokens: int) -> bool:
        """Extend the request's block list to cover ``num_tokens`` total
        positions. All-or-nothing: returns False (nothing allocated) when
        the free pool can't cover the extension."""
        need = self.blocks_for_tokens(num_tokens)
        with self._lock:
            blocks = self._owned.setdefault(request_id, [])
            missing = need - len(blocks)
            if missing <= 0:
                return True
            if missing > len(self._free):
                if not blocks:
                    self._owned.pop(request_id, None)
                return False
            for _ in range(missing):
                blocks.append(self._free.popleft())
            self.total_allocs += missing
            return True

    def free(self, request_id: str) -> int:
        """Return every block the request holds to the pool."""
        with self._lock:
            blocks = self._owned.pop(request_id, [])
            self._free.extend(blocks)
            self.total_frees += len(blocks)
            return len(blocks)

    def evict(self, request_id: str) -> int:
        """Free-with-attitude: same as :meth:`free` but counted as a
        preemption eviction (the scheduler took the blocks away; the
        request re-prefills on readmission)."""
        n = self.free(request_id)
        if n:
            self.total_evictions += 1
        return n

    def table_row(self, request_id: str, max_blocks: int) -> List[int]:
        """The request's block-table row, right-padded with the null
        block to the fixed ``max_blocks`` width the jitted steps expect."""
        blocks = self.owned(request_id)
        if len(blocks) > max_blocks:
            raise ValueError(
                f"request {request_id!r} holds {len(blocks)} blocks > "
                f"max_blocks_per_seq {max_blocks}"
            )
        return blocks + [0] * (max_blocks - len(blocks))

    def stats(self) -> Dict[str, float]:
        with self._lock:
            free = len(self._free)
            holders = len(self._owned)
        used = self.usable_blocks - free
        return {
            "num_blocks": self.num_blocks,
            "block_size": self.block_size,
            "used_blocks": used,
            "free_blocks": free,
            "holders": holders,
            "utilization": used / max(1, self.usable_blocks),
            "total_allocs": self.total_allocs,
            "total_frees": self.total_frees,
            "total_evictions": self.total_evictions,
        }
