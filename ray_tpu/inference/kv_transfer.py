"""KV-cache migration transport: replica→replica block shipping.

The handoff unit of disaggregated prefill/decode serving
(``serve_llm.llm_deployment(disaggregated=True)``): a prefill replica
runs ``engine.prefill_kv`` (export-after-prefill), this module PUBLISHES
the gathered block payload into the local daemon's shm store, and the
decode replica FETCHES it by asking *its* daemon to ``pull_object`` from
the source daemon — so migrated bytes ride the existing zero-copy data
plane end to end: RAW chunk frames received straight into the
destination segment, per-chunk CRC verification, whole-object digest
before seal (PR 8 + PR 11), resumable multi-source failover, admission
control. Nothing here re-implements transfer; the "object" is simply a
set of paged KV blocks, exactly the Ray-paper move of coordinating
specialized actors through the object plane.

Integrity ladder (digest-before-attach): the pull path verifies each
chunk CRC and the source-advertised whole-object digest before the
segment seals; :func:`fetch` additionally compares the store digest
against the CRC the *exporter* stamped into the descriptor — which also
covers the same-node short-circuit where no transfer ran at all. Only
then does the importing engine scatter the blocks into its cache.

Descriptors are small picklable dicts (they travel router→replica in
request payloads). When the process has no node daemon (local mode,
unit tests), the payload is carried INLINE in the descriptor up to
``kv_inline_max_bytes`` — same CRC gate, no data plane.

Lifetime: published segments are owned by the source daemon's store and
reaped after ``kv_export_ttl_s`` (the importer usually deletes its own
received copy immediately, recycling the segment into the daemon's
receive pool — ``ShmStore`` satellite). jax-free by design: routers and
ingress processes import this for the fallback/handoff metrics.
"""

from __future__ import annotations

import hashlib
import logging
import threading
import time
import uuid
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.core.config import GLOBAL_CONFIG

logger = logging.getLogger(__name__)


class KvTransferError(RuntimeError):
    """A migration step failed (publish, pull, digest, import). Always
    recoverable: the caller degrades to plain single-replica generation
    under the existing resume machinery."""


# -- metrics (registered once per process) ----------------------------------

_METRICS = None
_METRICS_LOCK = threading.Lock()


def migration_metrics():
    """``raytpu_kv_migration_*`` counters + the disagg handoff timer
    (README Observability catalog)."""
    global _METRICS
    if _METRICS is None:
        from ray_tpu.observability.metrics import Counter, Histogram

        with _METRICS_LOCK:
            if _METRICS is None:
                _METRICS = {
                    "transfers": Counter(
                        "raytpu_kv_migration_transfers_total",
                        "KV block payloads successfully migrated "
                        "replica-to-replica",
                    ),
                    "bytes": Counter(
                        "raytpu_kv_migration_bytes_total",
                        "KV bytes carried by successful migrations",
                    ),
                    "failures": Counter(
                        "raytpu_kv_migration_failures_total",
                        "migration steps that failed, by stage",
                        ("stage",),
                    ),
                    "fallbacks": Counter(
                        "raytpu_kv_migration_fallbacks_total",
                        "requests degraded to plain generation, by reason",
                        ("reason",),
                    ),
                    "handoff": Histogram(
                        "raytpu_disagg_handoff_seconds",
                        "prefill-dispatch to KV-descriptor latency "
                        "(disaggregated serving handoff)",
                        buckets=(
                            0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                            0.5, 1.0, 2.5, 5.0, 10.0,
                        ),
                    ),
                }
    return _METRICS


def count_failure(stage: str) -> None:
    migration_metrics()["failures"].inc(labels={"stage": stage})


def count_fallback(reason: str) -> None:
    migration_metrics()["fallbacks"].inc(labels={"reason": reason})


# -- plumbing ---------------------------------------------------------------


def _backend():
    """The process's cluster backend, or None when there is no node
    daemon to publish through (local mode → inline descriptors)."""
    try:
        from ray_tpu.core.api import _global_worker

        w = _global_worker()
        if w is None:
            return None
        be = w.backend
        if getattr(be, "daemon", None) is None or getattr(be, "io", None) is None:
            return None
        return be
    except Exception:  # noqa: BLE001 — absence of a backend is normal
        return None


def _kv_object_id(transfer_id: str):
    """Deterministic ObjectID for a transfer (the segment NAME is the
    capability, exactly like worker-created puts)."""
    from ray_tpu.core.ids import ObjectID

    return ObjectID(
        hashlib.blake2b(
            b"kvx:" + transfer_id.encode(), digest_size=ObjectID.SIZE
        ).digest()
    )


#: published-but-unreleased exports: transfer_id -> (ObjectID, expiry)
_EXPORTS: Dict[str, Tuple[Any, float]] = {}
_EXPORTS_LOCK = threading.Lock()


def _reap_exports(be) -> None:
    now = time.monotonic()
    with _EXPORTS_LOCK:
        dead = [t for t, (_o, exp) in _EXPORTS.items() if now > exp]
        victims = [_EXPORTS.pop(t)[0] for t in dead]
    for oid in victims:
        try:
            be.io.run(
                be.daemon.call("delete_object", {"object_id": oid.binary()}),
                timeout=10,
            )
        except Exception:  # noqa: BLE001 — best-effort reap
            pass


def release_export(transfer_id: str) -> None:
    """Explicitly drop a published export (the TTL reap is the backstop
    for descriptors that never got consumed)."""
    with _EXPORTS_LOCK:
        ent = _EXPORTS.pop(transfer_id, None)
    if ent is None:
        return
    be = _backend()
    if be is None:
        return
    try:
        be.io.run(
            be.daemon.call("delete_object", {"object_id": ent[0].binary()}),
            timeout=10,
        )
    except Exception:  # noqa: BLE001
        pass


# -- publish (prefill side) -------------------------------------------------


def publish(payload: Dict[str, Any], *, transfer_id: Optional[str] = None) -> Dict[str, Any]:
    """Turn an ``engine.prefill_kv`` payload into a migration
    descriptor. The KV array is written ONCE into a fresh shm segment
    named after the transfer's ObjectID, which the local daemon adopts —
    from then on it is a normal store object any daemon can pull with
    full integrity/resume semantics. Raises :class:`KvTransferError`
    when nothing can carry the payload (no daemon AND too big to
    inline)."""
    kv = np.ascontiguousarray(payload["kv"])
    tokens: List[int] = list(payload["tokens"])
    tid = transfer_id or uuid.uuid4().hex[:16]
    desc: Dict[str, Any] = {
        "transfer_id": tid,
        "tokens": len(tokens),
        "block_size": int(payload["block_size"]),
        "shape": tuple(int(d) for d in kv.shape),
        "dtype": str(kv.dtype),
        "size": int(kv.nbytes),
        "inline": None,
        "object_id": None,
        "source": None,
    }
    be = _backend()
    if be is None:
        cap = GLOBAL_CONFIG.kv_inline_max_bytes
        if kv.nbytes > cap:
            count_failure("export")
            raise KvTransferError(
                f"no node daemon and payload of {kv.nbytes} bytes exceeds "
                f"kv_inline_max_bytes={cap}"
            )
        raw = kv.tobytes()
        desc["inline"] = raw
        desc["crc32"] = zlib.crc32(raw)
        return desc
    _reap_exports(be)
    from ray_tpu.core.object_store import _attach, _create, segment_name

    oid = _kv_object_id(tid)
    name = segment_name(oid)
    try:
        try:
            seg = _create(name, kv.nbytes)
        except FileExistsError:
            # transfer-id collision can't happen (uuid); a stale segment
            # from a crashed exporter can — overwrite in place
            seg = _attach(name)
        try:
            view = np.frombuffer(memoryview(seg.buf)[: kv.nbytes], dtype=kv.dtype)
            view[:] = kv.reshape(-1)
            desc["crc32"] = zlib.crc32(memoryview(seg.buf)[: kv.nbytes])
            del view
        finally:
            seg.close()
        be.io.run(
            be.daemon.call(
                "adopt_object", {"object_id": oid.binary(), "size": kv.nbytes}
            ),
            timeout=30,
        )
    except Exception as e:  # noqa: BLE001 — publish failure → fallback
        count_failure("export")
        raise KvTransferError(f"kv publish failed: {e!r}") from e
    desc["object_id"] = oid.hex()
    desc["source"] = tuple(be.daemon_addr)
    with _EXPORTS_LOCK:
        _EXPORTS[tid] = (
            oid, time.monotonic() + GLOBAL_CONFIG.kv_export_ttl_s,
        )
    return desc


# -- fetch (decode side) ----------------------------------------------------


class FetchedPayload:
    """A migrated KV array plus the cleanup that returns its segment.
    ``close()`` is safe to call with the array still referenced (the
    mapping outlives live views; the daemon-side delete recycles the
    inode into the receive-segment pool either way)."""

    def __init__(self, array: np.ndarray, close: Callable[[], None]):
        self.array = array
        self._close = close

    def close(self) -> None:
        try:
            self._close()
        except Exception:  # noqa: BLE001 — cleanup must never raise
            pass


def fetch(desc: Dict[str, Any], *, timeout_s: float = 30.0) -> FetchedPayload:
    """Materialize a descriptor's KV payload locally. Remote descriptors
    ride ``pull_object`` on the local daemon (RAW receive-into-segment,
    per-chunk CRC, digest-verified seal, multi-source resume); the
    store digest is then compared against the exporter-stamped CRC
    before the array is handed to the importing engine — the
    digest-before-attach gate, which also covers the same-node
    short-circuit where no transfer ran."""
    shape = tuple(desc["shape"])
    dtype = np.dtype(desc["dtype"])
    inline = desc.get("inline")
    if inline is not None:
        if zlib.crc32(inline) != desc["crc32"]:
            count_failure("digest")
            raise KvTransferError("inline kv payload failed its crc gate")
        arr = np.frombuffer(inline, dtype=dtype).reshape(shape)
        migration_metrics()["transfers"].inc()
        migration_metrics()["bytes"].inc(len(inline))
        return FetchedPayload(arr, lambda: None)
    be = _backend()
    if be is None:
        count_failure("transfer")
        raise KvTransferError("no node daemon to pull the kv payload through")
    from ray_tpu.core.ids import ObjectID
    from ray_tpu.core.object_store import _attach

    oid = ObjectID(bytes.fromhex(desc["object_id"]))
    try:
        reply = be.io.run(
            be.daemon.call(
                "pull_object",
                {
                    "object_id": oid.binary(),
                    "sources": [tuple(desc["source"])],
                    "deadline_s": timeout_s,
                },
            ),
            timeout=timeout_s + 15,
        )
    except Exception as e:  # noqa: BLE001
        count_failure("transfer")
        raise KvTransferError(f"kv pull failed: {e!r}") from e
    if not (isinstance(reply, dict) and reply.get("segment")):
        count_failure("transfer")
        raise KvTransferError(f"kv pull failed: {reply!r}")
    try:
        info = be.io.run(
            be.daemon.call("object_info", {"object_id": oid.binary()}),
            timeout=30,
        )
    except Exception as e:  # noqa: BLE001
        count_failure("digest")
        raise KvTransferError(f"kv digest probe failed: {e!r}") from e
    digest = (info or {}).get("digest")
    if digest != desc["crc32"]:
        count_failure("digest")
        raise KvTransferError(
            f"kv payload digest mismatch: store={digest} descriptor="
            f"{desc['crc32']} — refusing to attach"
        )
    try:
        seg = _attach(reply["segment"])
    except Exception as e:  # noqa: BLE001
        count_failure("transfer")
        raise KvTransferError(f"kv segment attach failed: {e!r}") from e
    view = memoryview(seg.buf)[: desc["size"]]
    arr = np.frombuffer(view, dtype=dtype).reshape(shape)

    def _close():
        try:
            view.release()
        except BufferError:
            pass  # live numpy views keep the mapping valid
        try:
            seg.close()
        except Exception:  # noqa: BLE001
            pass
        # the received copy is private to this transfer: delete it and
        # hand the inode to the daemon's receive-segment reuse pool so
        # the NEXT migration skips segment create/zero entirely
        try:
            be.io.run(
                be.daemon.call(
                    "delete_object",
                    {"object_id": oid.binary(), "recycle_receive": True},
                ),
                timeout=10,
            )
        except Exception:  # noqa: BLE001
            pass
        # and release the SOURCE's export promptly — a consumed payload
        # parked until the TTL reap would occupy the prefill replica's
        # store for kv_export_ttl_s per migration, forcing spills of
        # LIVE objects under sustained traffic. Best-effort: the TTL
        # reap remains the backstop. (Same-node: the local delete above
        # already dropped the shared entry; this is then a no-op.)
        src = tuple(desc["source"])
        if src != tuple(be.daemon_addr):
            try:
                be.io.run(
                    be._client(src[0], src[1], role="noded").call(  # noqa: SLF001
                        "delete_object", {"object_id": oid.binary()}
                    ),
                    timeout=10,
                )
            except Exception:  # noqa: BLE001
                pass

    migration_metrics()["transfers"].inc()
    migration_metrics()["bytes"].inc(desc["size"])
    return FetchedPayload(arr, _close)
