"""KV-cache migration transport: replica→replica block shipping.

The handoff unit of disaggregated prefill/decode serving
(``serve_llm.llm_deployment(disaggregated=True)``): a prefill replica
runs ``engine.prefill_kv`` (export-after-prefill), this module PUBLISHES
the gathered block payload into the local daemon's shm store, and the
decode replica FETCHES it by asking *its* daemon to ``pull_object`` from
the source daemon — so migrated bytes ride the existing zero-copy data
plane end to end: RAW chunk frames received straight into the
destination segment, per-chunk CRC verification, whole-object digest
before seal (PR 8 + PR 11), resumable multi-source failover, admission
control. Nothing here re-implements transfer; the "object" is simply a
set of paged KV blocks, exactly the Ray-paper move of coordinating
specialized actors through the object plane.

Integrity ladder (digest-before-attach): the pull path verifies each
chunk CRC and the source-advertised whole-object digest before the
segment seals; :func:`fetch` additionally compares the store digest
against the CRC the *exporter* stamped into the descriptor — which also
covers the same-node short-circuit where no transfer ran at all. Only
then does the importing engine scatter the blocks into its cache.

Descriptors are small picklable dicts (they travel router→replica in
request payloads). When the process has no node daemon (local mode,
unit tests), the payload is carried INLINE in the descriptor up to
``kv_inline_max_bytes`` — same CRC gate, no data plane.

Lifetime: published segments are owned by the source daemon's store and
reaped after ``kv_export_ttl_s`` (the importer usually deletes its own
received copy immediately, recycling the segment into the daemon's
receive pool — ``ShmStore`` satellite). jax-free by design: routers and
ingress processes import this for the fallback/handoff metrics.
"""

from __future__ import annotations

import hashlib
import logging
import threading
import time
import uuid
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.core.config import GLOBAL_CONFIG

logger = logging.getLogger(__name__)


class KvTransferError(RuntimeError):
    """A migration step failed (publish, pull, digest, import). Always
    recoverable: the caller degrades to plain single-replica generation
    under the existing resume machinery."""


#: message marker carried by the engine's ``KvMigrationHandoff`` failure
#: (live decode migration: a draining replica flushed the request's full
#: KV into the tier and failed the stream resumably). Lives here — not
#: in engine.py — so jax-free router processes can match it.
KV_MIGRATION_MARKER = "kv-tier migration handoff"


# -- metrics (registered once per process) ----------------------------------

_METRICS = None
_METRICS_LOCK = threading.Lock()


def migration_metrics():
    """``raytpu_kv_migration_*`` counters + the disagg handoff timer
    (README Observability catalog)."""
    global _METRICS
    if _METRICS is None:
        from ray_tpu.observability.metrics import Counter, Histogram

        with _METRICS_LOCK:
            if _METRICS is None:
                _METRICS = {
                    "transfers": Counter(
                        "raytpu_kv_migration_transfers_total",
                        "KV block payloads successfully migrated "
                        "replica-to-replica",
                    ),
                    "bytes": Counter(
                        "raytpu_kv_migration_bytes_total",
                        "KV bytes carried by successful migrations",
                    ),
                    "failures": Counter(
                        "raytpu_kv_migration_failures_total",
                        "migration steps that failed, by stage",
                        ("stage",),
                    ),
                    "fallbacks": Counter(
                        "raytpu_kv_migration_fallbacks_total",
                        "requests degraded to plain generation, by reason",
                        ("reason",),
                    ),
                    "handoff": Histogram(
                        "raytpu_disagg_handoff_seconds",
                        "prefill-dispatch to KV-descriptor latency "
                        "(disaggregated serving handoff)",
                        buckets=(
                            0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                            0.5, 1.0, 2.5, 5.0, 10.0,
                        ),
                    ),
                }
    return _METRICS


def count_failure(stage: str) -> None:
    migration_metrics()["failures"].inc(labels={"stage": stage})


def count_fallback(reason: str) -> None:
    migration_metrics()["fallbacks"].inc(labels={"reason": reason})


# -- plumbing ---------------------------------------------------------------


def _backend():
    """The process's cluster backend, or None when there is no node
    daemon to publish through (local mode → inline descriptors)."""
    try:
        from ray_tpu.core.api import _global_worker

        w = _global_worker()
        if w is None:
            return None
        be = w.backend
        if getattr(be, "daemon", None) is None or getattr(be, "io", None) is None:
            return None
        return be
    except Exception:  # noqa: BLE001 — absence of a backend is normal
        return None


def _kv_object_id(transfer_id: str):
    """Deterministic ObjectID for a transfer (the segment NAME is the
    capability, exactly like worker-created puts)."""
    from ray_tpu.core.ids import ObjectID

    return ObjectID(
        hashlib.blake2b(
            b"kvx:" + transfer_id.encode(), digest_size=ObjectID.SIZE
        ).digest()
    )


#: published-but-unreleased exports: transfer_id -> (ObjectID, expiry)
_EXPORTS: Dict[str, Tuple[Any, float]] = {}
_EXPORTS_LOCK = threading.Lock()


def _reap_exports(be) -> None:
    now = time.monotonic()
    with _EXPORTS_LOCK:
        dead = [t for t, (_o, exp) in _EXPORTS.items() if now > exp]
        victims = [_EXPORTS.pop(t)[0] for t in dead]
    for oid in victims:
        try:
            be.io.run(
                be.daemon.call("delete_object", {"object_id": oid.binary()}),
                timeout=10,
            )
        except Exception:  # noqa: BLE001 — best-effort reap
            pass


def release_export(transfer_id: str) -> None:
    """Explicitly drop a published export (the TTL reap is the backstop
    for descriptors that never got consumed)."""
    with _EXPORTS_LOCK:
        ent = _EXPORTS.pop(transfer_id, None)
    if ent is None:
        return
    be = _backend()
    if be is None:
        return
    try:
        be.io.run(
            be.daemon.call("delete_object", {"object_id": ent[0].binary()}),
            timeout=10,
        )
    except Exception:  # noqa: BLE001
        pass


# -- publish (prefill side) -------------------------------------------------


def publish(payload: Dict[str, Any], *, transfer_id: Optional[str] = None) -> Dict[str, Any]:
    """Turn an ``engine.prefill_kv`` payload into a migration
    descriptor. The KV array is written ONCE into a fresh shm segment
    named after the transfer's ObjectID, which the local daemon adopts —
    from then on it is a normal store object any daemon can pull with
    full integrity/resume semantics. Raises :class:`KvTransferError`
    when nothing can carry the payload (no daemon AND too big to
    inline)."""
    kv = np.ascontiguousarray(payload["kv"])
    tokens: List[int] = list(payload["tokens"])
    tid = transfer_id or uuid.uuid4().hex[:16]
    desc: Dict[str, Any] = {
        "transfer_id": tid,
        "tokens": len(tokens),
        "block_size": int(payload["block_size"]),
        "shape": tuple(int(d) for d in kv.shape),
        "dtype": str(kv.dtype),
        "size": int(kv.nbytes),
        "inline": None,
        "object_id": None,
        "source": None,
    }
    be = _backend()
    if be is None:
        cap = GLOBAL_CONFIG.kv_inline_max_bytes
        if kv.nbytes > cap:
            count_failure("export")
            raise KvTransferError(
                f"no node daemon and payload of {kv.nbytes} bytes exceeds "
                f"kv_inline_max_bytes={cap}"
            )
        raw = kv.tobytes()
        desc["inline"] = raw
        desc["crc32"] = zlib.crc32(raw)
        return desc
    _reap_exports(be)
    from ray_tpu.core.object_store import _attach, _create, segment_name

    oid = _kv_object_id(tid)
    name = segment_name(oid)
    try:
        try:
            seg = _create(name, kv.nbytes)
        except FileExistsError:
            # transfer-id collision can't happen (uuid) but tier ids are
            # DETERMINISTIC ("tier:<digest>") and a stale segment from a
            # crashed exporter can linger — overwrite in place either
            # way (idempotent republish: same digest → same bytes)
            seg = _attach(name)
        try:
            view = np.frombuffer(memoryview(seg.buf)[: kv.nbytes], dtype=kv.dtype)
            view[:] = kv.reshape(-1)
            desc["crc32"] = zlib.crc32(memoryview(seg.buf)[: kv.nbytes])
            del view
        finally:
            seg.close()
        be.io.run(
            be.daemon.call(
                "adopt_object", {"object_id": oid.binary(), "size": kv.nbytes}
            ),
            timeout=30,
        )
    except Exception as e:  # noqa: BLE001 — publish failure → fallback
        count_failure("export")
        raise KvTransferError(f"kv publish failed: {e!r}") from e
    desc["object_id"] = oid.hex()
    desc["source"] = tuple(be.daemon_addr)
    with _EXPORTS_LOCK:
        _EXPORTS[tid] = (
            oid, time.monotonic() + GLOBAL_CONFIG.kv_export_ttl_s,
        )
    return desc


# -- fetch (decode side) ----------------------------------------------------


class FetchedPayload:
    """A migrated KV array plus the cleanup that returns its segment.
    ``close()`` is safe to call with the array still referenced (the
    mapping outlives live views; the daemon-side delete recycles the
    inode into the receive-segment pool either way)."""

    def __init__(self, array: np.ndarray, close: Callable[[], None]):
        self.array = array
        self._close = close

    def close(self) -> None:
        try:
            self._close()
        except Exception:  # noqa: BLE001 — cleanup must never raise
            pass


def fetch(
    desc: Dict[str, Any], *, timeout_s: float = 30.0, keep_source: bool = False
) -> FetchedPayload:
    """Materialize a descriptor's KV payload locally. Remote descriptors
    ride ``pull_object`` on the local daemon (RAW receive-into-segment,
    per-chunk CRC, digest-verified seal, multi-source resume); the
    store digest is then compared against the exporter-stamped CRC
    before the array is handed to the importing engine — the
    digest-before-attach gate, which also covers the same-node
    short-circuit where no transfer ran.

    ``keep_source=True`` is the KV-tier read mode: the published object
    is a shared cache entry, not a single-consumer handoff, so close()
    must neither delete the source export (other replicas will fault
    the same prefix in) nor — in the same-node short-circuit, where the
    pulled segment IS the tier copy — delete the local object."""
    shape = tuple(desc["shape"])
    dtype = np.dtype(desc["dtype"])
    inline = desc.get("inline")
    if inline is not None:
        if zlib.crc32(inline) != desc["crc32"]:
            count_failure("digest")
            raise KvTransferError(
                "inline kv payload digest mismatch — refusing to attach"
            )
        arr = np.frombuffer(inline, dtype=dtype).reshape(shape)
        migration_metrics()["transfers"].inc()
        migration_metrics()["bytes"].inc(len(inline))
        return FetchedPayload(arr, lambda: None)
    be = _backend()
    if be is None:
        count_failure("transfer")
        raise KvTransferError("no node daemon to pull the kv payload through")
    from ray_tpu.core.ids import ObjectID
    from ray_tpu.core.object_store import _attach

    oid = ObjectID(bytes.fromhex(desc["object_id"]))
    try:
        reply = be.io.run(
            be.daemon.call(
                "pull_object",
                {
                    "object_id": oid.binary(),
                    "sources": [tuple(desc["source"])],
                    "deadline_s": timeout_s,
                },
            ),
            timeout=timeout_s + 15,
        )
    except Exception as e:  # noqa: BLE001
        count_failure("transfer")
        raise KvTransferError(f"kv pull failed: {e!r}") from e
    if not (isinstance(reply, dict) and reply.get("segment")):
        count_failure("transfer")
        raise KvTransferError(f"kv pull failed: {reply!r}")
    try:
        info = be.io.run(
            be.daemon.call("object_info", {"object_id": oid.binary()}),
            timeout=30,
        )
    except Exception as e:  # noqa: BLE001
        count_failure("digest")
        raise KvTransferError(f"kv digest probe failed: {e!r}") from e
    digest = (info or {}).get("digest")
    if digest != desc["crc32"]:
        count_failure("digest")
        raise KvTransferError(
            f"kv payload digest mismatch: store={digest} descriptor="
            f"{desc['crc32']} — refusing to attach"
        )
    try:
        seg = _attach(reply["segment"])
    except Exception as e:  # noqa: BLE001
        count_failure("transfer")
        raise KvTransferError(f"kv segment attach failed: {e!r}") from e
    view = memoryview(seg.buf)[: desc["size"]]
    arr = np.frombuffer(view, dtype=dtype).reshape(shape)

    def _close():
        try:
            view.release()
        except BufferError:
            pass  # live numpy views keep the mapping valid
        try:
            seg.close()
        except Exception:  # noqa: BLE001
            pass
        src = tuple(desc["source"])
        same_node = src == tuple(be.daemon_addr)
        # the received copy is private to this transfer: delete it and
        # hand the inode to the daemon's receive-segment reuse pool so
        # the NEXT migration skips segment create/zero entirely.
        # EXCEPT keep_source + same-node: no transfer ran, the "received
        # copy" is the tier entry itself — deleting it here would purge
        # the tier on every local hit.
        if not (keep_source and same_node):
            try:
                be.io.run(
                    be.daemon.call(
                        "delete_object",
                        {"object_id": oid.binary(), "recycle_receive": True},
                    ),
                    timeout=10,
                )
            except Exception:  # noqa: BLE001
                pass
        # and release the SOURCE's export promptly — a consumed payload
        # parked until the TTL reap would occupy the prefill replica's
        # store for kv_export_ttl_s per migration, forcing spills of
        # LIVE objects under sustained traffic. Best-effort: the TTL
        # reap remains the backstop. (Same-node: the local delete above
        # already dropped the shared entry; this is then a no-op.
        # keep_source: the tier entry outlives every reader — lifetime
        # belongs to the holder daemon's registry TTL, never a reader.)
        if not keep_source and not same_node:
            try:
                be.io.run(
                    be._client(src[0], src[1], role="noded").call(  # noqa: SLF001
                        "delete_object", {"object_id": oid.binary()}
                    ),
                    timeout=10,
                )
            except Exception:  # noqa: BLE001
                pass

    migration_metrics()["transfers"].inc()
    migration_metrics()["bytes"].inc(desc["size"])
    return FetchedPayload(arr, _close)


# -- cluster-wide KV prefix tier (PR 17) ------------------------------------
#
# The tier promotes the point-to-point handoff above into a shared cache:
# engines write back popular full prefix blocks (spill-vs-drop policy in
# kv_cache.PagedBlockManager + explicit write-back at prefill/decode
# block boundaries), keyed by the 16-byte CHAIN DIGEST — the same
# capability-name trick as _kv_object_id, so republish is idempotent and
# any replica can derive the fetch capability from tokens alone. The
# holder's node daemon OWNS each entry (registry + TTL + cap eviction):
# tier state survives the replica process that wrote it, which is the
# whole warm-restart story. Readers fault blocks in over the zero-copy
# pull path with keep_source=True (see fetch) — a tier read never
# consumes the entry.
#
# Daemon-less processes (local mode, unit tests) fall back to a bounded
# in-process registry of inline descriptors: same API, same CRC gate,
# no data plane.

#: surgical KV-tier fault plan installed by tests via
#: ``LLMServer.testing_arm_kv_tier_chaos`` — wins over the env-driven
#: plan exactly like ``engine.testing_fault_plan``
testing_tier_plan = None

_PLAN_CACHE = None
_PLAN_CACHE_LOCK = threading.Lock()


def active_kv_tier_fault_plan():
    """Process-wide seeded KvTierFaultPlan from
    ``testing_kv_tier_chaos`` (or None); seed logged at activation."""
    global _PLAN_CACHE
    if _PLAN_CACHE is None:
        from ray_tpu.util.chaos import KvTierFaultPlan, SeededPlanCache

        with _PLAN_CACHE_LOCK:
            if _PLAN_CACHE is None:
                _PLAN_CACHE = SeededPlanCache(
                    KvTierFaultPlan, "kv_tier",
                    "testing_kv_tier_chaos", "testing_kv_tier_chaos_seed",
                    logger,
                )
    return _PLAN_CACHE.active()


def consult_tier_chaos(phase: str):
    """One deterministic chaos consult for a tier phase (``"fault_in"``
    | ``"migration"``): ``(mode, param)`` or None. The surgically-armed
    plan wins over the env plan (same precedence as the engine's)."""
    plan = testing_tier_plan or active_kv_tier_fault_plan()
    if plan is None:
        return None
    return plan.consult(phase)


def tier_key(ns: str, digest_hex: str) -> str:
    """Registry/capability key for one tier entry: the chain digest,
    scoped by the publisher's model-identity namespace. The digest alone
    names only the TOKENS — two models given the same prompt compute the
    same chain, so an unscoped key would let one model's registry entry
    (and shm segment, via the deterministic capability name) collide
    with — and silently serve — another model's KV."""
    return f"{ns}:{digest_hex}" if ns else digest_hex


def tier_transfer_id(digest: bytes, ns: str = "") -> str:
    return "tier:" + tier_key(ns, digest.hex())


#: daemon-less fallback registry: digest hex -> inline descriptor,
#: oldest-first eviction at kv_tier_max_entries
from collections import OrderedDict as _OrderedDict  # noqa: E402

_LOCAL_TIER: "_OrderedDict[str, Dict[str, Any]]" = _OrderedDict()
_LOCAL_TIER_LOCK = threading.Lock()


def _tier_metrics():
    from ray_tpu.observability import rpc_metrics as m

    return m


def tier_publish(
    digest: bytes, kv, block_size: int, *, ns: str = ""
) -> Optional[Dict[str, Any]]:
    """Write one full prefix block's KV back into the tier, keyed by its
    chain digest scoped under ``ns`` (the publisher's model-identity
    namespace — see :func:`tier_key`). Returns the (payload-free,
    routable) descriptor on success, None on failure — write-back is
    best-effort by design: a failed spill degrades to a drop, never to
    an engine error.

    With a daemon: the payload is published as a store object the
    DAEMON owns (adopt), then registered in the daemon's tier registry
    (which owns TTL/cap lifetime) — the local _EXPORTS TTL entry is
    deliberately NOT kept, a tier entry must outlive this process.
    Without one: bounded in-process inline registry."""
    key = tier_key(ns, digest.hex())
    try:
        kv = np.ascontiguousarray(kv)
        tid = tier_transfer_id(digest, ns)
        desc = publish(
            {"tokens": [0] * block_size, "kv": kv, "block_size": block_size},
            transfer_id=tid,
        )
    except KvTransferError:
        return None
    except Exception:  # noqa: BLE001 — never let write-back hurt serving
        return None
    desc["tier_digest"] = digest.hex()
    desc["tier_ns"] = ns
    be = _backend()
    if be is None:
        with _LOCAL_TIER_LOCK:
            _LOCAL_TIER[key] = desc
            _LOCAL_TIER.move_to_end(key)
            cap = max(1, GLOBAL_CONFIG.kv_tier_max_entries)
            while len(_LOCAL_TIER) > cap:
                _LOCAL_TIER.popitem(last=False)
        _tier_metrics().KV_TIER_BYTES.inc(desc["size"], labels={"direction": "publish"})
        return desc
    # lifetime transfer: the daemon registry owns the entry from here on
    # (registry eviction/TTL deletes the object); drop the exporter-side
    # TTL record so _reap_exports never kills a live tier entry
    with _EXPORTS_LOCK:
        _EXPORTS.pop(tid, None)
    routable = {k: v for k, v in desc.items() if k != "inline"}
    try:
        be.io.run(
            be.daemon.call(
                "kv_tier_put", {"digest": key, "desc": routable}
            ),
            timeout=10,
        )
    except Exception:  # noqa: BLE001 — unregistered entry = plain export
        # daemon didn't take ownership: restore the TTL reap so the
        # orphan segment can't leak forever
        with _EXPORTS_LOCK:
            _EXPORTS[tid] = (
                _kv_object_id(tid),
                time.monotonic() + GLOBAL_CONFIG.kv_export_ttl_s,
            )
        return None
    _tier_metrics().KV_TIER_BYTES.inc(desc["size"], labels={"direction": "publish"})
    return routable


def tier_fetch(desc: Dict[str, Any], *, timeout_s: float = 10.0) -> FetchedPayload:
    """Fault one tier block in: chaos consult, then a keep_source fetch
    (the entry stays resident for every other reader). Raises
    :class:`KvTransferError` on any failure — the caller's fallback
    ladder (next source → prefix replay → cold prefill) handles it."""
    verdict = consult_tier_chaos("fault_in")
    if verdict is not None:
        mode = verdict[0]
        if mode == "missing_block":
            count_failure("tier_missing")
            raise KvTransferError(
                "chaos missing_block: tier entry vanished between advert "
                "and fault-in"
            )
        if mode == "corrupt_block":
            # model a corrupted payload by breaking the expected CRC:
            # the digest-before-attach gate MUST fire and refuse it
            desc = dict(desc)
            desc["crc32"] = int(desc.get("crc32", 0)) ^ 0x5A5A5A5A
        elif mode == "stale_advert":
            # the holder dropped the entry but the retraction hasn't
            # reached this router yet: delete, then let the pull fail
            # FAST with no source (one-hop fall-through, not a timeout)
            tier_delete(
                tier_key(
                    str(desc.get("tier_ns") or ""),
                    str(desc.get("tier_digest") or ""),
                ),
                desc=desc,
            )
            desc = dict(desc)
            desc.pop("inline", None)  # inline copies can't go stale
    return fetch(desc, timeout_s=timeout_s, keep_source=True)


def tier_delete(key: str, *, desc: Optional[Dict[str, Any]] = None) -> None:
    """Drop one tier entry (registry + object), best-effort. ``key`` is
    the full registry key (:func:`tier_key` — digest hex, namespace-
    prefixed when the publisher had one). Used by the stale_advert
    chaos mode and by holders retracting entries."""
    with _LOCAL_TIER_LOCK:
        _LOCAL_TIER.pop(key, None)
    be = _backend()
    if be is None:
        return
    try:
        be.io.run(
            be.daemon.call("kv_tier_del", {"digest": key}), timeout=10
        )
    except Exception:  # noqa: BLE001
        pass
    # cross-node descriptors name their holder: delete there too so a
    # stale_advert injection actually removes the bytes the pull wants
    if desc and desc.get("source") and tuple(desc["source"]) != tuple(be.daemon_addr):
        src = tuple(desc["source"])
        try:
            be.io.run(
                be._client(src[0], src[1], role="noded").call(  # noqa: SLF001
                    "kv_tier_del", {"digest": key}
                ),
                timeout=10,
            )
        except Exception:  # noqa: BLE001
            pass


def tier_list(ns: Optional[str] = None) -> Dict[str, Dict[str, Any]]:
    """Tier entries the LOCAL daemon holds — the warm-restart recovery
    read: a replacement replica re-adverts these within one gossip beat
    of booting. ``ns=None`` returns the raw registry (full keys);
    passing a namespace (including ``""``) filters to THAT model's
    entries and strips the prefix, returning digest hex -> descriptor —
    the registry is node-global, so recovery must never adopt (and
    re-advert) entries another deployment/model published."""
    be = _backend()
    if be is None:
        with _LOCAL_TIER_LOCK:
            entries = dict(_LOCAL_TIER)
    else:
        try:
            reply = be.io.run(be.daemon.call("kv_tier_list", {}), timeout=10)
        except Exception:  # noqa: BLE001
            return {}
        if not isinstance(reply, dict):
            return {}
        entries = reply.get("entries", {})
    if ns is None:
        return entries
    if not ns:
        return {k: v for k, v in entries.items() if ":" not in k}
    prefix = f"{ns}:"
    return {
        k[len(prefix):]: v for k, v in entries.items() if k.startswith(prefix)
    }
