"""Bucketed jitted prefill/decode steps over the paged KV cache.

Fixed shapes are the whole game on TPU: XLA compiles one program per
input shape, so the runner rounds every prefill chunk up to a length
bucket and every decode batch up to a size bucket. After warmup the
engine must see ZERO recompiles — the jit cache holds exactly one entry
per bucket, asserted via ``recompiles_after_warmup()`` (backed by
``PjitFunction._cache_size`` when jax exposes it, a shape-signature
count otherwise).

The device cache lives here as functional state: every step returns a
new cache value and the runner swaps its reference — donation hands the
buffer back on TPU (``donate_argnums``); on CPU/GPU test backends jax
copies, which the toy config absorbs.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ray_tpu.models.llama import (
    LlamaConfig,
    copy_paged_blocks,
    gather_paged_blocks,
    init_paged_kv_cache,
    paged_decode_step,
    paged_prefill_step,
    paged_verify_step,
    scatter_paged_blocks,
)

#: block-copy pairs per compiled COW program (pairs pad with null->null)
_COW_WIDTH = 4

#: blocks per compiled KV gather/scatter program (KV-cache migration);
#: short chunks pad with the null block so the shape never varies
_KV_IO_WIDTH = 8


def _round_up_bucket(n: int, buckets: Sequence[int]) -> int:
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"{n} exceeds largest bucket {buckets[-1]}")


class PagedModelRunner:
    def __init__(
        self,
        cfg: LlamaConfig,
        params,
        *,
        num_blocks: int,
        block_size: int,
        prefill_buckets: Sequence[int],
        decode_buckets: Sequence[int],
        verify_buckets: Sequence[int] = (),
        cache_dtype=None,
    ):
        import jax

        self.cfg = cfg
        self.params = params
        self.block_size = block_size
        self.num_blocks = num_blocks
        self.prefill_buckets = tuple(sorted(prefill_buckets))
        self.decode_buckets = tuple(sorted(decode_buckets))
        #: speculative-verify window buckets (k+1 positions per step);
        #: empty unless the engine enables speculation, so plain
        #: deployments keep their exact compile_count
        self.verify_buckets = tuple(sorted(verify_buckets))
        #: fixed block-table width every request/table row pads to
        self.max_blocks_per_seq = -(-cfg.max_seq_len // block_size)
        if num_blocks - 1 < self.max_blocks_per_seq:
            raise ValueError(
                f"num_blocks={num_blocks} can't hold one max-length sequence "
                f"({self.max_blocks_per_seq} blocks + null block)"
            )
        self.cache = init_paged_kv_cache(cfg, num_blocks, block_size, cache_dtype)

        # donation returns the cache buffer in place on TPU; CPU would
        # warn-and-copy, so only donate where it's real
        donate = (2,) if jax.default_backend() == "tpu" else ()
        self._prefill_jit = jax.jit(
            partial(paged_prefill_step, cfg), donate_argnums=donate
        )
        self._decode_jit = jax.jit(
            partial(paged_decode_step, cfg), donate_argnums=donate
        )
        # speculative verification: prefill-shaped, all-position logits.
        # Always constructed (an uncalled jit holds zero cache entries so
        # compile accounting is unchanged), only warmed when the engine
        # passes verify buckets.
        self._verify_jit = jax.jit(
            partial(paged_verify_step, cfg), donate_argnums=donate
        )
        # COW block duplication (prefix cache): cache is arg 0 here.
        # partial() gives THIS runner its own jit identity — a bare
        # module-level function would share one compiled-program cache
        # across every runner in the process, and another runner's cache
        # shape would show up in this one's recompile accounting
        cow_donate = (0,) if jax.default_backend() == "tpu" else ()
        self._copy_jit = jax.jit(
            partial(copy_paged_blocks), donate_argnums=cow_donate
        )
        # KV-cache migration programs (disaggregated serving): the gather
        # reads blocks out (export — never donated, the cache stays
        # live), the scatter writes imported blocks in (donation like the
        # COW copy). Compiled at warmup only when the engine opts in
        # (kv_transfer_enabled), so plain deployments keep their exact
        # compile_count; a lazy first use still works, it just shows up
        # in recompiles_after_warmup.
        self._gather_jit = jax.jit(partial(gather_paged_blocks))
        self._scatter_jit = jax.jit(
            partial(scatter_paged_blocks), donate_argnums=cow_donate
        )
        self._seen_shapes: set = set()
        self._warmup_compiles: Optional[int] = None

    # -- compile accounting ----------------------------------------------
    def _jit_cache_entries(self) -> int:
        total = 0
        for fn in (
            self._prefill_jit,
            self._decode_jit,
            self._verify_jit,
            self._copy_jit,
            self._gather_jit,
            self._scatter_jit,
        ):
            size = getattr(fn, "_cache_size", None)
            if size is None:
                return len(self._seen_shapes)
            total += size()
        return total

    def mark_warm(self) -> None:
        """Call after warmup: compiles past this point are regressions."""
        self._warmup_compiles = self._jit_cache_entries()

    def recompiles_after_warmup(self) -> int:
        if self._warmup_compiles is None:
            return 0
        return max(0, self._jit_cache_entries() - self._warmup_compiles)

    def compile_count(self) -> int:
        return self._jit_cache_entries()

    def warmup(
        self, buckets_prefill=None, buckets_decode=None, *, kv_io: bool = False
    ) -> None:
        """Compile every (or the given) bucket up front with trash inputs
        aimed at the null block, then :meth:`mark_warm`. ``kv_io`` also
        compiles the KV-migration gather/scatter programs (disaggregated
        serving opts in; plain engines keep their compile count)."""
        M = self.max_blocks_per_seq
        for c in buckets_prefill if buckets_prefill is not None else self.prefill_buckets:
            tokens = np.zeros(c, np.int32)
            row = np.zeros(M, np.int32)
            self.cache, _ = self._prefill_jit(
                self.params, self.cache, tokens, row, np.int32(0), np.int32(0)
            )
            self._seen_shapes.add(("p", c))
        for b in buckets_decode if buckets_decode is not None else self.decode_buckets:
            self.cache, _ = self._decode_jit(
                self.params,
                self.cache,
                np.zeros(b, np.int32),
                np.zeros(b, np.int32),
                np.zeros((b, M), np.int32),
                np.ones(b, np.int32),
            )
            self._seen_shapes.add(("d", b))
        # speculative-verify windows (only when the engine opted in via
        # verify_buckets — plain engines keep their exact compile count).
        # The batch axis rides the decode buckets: every (B-bucket,
        # window-bucket) pair a live engine can issue gets compiled here.
        for c in self.verify_buckets:
            for b in buckets_decode if buckets_decode is not None else self.decode_buckets:
                self.cache, _ = self._verify_jit(
                    self.params,
                    self.cache,
                    np.zeros((b, c), np.int32),
                    np.zeros((b, M), np.int32),
                    np.zeros(b, np.int32),
                    np.zeros(b, np.int32),
                )
                self._seen_shapes.add(("v", b, c))
        # the COW copy program (all-null pairs write the null block's
        # trash back onto itself)
        pad = np.zeros(_COW_WIDTH, np.int32)
        self.cache = self._copy_jit(self.cache, pad, pad)
        self._seen_shapes.add(("c", _COW_WIDTH))
        if kv_io:
            ids = np.zeros(_KV_IO_WIDTH, np.int32)
            kv = np.asarray(self._gather_jit(self.cache, ids))
            self.cache = self._scatter_jit(self.cache, ids, kv)
            self._seen_shapes.add(("g", _KV_IO_WIDTH))
            self._seen_shapes.add(("s", _KV_IO_WIDTH))
        self.mark_warm()

    # -- steps ------------------------------------------------------------
    def copy_blocks(self, pairs: Sequence[Tuple[int, int]]) -> None:
        """Device-side block duplication (prefix-cache COW): each
        ``(src, dst)`` pair copies one whole block across every layer.
        Pairs beyond ``_COW_WIDTH`` run in chunks; short chunks pad with
        null->null no-op pairs so the compiled shape never varies."""
        for i in range(0, len(pairs), _COW_WIDTH):
            chunk = pairs[i : i + _COW_WIDTH]
            src = np.zeros(_COW_WIDTH, np.int32)
            dst = np.zeros(_COW_WIDTH, np.int32)
            for j, (s, d) in enumerate(chunk):
                src[j], dst[j] = s, d
            self._seen_shapes.add(("c", _COW_WIDTH))
            self.cache = self._copy_jit(self.cache, src, dst)

    def gather_blocks(self, block_ids: Sequence[int]) -> np.ndarray:
        """Read whole cache blocks to host (KV-migration export):
        returns ``[2, n_layers, len(block_ids), block_size, n_kv,
        head_dim]`` numpy in the cache dtype. Runs in _KV_IO_WIDTH
        chunks padded with the null block so the compiled shape never
        varies; padding rows are sliced off before concatenation."""
        outs = []
        for i in range(0, len(block_ids), _KV_IO_WIDTH):
            chunk = block_ids[i : i + _KV_IO_WIDTH]
            ids = np.zeros(_KV_IO_WIDTH, np.int32)
            ids[: len(chunk)] = chunk
            self._seen_shapes.add(("g", _KV_IO_WIDTH))
            out = self._gather_jit(self.cache, ids)
            outs.append(np.asarray(out)[:, :, : len(chunk)])
        if not outs:
            shape = self.cache["k"].shape  # [L, N, bs, kv, hd]
            return np.zeros(
                (2, shape[0], 0, shape[2], shape[3], shape[4]),
                np.asarray(self.cache["k"]).dtype,
            )
        return np.concatenate(outs, axis=2) if len(outs) > 1 else outs[0]

    def scatter_blocks(self, block_ids: Sequence[int], kv: np.ndarray) -> None:
        """Write migrated KV blocks into this cache (KV-migration
        import): ``kv`` is the :meth:`gather_blocks` layout, one row per
        id in ``block_ids``. Short chunks pad with the null block (its
        rows get zero-filled trash — inert by construction)."""
        for i in range(0, len(block_ids), _KV_IO_WIDTH):
            chunk = block_ids[i : i + _KV_IO_WIDTH]
            ids = np.zeros(_KV_IO_WIDTH, np.int32)
            ids[: len(chunk)] = chunk
            buf = np.zeros(
                kv.shape[:2] + (_KV_IO_WIDTH,) + kv.shape[3:], kv.dtype
            )
            buf[:, :, : len(chunk)] = kv[:, :, i : i + len(chunk)]
            self._seen_shapes.add(("s", _KV_IO_WIDTH))
            self.cache = self._scatter_jit(self.cache, ids, buf)

    def prefill_chunk(
        self,
        tokens: Sequence[int],
        block_row: Sequence[int],
        ctx_len: int,
    ) -> np.ndarray:
        """Run one prefill chunk; returns logits [vocab] (fp32 numpy) for
        the chunk's last valid token."""
        true_len = len(tokens)
        bucket = _round_up_bucket(true_len, self.prefill_buckets)
        padded = np.zeros(bucket, np.int32)
        padded[:true_len] = tokens
        row = np.asarray(block_row, np.int32)
        self._seen_shapes.add(("p", bucket))
        self.cache, logits = self._prefill_jit(
            self.params, self.cache, padded, row,
            np.int32(ctx_len), np.int32(true_len),
        )
        return np.asarray(logits)

    def verify_batch(
        self,
        windows: Sequence[Sequence[int]],
        block_rows: Sequence[Sequence[int]],
        ctx_lens: Sequence[int],
    ) -> List[np.ndarray]:
        """Run speculative-verify windows (``[last_committed, d_1..d_k]``
        each) for a batch of slots in ONE jitted step. Returns one
        logits array [len(window), vocab] (fp32 numpy) per slot, a row
        per valid window position. The batch axis pads to a decode
        bucket; padding slots carry ``true_len=0`` so every position is
        invalid and the writes land on the null block."""
        n = len(windows)
        cbucket = _round_up_bucket(max(len(w) for w in windows), self.verify_buckets)
        bbucket = _round_up_bucket(n, self.decode_buckets)
        M = self.max_blocks_per_seq
        tokens = np.zeros((bbucket, cbucket), np.int32)
        tables = np.zeros((bbucket, M), np.int32)
        ctx = np.zeros(bbucket, np.int32)
        tl = np.zeros(bbucket, np.int32)
        for i, w in enumerate(windows):
            tokens[i, : len(w)] = w
            tables[i] = block_rows[i]
            ctx[i] = ctx_lens[i]
            tl[i] = len(w)
        self._seen_shapes.add(("v", bbucket, cbucket))
        self.cache, logits = self._verify_jit(
            self.params, self.cache, tokens, tables, ctx, tl
        )
        out = np.asarray(logits)
        return [out[i, : len(w)] for i, w in enumerate(windows)]

    def decode(
        self,
        tokens: Sequence[int],
        positions: Sequence[int],
        block_rows: Sequence[Sequence[int]],
        ctx_lens: Sequence[int],
    ) -> np.ndarray:
        """Advance a decode batch one token; returns logits [n, vocab]
        for the n REAL slots (padding stripped)."""
        n = len(tokens)
        bucket = _round_up_bucket(n, self.decode_buckets)
        M = self.max_blocks_per_seq
        t = np.zeros(bucket, np.int32)
        p = np.zeros(bucket, np.int32)
        bt = np.zeros((bucket, M), np.int32)
        cl = np.ones(bucket, np.int32)  # padding slots: ctx=1 over the null block
        t[:n] = tokens
        p[:n] = positions
        bt[:n] = np.asarray(block_rows, np.int32)
        cl[:n] = ctx_lens
        self._seen_shapes.add(("d", bucket))
        self.cache, logits = self._decode_jit(self.params, self.cache, t, p, bt, cl)
        return np.asarray(logits)[:n]
