"""Continuous-batching scheduler: per-step prefill/decode selection.

Reference shape (vLLM / the TPU inference workers in PAPERS.md): one
scheduler invocation per engine step returns a :class:`StepPlan` — at
most ``max_prefills_per_step`` prefill *chunks* plus the batch of decode
slots to advance one token. Decode and prefill coexist in a step, which
is what makes the batching "continuous": a new request's prefill rides
alongside the standing decode batch instead of draining it.

Policies, all host-side and unit-testable without jax:

* **admission control** — a request is admitted only when the block pool
  can cover its full prompt plus one decode block of headroom; otherwise
  it waits in the FIFO admission queue (bounded by ``max_queue_depth``).
* **preemption** — when a decoding request needs one more block and the
  pool is dry, the lowest-priority latest-arrival running request is
  evicted: blocks freed, request back to the FRONT of the queue with its
  generated-so-far tokens kept; readmission re-prefills prompt+generated
  (vLLM's recompute-style preemption — cheaper than swap on TPU where
  host<->HBM bandwidth is the scarce resource).
* **cancellation** — frees blocks immediately, whether the request is
  queued, prefilling, or decoding.
"""

from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ray_tpu.inference.kv_cache import PagedBlockManager

# request lifecycle states
QUEUED = "QUEUED"
PREFILL = "PREFILL"
DECODE = "DECODE"
FINISHED = "FINISHED"
CANCELLED = "CANCELLED"
FAILED = "FAILED"

_seq = itertools.count()


@dataclass
class Request:
    """One generation request as the scheduler sees it."""

    request_id: str
    prompt: List[int]
    max_new_tokens: int = 16
    #: larger = more important; preemption victims are chosen from the
    #: lowest priority first (ties: latest arrival)
    priority: int = 0
    temperature: float = 0.0
    eos_token: Optional[int] = None
    #: ``core.deadline.Deadline`` (or None) — the engine fails the
    #: request the step after its budget runs out
    deadline: object = None
    seed: Optional[int] = None
    #: export-after-prefill mode (disaggregated serving): the request
    #: finishes when its prompt K/V is fully written — no token is ever
    #: sampled; the engine gathers the full blocks to host and hands the
    #: payload to the waiting exporter instead
    prefill_only: bool = False
    #: SLO-ledger label: the ingress priority class that admitted this
    #: request ("" for direct callers) — rides into the latency
    #: histograms and the flight-recorder entry
    tenant_class: str = ""
    #: pre-measured stage durations stamped by upstream tiers (e.g. the
    #: decode replica's KV import ran BEFORE submit) — merged into the
    #: ledger's stage breakdown at finish
    ledger_stages: Dict[str, float] = field(default_factory=dict)
    #: False for router RESUME attempts (rid.rN): the survivor's warm
    #: replay produces an artificially fast engine-view TTFT/ITL, so
    #: observing it into the SLO histograms would make cluster quantiles
    #: look BETTER under failover. The client-perceived failover cost
    #: lives in the router-tier ledger; resume attempts still book
    #: goodput/fault tokens and file flight-recorder entries.
    record_slo: bool = True
    #: speculative-decoding draft budget for this request (0 = plain
    #: decode; the per-request off-switch). The engine stamps it from
    #: EngineConfig at submit; the scheduler may plan LESS per step
    #: (``spec_step_k``) under block pressure or adaptive-k shrink.
    spec_k: int = 0

    state: str = QUEUED
    #: prompt positions already written to the KV cache (chunked prefill
    #: cursor); on preemption this resets to 0 and the *effective* prompt
    #: becomes the prompt + generated SNAPSHOT taken at eviction
    prefill_pos: int = 0
    generated: List[int] = field(default_factory=list)
    preemptions: int = 0
    #: frozen at preemption time (prompt + generated-so-far). A live
    #: ``prompt + self.generated`` here would GROW as decode appends
    #: tokens, flipping ``prefill_done`` back to False every step and
    #: silently routing decode through ungrown prefill chunks.
    restart_prompt: Optional[List[int]] = None
    #: device block copies the engine must run BEFORE this request's
    #: next prefill chunk (prefix-cache COW: a full-prompt hit recomputes
    #: its last token into a private copy of the final shared block)
    pending_cow: List[tuple] = field(default_factory=list)
    #: prompt tokens covered by the prefix cache at (re)admission —
    #: prefill was skipped for them (observability)
    cached_prefix_tokens: int = 0
    arrival: int = field(default_factory=lambda: next(_seq))
    # -- SLO-ledger lifecycle stamps (monotonic floats on the request
    # object the scheduler/engine already pass around — the hot path
    # pays one clock read per boundary, no allocation)
    #: first admission into the running set (queue-wait ends here;
    #: readmissions after preemption keep the ORIGINAL stamp — the
    #: client-visible queue wait happened once)
    admitted_at: Optional[float] = None
    #: prompt K/V fully written (prefill stage ends here)
    prefill_done_at: Optional[float] = None
    #: last token emission (the engine derives per-token decode gaps)
    last_emit_at: Optional[float] = None
    #: worst inter-token gap seen (the request's ITL high-water mark)
    max_itl_s: float = 0.0
    #: drafts the CURRENT step may verify for this slot — set by the
    #: scheduler every plan (0 = this step decodes plainly): speculation
    #: is opportunistic, it never preempts and shrinks to zero whenever
    #: the pool can't cover the extra draft positions
    spec_step_k: int = 0

    @property
    def effective_prompt(self) -> List[int]:
        """What prefill must (re)process: the original prompt, or the
        snapshot taken when the request was last preempted."""
        return self.restart_prompt if self.restart_prompt is not None else self.prompt

    @property
    def context_len(self) -> int:
        """Token positions currently live in the KV cache."""
        return len(self.prompt) + len(self.generated)

    @property
    def prefill_done(self) -> bool:
        return self.prefill_pos >= len(self.effective_prompt)

    @property
    def finished(self) -> bool:
        return self.state in (FINISHED, CANCELLED, FAILED)


@dataclass
class StepPlan:
    """What one engine step should run."""

    #: (request, chunk_start, chunk_len) prefill chunks, at most
    #: ``max_prefills_per_step``
    prefills: List[tuple] = field(default_factory=list)
    #: requests advancing one decode token this step
    decodes: List[Request] = field(default_factory=list)
    #: requests the scheduler finished/failed while planning (deadline
    #: expiry, preemption-queue overflow) — the engine must notify waiters
    reaped: List[Request] = field(default_factory=list)

    @property
    def empty(self) -> bool:
        return not self.prefills and not self.decodes and not self.reaped


class ContinuousBatchingScheduler:
    def __init__(
        self,
        blocks: PagedBlockManager,
        *,
        max_decode_batch: int = 8,
        max_prefill_chunk: int = 64,
        max_prefills_per_step: int = 1,
        max_queue_depth: int = 128,
    ):
        self.blocks = blocks
        self.max_decode_batch = max_decode_batch
        self.max_prefill_chunk = max_prefill_chunk
        self.max_prefills_per_step = max_prefills_per_step
        self.max_queue_depth = max_queue_depth
        self.waiting: List[Request] = []
        self.running: List[Request] = []
        #: engine-side speculative caps, consulted when planning decode
        #: slots: ``spec_k_live`` is the adaptive-k controller's current
        #: ceiling (None = uncapped), ``spec_max_context`` the model's
        #: max_seq_len (draft positions must stay inside the block-table
        #: row width)
        self.spec_k_live: Optional[int] = None
        self.spec_max_context: Optional[int] = None
        self._lock = threading.RLock()
        self.admitting = True
        # observability
        self.total_admitted = 0
        self.total_preempted = 0
        self.steps_with_prefill_and_decode = 0
        self.max_decode_batch_seen = 0
        #: prefill tokens RE-RUN because a preemption evicted their KV
        #: (minus what the prefix cache still covered at readmission) —
        #: the engine delta-exports this as fault-cost tokens
        self.total_replay_prefill_tokens = 0

    # -- intake -----------------------------------------------------------
    def add(self, req: Request) -> None:
        with self._lock:
            if not self.admitting:
                raise RuntimeError("engine is draining: not admitting requests")
            if len(self.waiting) >= self.max_queue_depth:
                raise RuntimeError(
                    f"admission queue full ({self.max_queue_depth} waiting)"
                )
            self.waiting.append(req)

    def cancel(self, request_id: str) -> Optional[Request]:
        """Cancel wherever the request is; frees its blocks. Returns the
        request (for waiter notification) or None if unknown/finished."""
        with self._lock:
            for pool in (self.waiting, self.running):
                for req in pool:
                    if req.request_id == request_id:
                        pool.remove(req)
                        req.state = CANCELLED
                        self.blocks.free(request_id)
                        return req
        return None

    def take_all(self) -> List[Request]:
        """Atomically strip every queued + running request (engine-level
        failure path: the caller owns notifying waiters / freeing blocks)."""
        with self._lock:
            out = list(self.waiting) + list(self.running)
            self.waiting.clear()
            self.running.clear()
            return out

    def has_work(self) -> bool:
        with self._lock:
            return bool(self.waiting or self.running)

    def queue_depth(self) -> int:
        with self._lock:
            return len(self.waiting)

    def outstanding_tokens(self) -> int:
        """Token-denominated backlog: prefill still owed plus decode
        still to run, across queued and running requests — the router's
        least-outstanding-tokens load signal (a queue-DEPTH count rates
        a 4-token probe and a 2k-token prompt the same; tokens don't)."""
        total = 0
        with self._lock:
            for req in self.waiting:
                total += len(req.effective_prompt) + req.max_new_tokens
            for req in self.running:
                prompt = req.effective_prompt
                total += max(0, len(prompt) - req.prefill_pos)
                total += max(0, req.max_new_tokens - len(req.generated))
        return total

    # -- planning ---------------------------------------------------------
    def _admit(self, reaped: List[Request]) -> None:
        """FIFO admission: pop waiting requests while blocks cover their
        effective prompt + one decode block of headroom."""
        # expiry sweep over the WHOLE queue first: an expired request
        # stuck behind a non-admittable head must still fail promptly —
        # a head-only check would leave it QUEUED (and its caller
        # blocked) until the head eventually admits
        for req in list(self.waiting):
            if req.deadline is not None and getattr(req.deadline, "expired", False):
                self.waiting.remove(req)
                req.state = FAILED
                reaped.append(req)
        while self.waiting:
            req = self.waiting[0]
            prompt = req.effective_prompt
            # prefix cache: attach shared blocks covering the longest
            # cached prefix; prefill then plans only the uncached tail.
            # A readmission re-queries too — its own blocks usually
            # still sit in the cache, making readmission near-free.
            cached, cow = self.blocks.acquire_prefix(req.request_id, prompt)
            need = len(prompt) + 1  # headroom: first decode token
            if not self.blocks.grow_to(req.request_id, need):
                if cached or cow:
                    # roll the acquisition back: a QUEUED request must
                    # hold nothing, or pool accounting drifts while it
                    # waits (the next tick re-acquires — the hit blocks
                    # just return to the cache LRU meanwhile)
                    self.blocks.free(req.request_id)
                break  # FIFO: don't starve the head by admitting behind it
            self.waiting.pop(0)
            req.state = PREFILL
            req.prefill_pos = cached
            req.pending_cow = list(cow)
            req.cached_prefix_tokens = cached
            self.blocks.note_prefix_hit(cached)
            self.running.append(req)
            if req.admitted_at is None:
                req.admitted_at = time.monotonic()
            if req.preemptions == 0:
                # readmissions after preemption are churn, not intake —
                # they show up in total_preempted instead
                self.total_admitted += 1
            else:
                # the fault-cost ledger: prefill work this readmission
                # must REDO (the cache-covered prefix costs nothing)
                self.total_replay_prefill_tokens += max(0, len(prompt) - cached)

    def _preempt_one(self, exclude: Request, protected_ids=frozenset()) -> bool:
        """Evict the lowest-priority, latest-arrival running request
        (other than ``exclude`` and anything in ``protected_ids`` — the
        requests already placed in THIS step's plan, which the engine
        will execute with the block tables they hold right now) and push
        it back to the queue front."""
        candidates = [
            r
            for r in self.running
            if r is not exclude and id(r) not in protected_ids
        ]
        if not candidates:
            return False
        victim = min(candidates, key=lambda r: (r.priority, -r.arrival))
        if victim.priority > exclude.priority:
            return False  # never preempt strictly-higher priority work
        self.running.remove(victim)
        self.blocks.evict(victim.request_id)  # also drops any COW pins
        victim.state = QUEUED
        victim.prefill_pos = 0
        victim.preemptions += 1
        victim.restart_prompt = victim.prompt + victim.generated
        # an unexecuted COW died with the eviction: readmission
        # re-acquires from the cache and plans a fresh copy if needed
        victim.pending_cow = []
        self.waiting.insert(0, victim)
        self.total_preempted += 1
        return True

    def schedule(self) -> StepPlan:
        plan = StepPlan()
        with self._lock:
            self._admit(plan.reaped)

            # deadline reaping for running work (budget exhausted mid-flight)
            for req in list(self.running):
                if req.deadline is not None and getattr(req.deadline, "expired", False):
                    self.running.remove(req)
                    self.blocks.free(req.request_id)
                    req.state = FAILED
                    plan.reaped.append(req)

            # prefill chunks: oldest prefill-incomplete requests first
            prefilling = sorted(
                (r for r in self.running if not r.prefill_done),
                key=lambda r: (-r.priority, r.arrival),
            )
            for req in prefilling[: self.max_prefills_per_step]:
                prompt = req.effective_prompt
                start = req.prefill_pos
                chunk = min(self.max_prefill_chunk, len(prompt) - start)
                plan.prefills.append((req, start, chunk))

            # decode batch: fully-prefilled requests, highest priority /
            # oldest first when the batch cap bites. Each needs this
            # step's write position covered by a block — grow, preempting
            # on exhaustion. A victim must never be something already in
            # the plan: the engine would run it on freed (null) blocks.
            planned_ids = {id(p[0]) for p in plan.prefills}
            decodable = sorted(
                (r for r in self.running if r.prefill_done),
                key=lambda r: (-r.priority, r.arrival),
            )
            for req in decodable[: self.max_decode_batch]:
                if req not in self.running:
                    continue  # evicted by an earlier decode's growth
                # the step writes KV at position context_len-1 (the token
                # sampled LAST step): coverage of exactly context_len
                # positions; the token emitted this step grows the table
                # next step
                need = req.context_len
                # speculative slots want k extra positions (the verify
                # window writes K/V at context_len-1 .. context_len+k-1).
                # Opportunistic only: spec growth never preempts, and a
                # dry pool degrades the slot to plain decode this step.
                k = req.spec_k
                if k > 0:
                    if self.spec_k_live is not None:
                        k = min(k, self.spec_k_live)
                    k = min(k, req.max_new_tokens - len(req.generated) - 1)
                    if self.spec_max_context is not None:
                        k = min(k, self.spec_max_context - need)
                    k = max(0, k)
                req.spec_step_k = 0
                if k > 0 and self.blocks.grow_to(req.request_id, need + k):
                    req.spec_step_k = k
                    plan.decodes.append(req)
                    planned_ids.add(id(req))
                    continue
                grown = self.blocks.grow_to(req.request_id, need)
                while not grown and self._preempt_one(req, planned_ids):
                    grown = self.blocks.grow_to(req.request_id, need)
                if grown:
                    plan.decodes.append(req)
                    planned_ids.add(id(req))
                # else: stalled this step — retried next step once a
                # finishing request returns blocks

            if plan.prefills and plan.decodes:
                self.steps_with_prefill_and_decode += 1
            self.max_decode_batch_seen = max(
                self.max_decode_batch_seen, len(plan.decodes)
            )
        return plan

    # -- completion -------------------------------------------------------
    def finish(self, req: Request, state: str = FINISHED) -> bool:
        """Move ``req`` to a terminal state and free its blocks. Returns
        False when the request is ALREADY terminal — cancel() and the
        step thread's done-path race, and both state transitions happen
        under this lock, so exactly one caller wins (the loser must not
        notify waiters or count the outcome again)."""
        with self._lock:
            if req.finished:
                return False
            if req in self.running:
                self.running.remove(req)
            self.blocks.free(req.request_id)
            req.state = state
            return True

    def stats(self) -> Dict[str, float]:
        with self._lock:
            return {
                "queue_depth": len(self.waiting),
                "running": len(self.running),
                "admitting": self.admitting,
                "total_admitted": self.total_admitted,
                "total_preempted": self.total_preempted,
                "steps_with_prefill_and_decode": self.steps_with_prefill_and_decode,
                "max_decode_batch_seen": self.max_decode_batch_seen,
            }
