"""serve.llm-style deployment: the InferenceEngine behind Serve.

``llm_deployment(...)`` returns a regular Serve :class:`Deployment`
whose replicas each host one :class:`LLMServer` (engine + model params).
Tokens stream to callers through the runtime's ``num_returns="streaming"``
generator path and the Serve router/proxy:

    from ray_tpu import serve
    from ray_tpu.inference import EngineConfig, llm_deployment

    dep = llm_deployment(LlamaConfig.tiny(), engine=EngineConfig(num_blocks=64))
    handle = serve.run(dep.bind())
    for tok in handle.stream({"prompt": [3, 7, 11], "max_new_tokens": 16},
                             _method="generate"):
        ...

Per-request deadlines: the caller's timeout propagates onto the task
spec (``core/deadline.py``) and the executing replica re-enters the
budget, so ``LLMServer.generate`` submits with the remaining budget and
the engine stops decoding for callers that already gave up. Node drain:
each replica engine subscribes to the node DRAINING push — a preemption
warning stops admission while Serve unroutes the replica and waits for
the in-flight streams, so clients see completed generations, not errors.

Retry semantics note: ``handle.call``/``router.execute`` are
at-least-once — a replica death mid-call re-executes the generation on a
survivor. Generation is NOT idempotent across replicas (fresh params =
same tokens, but duplicated sampling work); callers that care should use
``handle.stream`` (retries only before the first token) or pass a
``request_id`` and dedupe downstream.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Sequence, Union


class LLMServer:
    """One replica: model params + continuous-batching engine.

    Defined undecorated at module level so cloudpickle exports it by
    reference (see serve/replica.py for the rationale).
    """

    def __init__(
        self,
        model_cfg=None,
        engine_cfg=None,
        *,
        seed: int = 0,
        params=None,
        export_metrics: bool = True,
    ):
        import jax

        from ray_tpu.core.config import GLOBAL_CONFIG
        from ray_tpu.inference.engine import EngineConfig, InferenceEngine
        from ray_tpu.models.llama import LlamaConfig, init_params

        if model_cfg is None:
            model_cfg = LlamaConfig.tiny()
        self.model_cfg = model_cfg
        if params is None:
            params = init_params(model_cfg, jax.random.PRNGKey(seed))
        self.engine = InferenceEngine(
            model_cfg, params, engine_cfg or EngineConfig()
        ).start()
        self.engine.attach_node_drain_listener()
        self._metrics_server = None
        if export_metrics and GLOBAL_CONFIG.metrics_export_enabled:
            # replicas run in worker processes, which don't host the
            # daemon's /metrics endpoint — export the engine gauges from
            # an auto-port server of our own (address via metrics_address)
            from ray_tpu.observability.metrics import MetricsServer

            self._metrics_server = MetricsServer(
                host=GLOBAL_CONFIG.metrics_bind_host, port=0
            )

    # -- request plumbing -------------------------------------------------
    @staticmethod
    def _parse(request) -> Dict[str, Any]:
        if isinstance(request, dict):
            if "prompt" not in request:
                raise ValueError("request dict needs a 'prompt' (list of token ids)")
            return dict(request)
        if isinstance(request, (list, tuple)):
            return {"prompt": list(request)}
        raise TypeError(
            f"request must be a dict or token list, got {type(request).__name__}"
        )

    def generate(self, request) -> Iterator[int]:
        """Streaming entry (call with ``num_returns="streaming"`` /
        ``handle.stream(..., _method="generate")``): yields token ids as
        they decode. Request fields: prompt (required), max_new_tokens,
        temperature, priority, eos_token, request_id, seed."""
        r = self._parse(request)
        yield from self.engine.generate(
            r["prompt"],
            max_new_tokens=r.get("max_new_tokens"),
            temperature=float(r.get("temperature", 0.0)),
            priority=int(r.get("priority", 0)),
            eos_token=r.get("eos_token"),
            request_id=r.get("request_id"),
            seed=r.get("seed"),
        )

    def __call__(self, request) -> Dict[str, Any]:
        """Non-streaming: returns the full generation in one reply."""
        return {"tokens": list(self.generate(request))}

    # -- introspection ----------------------------------------------------
    def engine_stats(self) -> Dict[str, Any]:
        return self.engine.stats()

    def routing_stats(self) -> Dict[str, Any]:
        """Load + prefix-digest gossip consumed by the serve router's
        cache-affinity scoring. The presence of this method is what opts
        a deployment's replicas into the gossip reporter
        (``serve/replica.py``) — plain deployments never pay for it."""
        return self.engine.routing_stats()

    def metrics_address(self) -> Optional[str]:
        if self._metrics_server is None:
            return None
        return f"{self._metrics_server.host}:{self._metrics_server.port}"

    def begin_drain(self, grace_s: Optional[float] = None) -> None:
        """Test/ops hook: drain without a node event."""
        self.engine.begin_drain(grace_s)

    def check_health(self) -> bool:
        return not self.engine._stop.is_set()

    def __del__(self):
        try:
            self.engine.stop()
            if self._metrics_server is not None:
                self._metrics_server.stop()
        except Exception:
            pass


def llm_deployment(
    model_cfg=None,
    *,
    engine: Any = None,
    name: str = "llm",
    num_replicas: int = 1,
    max_concurrent_queries: int = 32,
    ray_actor_options: Optional[Dict[str, Any]] = None,
    route_prefix: Optional[str] = "/llm",
    seed: int = 0,
    autoscaling_config=None,
    version: Optional[str] = None,
):
    """Build a Serve deployment serving ``model_cfg`` through a
    continuous-batching engine (the ``serve.llm`` entry point).

    ``serve.run(llm_deployment(cfg).bind())`` → DeploymentHandle whose
    ``stream(request, _method="generate")`` yields tokens and whose
    ``remote(request)`` returns the whole generation. ``num_replicas``
    scales out: each replica hosts its own engine (same ``seed`` → same
    params → identical generations), the router scores replicas by
    outstanding tokens blended with prefix-cache affinity, and
    ``autoscaling_config`` reacts to serve ongoing counts PLUS the
    engines' gossiped admission-queue depth. Pin ``version`` to make a
    num_replicas redeploy an in-place scale instead of a rolling
    replacement (model code rarely changes between scale events; a
    fresh replica warmup per scale step would)."""
    from ray_tpu import serve

    dep = serve.deployment(
        name=name,
        num_replicas=num_replicas,
        max_concurrent_queries=max_concurrent_queries,
        ray_actor_options=ray_actor_options,
        route_prefix=route_prefix,
        autoscaling_config=autoscaling_config,
        version=version,
    )(LLMServer)

    class _BoundDeployment:
        """Deployment with the model/engine config pre-bound."""

        def __init__(self, inner):
            self._inner = inner

        def bind(self, **overrides):
            kwargs = {"seed": seed, **overrides}
            return self._inner.bind(model_cfg, engine, **kwargs)

        def __getattr__(self, item):
            return getattr(self._inner, item)

    return _BoundDeployment(dep)
