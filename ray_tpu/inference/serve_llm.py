"""serve.llm-style deployment: the InferenceEngine behind Serve.

``llm_deployment(...)`` returns a regular Serve :class:`Deployment`
whose replicas each host one :class:`LLMServer` (engine + model params).
Tokens stream to callers through the runtime's ``num_returns="streaming"``
generator path and the Serve router/proxy:

    from ray_tpu import serve
    from ray_tpu.inference import EngineConfig, llm_deployment

    dep = llm_deployment(LlamaConfig.tiny(), engine=EngineConfig(num_blocks=64))
    handle = serve.run(dep.bind())
    for tok in handle.stream({"prompt": [3, 7, 11], "max_new_tokens": 16},
                             _method="generate"):
        ...

Per-request deadlines: the caller's timeout propagates onto the task
spec (``core/deadline.py``) and the executing replica re-enters the
budget, so ``LLMServer.generate`` submits with the remaining budget and
the engine stops decoding for callers that already gave up. Node drain:
each replica engine subscribes to the node DRAINING push — a preemption
warning stops admission while Serve unroutes the replica and waits for
the in-flight streams, so clients see completed generations, not errors.

Retry semantics note (the three-tier contract, serve/router.py):
``handle.call``/``router.execute`` are at-least-once across replica
death; ``handle.stream(..., _method="generate")`` is EXACTLY-ONCE —
``generate`` is declared in :attr:`LLMServer.resumable_streams`, so the
router resumes an interrupted stream on a survivor with the prompt
extended by the already-delivered tokens, and deterministic continuation
(engine sampling keyed on ``(seed, position)``) makes the replayed
stream byte-exact. The replay is sound ONLY because generation is
side-effect-free and deterministic given (params seed, request seed,
prompt) — a callable with external side effects must not declare its
streams resumable.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Sequence, Union

from ray_tpu.core.streaming import TokenChunk


class LLMServer:
    """One replica: model params + continuous-batching engine.

    Defined undecorated at module level so cloudpickle exports it by
    reference (see serve/replica.py for the rationale).
    """

    #: streaming methods that are safe to RESUME on another replica after
    #: a mid-stream death (serve router exactly-once token delivery).
    #: Sound here because generation is deterministic (same params seed +
    #: request seed + prompt → same tokens, engine sampling keyed on
    #: (seed, position)) and side-effect-free; anything that writes to
    #: the outside world per item must never appear in this tuple.
    resumable_streams = ("generate",)

    def __init__(
        self,
        model_cfg=None,
        engine_cfg=None,
        *,
        seed: int = 0,
        params=None,
        export_metrics: bool = True,
    ):
        import jax

        from ray_tpu.core.config import GLOBAL_CONFIG
        from ray_tpu.inference.engine import EngineConfig, InferenceEngine
        from ray_tpu.models.llama import LlamaConfig, init_params

        if model_cfg is None:
            model_cfg = LlamaConfig.tiny()
        self.model_cfg = model_cfg
        if params is None:
            params = init_params(model_cfg, jax.random.PRNGKey(seed))
        self.engine = InferenceEngine(
            model_cfg, params, engine_cfg or EngineConfig()
        ).start()
        self.engine.attach_node_drain_listener()
        self._metrics_server = None
        if export_metrics and GLOBAL_CONFIG.metrics_export_enabled:
            # replicas run in worker processes, which don't host the
            # daemon's /metrics endpoint — export the engine gauges from
            # an auto-port server of our own (address via metrics_address)
            from ray_tpu.observability.metrics import MetricsServer

            self._metrics_server = MetricsServer(
                host=GLOBAL_CONFIG.metrics_bind_host, port=0
            )

    # -- request plumbing -------------------------------------------------
    @staticmethod
    def _parse(request) -> Dict[str, Any]:
        if isinstance(request, dict):
            if "prompt" not in request:
                raise ValueError("request dict needs a 'prompt' (list of token ids)")
            return dict(request)
        if isinstance(request, (list, tuple)):
            return {"prompt": list(request)}
        raise TypeError(
            f"request must be a dict or token list, got {type(request).__name__}"
        )

    def generate(self, request) -> Iterator[int]:
        """Streaming entry (call with ``num_returns="streaming"`` /
        ``handle.stream(..., _method="generate")``): yields
        :class:`TokenChunk` bursts of token ids as they decode (one per
        engine wake-up — the serve router flattens them, so
        ``handle.stream`` consumers still see a per-token stream).
        Request fields: prompt (required), max_new_tokens,
        temperature, priority, eos_token, request_id, seed, resume_from,
        speculative (per-request off-switch for a speculative engine —
        output bytes are identical either way).

        ``resume_from`` (stamped by the serve router for resumable
        streams; absent for direct callers) switches to seq-numbered
        mode: the prompt carries ``resume_from`` already-delivered
        tokens of an interrupted stream, and chunk elements become
        ``(seq, token)`` pairs so the router can suppress replayed
        duplicates at the failover boundary. ``max_new_tokens`` stays
        the ORIGINAL request's cap — the replica subtracts what was
        already delivered, so the client-visible stream length never
        changes across failovers."""
        import time as _time

        r = self._parse(request)
        resume_from = r.get("resume_from")
        tenant_class = str(r.get("tenant_class") or "")
        # resumable streams are observed into the SLO latency histograms
        # by the ROUTER (slo_observer="router"): the router sees the
        # client-perceived timeline — failover stalls count as slow
        # gaps, samples survive replica SIGKILLs, and a resume attempt's
        # artificially fast warm replay (resume_attempt>=1) never lands
        # as its own sample. The engine observes only for requests no
        # router is watching (direct callers, non-resumable streams).
        record_slo = not (
            r.get("resume_attempt") or r.get("slo_observer") == "router"
        )
        ledger_stages = {}
        desc = r.pop("kv_import", None)
        if desc is not None and not resume_from:
            # not resume_from: attempt 0 of a resumable stream carries
            # resume_from=0 (the router stamps it on every attempt), and
            # 0 delivered tokens means the prompt is still the original
            # one the descriptor was exported for
            # disaggregated handoff: install the prefill pool's KV
            # blocks BEFORE submitting, so admission acquires them as a
            # prefix hit (prefill_pos=cached; the 1-token tail rides the
            # existing COW last-block rule). Any failure — transfer,
            # digest, pool pressure, shape mismatch — degrades to a
            # plain full prefill right here; the stream never fails
            # because of the migration.
            t0 = _time.monotonic()
            self._import_kv(desc, r["prompt"])
            # ledger stage: the KV fetch+scatter ran BEFORE submit, so
            # its cost is handed to the engine's ledger as a pre-stage
            ledger_stages["kv_import"] = _time.monotonic() - t0
        tier = r.pop("kv_tier", None)
        if tier is not None:
            # cluster KV-tier fault-in (PR 17): unlike kv_import this
            # runs on EVERY attempt — on a resume the prompt already
            # carries the delivered tokens, and the router re-attached
            # descriptors for the extended token chain, so a failover's
            # "replay" becomes tier hits instead of re-prefill. Every
            # failure rung degrades toward plain prefix replay; the
            # stream itself can never fail here.
            t0 = _time.monotonic()
            committed = self._import_tier(tier, r["prompt"])
            ledger_stages["kv_tier"] = _time.monotonic() - t0
            # the router books replayed=0 when the chain COVERED the
            # stream — but the fallback outcome is only known HERE, so
            # a covered-but-failed fault-in reconciles its real replay
            # cost into the resume counters from the replica side
            self._reconcile_tier_replay(tier, r["prompt"], resume_from, committed)
        if resume_from is None:
            # bursts ride ONE stream item each (TokenChunk; the router
            # flattens): a speculative engine commits up to k+1 tokens
            # per verify step, and per-item stream overhead must be paid
            # per step, not per token, for that win to reach clients
            for chunk in self.engine.generate_chunks(
                r["prompt"],
                max_new_tokens=r.get("max_new_tokens"),
                temperature=float(r.get("temperature", 0.0)),
                priority=int(r.get("priority", 0)),
                eos_token=r.get("eos_token"),
                request_id=r.get("request_id"),
                seed=r.get("seed"),
                tenant_class=tenant_class,
                ledger_stages=ledger_stages,
                record_slo=record_slo,
                speculative=r.get("speculative"),
            ):
                yield TokenChunk(chunk)
            return
        seq = int(resume_from)
        max_new = r.get("max_new_tokens")
        if max_new is None:
            max_new = self.engine.engine_cfg.max_new_tokens_default
        # the cap the ORIGINAL run actually obeyed: the engine clamps
        # max_new_tokens to the context room (max_seq_len - prompt), so
        # a room-clamped stream ends early — resume math must use the
        # clamped cap, or a death exactly after the last clamped token
        # would resubmit with remaining>0 and a full-context prompt,
        # raising "prompt >= max_seq_len" instead of closing cleanly
        orig_prompt_len = len(r["prompt"]) - seq
        effective_cap = min(
            int(max_new), max(0, self.engine.cfg.max_seq_len - orig_prompt_len)
        )
        remaining = effective_cap - seq
        if remaining <= 0:
            # the whole (clamped) budget was delivered before the
            # failover: the resume covers only the end-of-stream signal
            return
        eos = r.get("eos_token")
        if eos is not None and seq > 0 and r["prompt"][-1] == eos:
            # the stream already ENDED at this EOS — it was delivered,
            # then the replica died before the end-of-stream signal. The
            # engine's EOS check applies only to SAMPLED tokens, so
            # decoding past the replayed EOS would emit tokens an
            # undisturbed run never produced.
            return
        for chunk in self.engine.generate_chunks(
            r["prompt"],
            max_new_tokens=remaining,
            temperature=float(r.get("temperature", 0.0)),
            priority=int(r.get("priority", 0)),
            eos_token=r.get("eos_token"),
            request_id=r.get("request_id"),
            seed=r.get("seed"),
            tenant_class=tenant_class,
            ledger_stages=ledger_stages,
            record_slo=record_slo,
            speculative=r.get("speculative"),
        ):
            yield TokenChunk((seq + i, tok) for i, tok in enumerate(chunk))
            seq += len(chunk)

    def __call__(self, request) -> Dict[str, Any]:
        """Non-streaming: returns the full generation in one reply."""
        return {"tokens": [t for chunk in self.generate(request) for t in chunk]}

    # -- disaggregated prefill/decode (inference/kv_transfer.py) ----------
    def prefill_export(self, request) -> Optional[Dict[str, Any]]:
        """Prefill-pool entry of the disaggregated two-stage dispatch:
        run ONLY the prompt's prefill (no token sampled), publish the
        gathered KV blocks through the local daemon's store, and return
        the migration descriptor the router attaches to the decode
        dispatch. Returns None when the prompt spans no full block —
        nothing worth migrating. Idempotent in effect: a retried export
        publishes a fresh segment; unconsumed ones are TTL-reaped."""
        from ray_tpu.inference import kv_transfer

        r = self._parse(request)
        payload = self.engine.prefill_kv(
            r["prompt"],
            priority=int(r.get("priority", 0)),
            request_id=r.get("request_id"),
        )
        if payload is None:
            return None
        return kv_transfer.publish(payload)

    def _import_kv(self, desc: Dict[str, Any], prompt) -> bool:
        """Decode-pool half: fetch the descriptor's payload (zero-copy
        pull path, digest-before-attach) and scatter it into this
        engine's cache + radix index. Failure ladder: every exception is
        swallowed into a counted fallback — the caller proceeds with a
        plain prefill."""
        from ray_tpu.inference import kv_transfer

        eng = self.engine
        try:
            shape = tuple(desc.get("shape") or ())
            cache_k = eng.runner.cache["k"]  # [L, N, bs, n_kv, hd]
            expect = (
                2, cache_k.shape[0], None, cache_k.shape[2],
                cache_k.shape[3], cache_k.shape[4],
            )
            if (
                len(shape) != 6
                or int(desc.get("block_size") or 0) != eng.blocks.block_size
                or any(e is not None and s != e for s, e in zip(shape, expect))
                or str(desc.get("dtype")) != str(cache_k.dtype)
            ):
                kv_transfer.count_failure("shape")
                kv_transfer.count_fallback("shape_mismatch")
                return False
            from ray_tpu.core.config import GLOBAL_CONFIG

            fetched = kv_transfer.fetch(
                desc, timeout_s=GLOBAL_CONFIG.serve_disagg_handoff_timeout_s
            )
            try:
                covered = eng.import_kv_blocks(
                    [int(t) for t in prompt[: int(desc["tokens"])]],
                    fetched.array,
                )
            finally:
                fetched.close()
            return covered > 0
        except kv_transfer.KvTransferError:
            kv_transfer.count_fallback("transfer")
            return False
        except Exception:  # noqa: BLE001 — migration must never fail a stream
            kv_transfer.count_failure("import")
            kv_transfer.count_fallback("import")
            return False

    def _import_tier(self, spec: Dict[str, Any], prompt) -> int:
        """Cluster KV-tier consumer: fault the router-attached prefix
        blocks in (zero-copy pull, digest-before-attach, keep_source —
        tier reads never consume the entry) and commit them into this
        engine's cache. ``spec`` is ``{"blocks": [[digest_hex, desc],
        ...]}`` — a consecutive root-anchored chain the router matched
        against the request's tokens, so a fetched block's KV provably
        belongs to exactly that token prefix (chain-digest keying).

        Counted fallback ladder, longest-valid-prefix semantics: the
        first block that fails STOPS the chain (later blocks would be
        unreachable in the radix index anyway) and everything already
        fetched still commits — partial warmth beats none. Returns the
        number of tokens committed; 0 means the caller proceeds on pure
        prefix replay / cold prefill, byte-exact either way."""
        import os
        import signal

        from ray_tpu.core.config import GLOBAL_CONFIG
        from ray_tpu.inference import kv_transfer
        from ray_tpu.observability import rpc_metrics

        eng = self.engine
        blocks = list(spec.get("blocks") or ())
        if not blocks:
            return 0
        bs = eng.blocks.block_size
        cache_k = eng.runner.cache["k"]  # [L, N, bs, n_kv, hd]
        expect = (
            2, cache_k.shape[0], 1, cache_k.shape[2],
            cache_k.shape[3], cache_k.shape[4],
        )
        fetched: List[Any] = []
        try:
            for _digest_hex, desc in blocks:
                if str(desc.get("tier_ns") or "") != getattr(eng, "_tier_ns", ""):
                    # model-identity gate: the chain digest names the
                    # TOKENS, not the weights that computed the KV — a
                    # descriptor published under another deployment's
                    # namespace passes every shape/dtype check (same
                    # architecture!) yet holds a different model's KV
                    rpc_metrics.KV_TIER_FALLBACKS.inc(
                        labels={"reason": "namespace"}
                    )
                    break
                shape = tuple(desc.get("shape") or ())
                if (
                    len(shape) != 6
                    or int(desc.get("block_size") or 0) != bs
                    or any(s != e for s, e in zip(shape, expect))
                    or str(desc.get("dtype")) != str(cache_k.dtype)
                ):
                    rpc_metrics.KV_TIER_FALLBACKS.inc(
                        labels={"reason": "shape"}
                    )
                    break
                try:
                    payload = kv_transfer.tier_fetch(
                        desc,
                        timeout_s=(
                            GLOBAL_CONFIG.serve_disagg_handoff_timeout_s
                        ),
                    )
                except kv_transfer.KvTransferError as e:
                    msg = str(e)
                    reason = (
                        "missing" if "missing" in msg
                        else "digest" if "digest" in msg
                        else "transfer"
                    )
                    rpc_metrics.KV_TIER_FALLBACKS.inc(
                        labels={"reason": reason}
                    )
                    break
                fetched.append(payload)
            if not fetched:
                return 0
            verdict = kv_transfer.consult_tier_chaos("migration")
            if verdict is not None and verdict[0] == "kill_mid_migration":
                # die exactly like a replica lost mid-migration: blocks
                # fetched, nothing committed, process gone without a
                # goodbye. The router's resume machinery is the fallback
                # rung (STREAM_RESUMES counts it); the tier entries
                # survive in their holder daemons for the next attempt.
                os.kill(os.getpid(), signal.SIGKILL)
            import numpy as _np

            kv = (
                fetched[0].array
                if len(fetched) == 1
                else _np.concatenate([f.array for f in fetched], axis=2)
            )
            covered = len(fetched) * bs
            n = eng.import_kv_blocks(
                [int(t) for t in prompt[:covered]], kv
            )
            if n > 0:
                rpc_metrics.KV_TIER_HITS.inc(n // bs)
                rpc_metrics.KV_TIER_BYTES.inc(
                    sum(int(f.array.nbytes) for f in fetched),
                    labels={"direction": "fault_in"},
                )
            return int(n)
        except Exception:  # noqa: BLE001 — fault-in must never fail a stream
            rpc_metrics.KV_TIER_FALLBACKS.inc(labels={"reason": "import"})
            return 0
        finally:
            for f in fetched:
                f.close()

    def _reconcile_tier_replay(
        self, spec: Optional[Dict[str, Any]], prompt, resume_from, committed: int
    ) -> None:
        """Replay accounting for RESUME attempts: the router books
        ``replayed=0`` when the attached chain COVERS the stream,
        trusting the fault-in — but only this side knows whether it
        actually landed. When a covered chain commits short (fallback
        ladder: missing holder, digest mismatch, import failure), the
        positions the router assumed warm get re-prefilled here, and the
        delivered-region share of that work is real replay — book the
        shortfall into the same sinks the router uses so covered-but-
        failed fault-ins stop undercounting replay."""
        try:
            seq = int(resume_from or 0)
            if seq <= 0 or not spec:
                return
            n_blocks = len(spec.get("blocks") or ())
            tokens = int(spec.get("tokens") or 0)
            if n_blocks <= 0 or tokens <= 0:
                return
            bs = tokens // n_blocks
            prompt_len = len(prompt)
            if tokens < prompt_len - bs:
                return  # not covered: the router counted the replay
            # positions assumed warm but re-prefilled, clipped to the
            # delivered region (re-prefilling the ORIGINAL prompt is
            # prompt work any attempt-0 request pays too, not replay)
            owed = max(0, tokens - max(int(committed), prompt_len - seq))
            if owed <= 0:
                return
            from ray_tpu.observability import rpc_metrics
            from ray_tpu.observability.slo import slo_metrics

            rpc_metrics.STREAM_RESUME_REPLAY_TOKENS.inc(owed)
            slo_metrics()["fault"].inc(
                owed,
                labels={
                    "deployment": self.engine.slo_deployment,
                    "reason": "resume_replay",
                },
            )
        except Exception:  # noqa: BLE001 — accounting never fails a stream
            pass

    def cancel(self, request_id: str) -> bool:
        """Cancel a queued/running request by id; frees its KV blocks.
        The serve stream-close path usually beats callers to it (an
        abandoned stream cancels its producer task, which closes the
        generator and cancels the engine request) — this is the explicit
        escape hatch for callers that tracked only the request id."""
        return self.engine.cancel(str(request_id))

    # -- introspection ----------------------------------------------------
    def set_deployment_name(self, name: str) -> None:
        """serve/replica.py hook: stamps the deployment label onto the
        engine's SLO histograms/counters before any request arrives."""
        self.engine.set_deployment_name(name)

    def slo_snapshot(self) -> Dict[str, Any]:
        """SLO-ledger dump for ``serve.slo_report()``: this process's
        latency histograms + flight recorder, plus the engine's intake
        books (exact conservation: submitted == finished + failed +
        cancelled + in-flight)."""
        return self.engine.slo_snapshot()

    def engine_stats(self) -> Dict[str, Any]:
        return self.engine.stats()

    def routing_stats(self) -> Dict[str, Any]:
        """Load + prefix-digest gossip consumed by the serve router's
        cache-affinity scoring. The presence of this method is what opts
        a deployment's replicas into the gossip reporter
        (``serve/replica.py``) — plain deployments never pay for it."""
        return self.engine.routing_stats()

    def metrics_address(self) -> Optional[str]:
        if self._metrics_server is None:
            return None
        return f"{self._metrics_server.host}:{self._metrics_server.port}"

    def begin_drain(
        self, grace_s: Optional[float] = None, migrate: bool = False
    ) -> None:
        """Test/ops hook: drain without a node event. ``migrate=True``
        (tier-enabled engines only) additionally hands every in-flight
        decode's FULL KV — prompt plus generated — to the tier and fails
        the requests with the resumable migration marker, so the router
        moves each stream to a survivor that admits it as tier hits:
        live decode migration instead of drain-then-replay."""
        self.engine.begin_drain(grace_s, migrate=migrate)

    def check_health(self) -> bool:
        """Polled by the serve controller (replica.health): False once
        the engine's step loop is dead or wedged — the signal that gets
        a stalled replica proactively restarted (engine.healthy())."""
        return self.engine.healthy()

    def testing_arm_replica_chaos(self, spec: str, seed: int) -> int:
        """Test hook: install a ReplicaFaultPlan on THIS replica only
        (the env/config plan arms every replica including controller
        replacements — surgical tests target one). Returns the seed."""
        from ray_tpu.util.chaos import ReplicaFaultPlan

        self.engine.testing_fault_plan = ReplicaFaultPlan(spec, seed)
        return seed

    def testing_arm_kv_tier_chaos(self, spec: str, seed: int) -> int:
        """Test hook: install a KvTierFaultPlan in THIS replica's
        kv_transfer module only (surgical tier chaos — the env plan
        would arm every process including controller replacements).
        Returns the seed for the repro line."""
        from ray_tpu.inference import kv_transfer
        from ray_tpu.util.chaos import KvTierFaultPlan

        kv_transfer.testing_tier_plan = KvTierFaultPlan(spec, seed)
        return seed

    def __del__(self):
        try:
            self.engine.stop()
            if self._metrics_server is not None:
                self._metrics_server.stop()
        except Exception:
            pass


def llm_deployment(
    model_cfg=None,
    *,
    engine: Any = None,
    name: str = "llm",
    num_replicas: int = 1,
    max_concurrent_queries: int = 32,
    ray_actor_options: Optional[Dict[str, Any]] = None,
    route_prefix: Optional[str] = "/llm",
    seed: int = 0,
    autoscaling_config=None,
    version: Optional[str] = None,
    kv_tier: bool = False,
    disaggregated: bool = False,
    prefill_replicas: int = 1,
    decode_replicas: Optional[int] = None,
    prefill_autoscaling_config=None,
    prefill_actor_options: Optional[Dict[str, Any]] = None,
):
    """Build a Serve deployment serving ``model_cfg`` through a
    continuous-batching engine (the ``serve.llm`` entry point).

    ``serve.run(llm_deployment(cfg).bind())`` → DeploymentHandle whose
    ``stream(request, _method="generate")`` yields tokens and whose
    ``remote(request)`` returns the whole generation. ``num_replicas``
    scales out: each replica hosts its own engine (same ``seed`` → same
    params → identical generations), the router scores replicas by
    outstanding tokens blended with prefix-cache affinity, and
    ``autoscaling_config`` reacts to serve ongoing counts PLUS the
    engines' gossiped admission-queue depth. Pin ``version`` to make a
    num_replicas redeploy an in-place scale instead of a rolling
    replacement (model code rarely changes between scale events; a
    fresh replica warmup per scale step would).

    ``disaggregated=True`` splits prefill from decode onto two replica
    pools (README "Disaggregated serving"): a sibling
    ``{name}-prefill`` deployment (``prefill_replicas`` /
    ``prefill_autoscaling_config`` / ``prefill_actor_options``) computes
    prompt KV and exports it over the zero-copy data plane; the decode
    pool (``decode_replicas``, default ``num_replicas``) imports the
    blocks as prefix-cache hits and streams from a 1-token tail
    prefill. ``serve.run(dep.bind())`` deploys BOTH pools; the returned
    handle routes exactly as before (the two-stage dispatch lives in
    the router, keyed off the deployment's ``disagg_prefill`` meta).
    Both engines get ``kv_transfer_enabled`` forced on so migrations
    never recompile. Handoff failures at every rung degrade to plain
    single-replica generation — ``disaggregated`` changes the cost
    profile, never the token stream (deterministic continuation makes
    the handoff byte-exact by construction).

    ``kv_tier=True`` opts every replica into the cluster-wide KV prefix
    tier (README "KV prefix tier"): engines write popular full prefix
    blocks back into daemon-owned tier storage and advertise them
    through the routing gossip, replicas fault advertised prefixes in
    over the zero-copy pull path, and mid-stream failovers resume as
    tier hits instead of replayed prefill. Forces
    ``kv_transfer_enabled`` too (the tier rides the same data plane).
    Off by default: tier write-back warms gather/scatter programs and
    changes the warmup compile set."""
    from ray_tpu import serve

    if kv_tier:
        import dataclasses as _dc

        from ray_tpu.inference.engine import EngineConfig as _EC

        engine = _dc.replace(
            engine or _EC(), kv_transfer_enabled=True, kv_tier_enabled=True
        )

    if not disaggregated:
        dep = serve.deployment(
            name=name,
            num_replicas=num_replicas,
            max_concurrent_queries=max_concurrent_queries,
            ray_actor_options=ray_actor_options,
            route_prefix=route_prefix,
            autoscaling_config=autoscaling_config,
            version=version,
        )(LLMServer)

        class _BoundDeployment:
            """Deployment with the model/engine config pre-bound."""

            def __init__(self, inner):
                self._inner = inner

            def bind(self, **overrides):
                kwargs = {"seed": seed, **overrides}
                return self._inner.bind(model_cfg, engine, **kwargs)

            def __getattr__(self, item):
                return getattr(self._inner, item)

        return _BoundDeployment(dep)

    import dataclasses

    from ray_tpu.inference.engine import EngineConfig
    from ray_tpu.serve import Deployment, DisaggApplication
    from ray_tpu.serve.config import DeploymentConfig

    ec = engine or EngineConfig()
    if not ec.kv_transfer_enabled:
        ec = dataclasses.replace(ec, kv_transfer_enabled=True)
    prefill_name = f"{name}-prefill"
    decode_dep = Deployment(
        LLMServer,
        name,
        DeploymentConfig(
            num_replicas=decode_replicas or num_replicas,
            max_concurrent_queries=max_concurrent_queries,
            ray_actor_options=dict(ray_actor_options or {}),
            autoscaling=autoscaling_config,
            route_prefix=route_prefix,
            version=version,
            disagg_prefill=prefill_name,
        ),
    )
    prefill_dep = Deployment(
        LLMServer,
        prefill_name,
        DeploymentConfig(
            num_replicas=prefill_replicas,
            max_concurrent_queries=max_concurrent_queries,
            ray_actor_options=dict(
                prefill_actor_options or ray_actor_options or {}
            ),
            autoscaling=prefill_autoscaling_config,
            route_prefix=None,
            version=version,
        ),
    )

    class _BoundDisagg:
        """Two-pool deployment bundle with the configs pre-bound.
        ``bind()`` returns a :class:`serve.DisaggApplication` —
        ``serve.run`` deploys the prefill pool first, then the decode
        pool, and hands back the decode pool's handle."""

        def __init__(self, decode, prefill):
            self._decode = decode
            self._prefill = prefill

        def bind(self, **overrides):
            kwargs = {"seed": seed, **overrides}
            app = DisaggApplication(
                self._decode, (model_cfg, ec), dict(kwargs)
            )
            app.prefill_app = self._prefill.bind(model_cfg, ec, **kwargs)
            return app

        def __getattr__(self, item):
            return getattr(self._decode, item)

    return _BoundDisagg(decode_dep, prefill_dep)
