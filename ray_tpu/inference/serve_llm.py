"""serve.llm-style deployment: the InferenceEngine behind Serve.

``llm_deployment(...)`` returns a regular Serve :class:`Deployment`
whose replicas each host one :class:`LLMServer` (engine + model params).
Tokens stream to callers through the runtime's ``num_returns="streaming"``
generator path and the Serve router/proxy:

    from ray_tpu import serve
    from ray_tpu.inference import EngineConfig, llm_deployment

    dep = llm_deployment(LlamaConfig.tiny(), engine=EngineConfig(num_blocks=64))
    handle = serve.run(dep.bind())
    for tok in handle.stream({"prompt": [3, 7, 11], "max_new_tokens": 16},
                             _method="generate"):
        ...

Per-request deadlines: the caller's timeout propagates onto the task
spec (``core/deadline.py``) and the executing replica re-enters the
budget, so ``LLMServer.generate`` submits with the remaining budget and
the engine stops decoding for callers that already gave up. Node drain:
each replica engine subscribes to the node DRAINING push — a preemption
warning stops admission while Serve unroutes the replica and waits for
the in-flight streams, so clients see completed generations, not errors.

Retry semantics note (the three-tier contract, serve/router.py):
``handle.call``/``router.execute`` are at-least-once across replica
death; ``handle.stream(..., _method="generate")`` is EXACTLY-ONCE —
``generate`` is declared in :attr:`LLMServer.resumable_streams`, so the
router resumes an interrupted stream on a survivor with the prompt
extended by the already-delivered tokens, and deterministic continuation
(engine sampling keyed on ``(seed, position)``) makes the replayed
stream byte-exact. The replay is sound ONLY because generation is
side-effect-free and deterministic given (params seed, request seed,
prompt) — a callable with external side effects must not declare its
streams resumable.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Sequence, Union


class LLMServer:
    """One replica: model params + continuous-batching engine.

    Defined undecorated at module level so cloudpickle exports it by
    reference (see serve/replica.py for the rationale).
    """

    #: streaming methods that are safe to RESUME on another replica after
    #: a mid-stream death (serve router exactly-once token delivery).
    #: Sound here because generation is deterministic (same params seed +
    #: request seed + prompt → same tokens, engine sampling keyed on
    #: (seed, position)) and side-effect-free; anything that writes to
    #: the outside world per item must never appear in this tuple.
    resumable_streams = ("generate",)

    def __init__(
        self,
        model_cfg=None,
        engine_cfg=None,
        *,
        seed: int = 0,
        params=None,
        export_metrics: bool = True,
    ):
        import jax

        from ray_tpu.core.config import GLOBAL_CONFIG
        from ray_tpu.inference.engine import EngineConfig, InferenceEngine
        from ray_tpu.models.llama import LlamaConfig, init_params

        if model_cfg is None:
            model_cfg = LlamaConfig.tiny()
        self.model_cfg = model_cfg
        if params is None:
            params = init_params(model_cfg, jax.random.PRNGKey(seed))
        self.engine = InferenceEngine(
            model_cfg, params, engine_cfg or EngineConfig()
        ).start()
        self.engine.attach_node_drain_listener()
        self._metrics_server = None
        if export_metrics and GLOBAL_CONFIG.metrics_export_enabled:
            # replicas run in worker processes, which don't host the
            # daemon's /metrics endpoint — export the engine gauges from
            # an auto-port server of our own (address via metrics_address)
            from ray_tpu.observability.metrics import MetricsServer

            self._metrics_server = MetricsServer(
                host=GLOBAL_CONFIG.metrics_bind_host, port=0
            )

    # -- request plumbing -------------------------------------------------
    @staticmethod
    def _parse(request) -> Dict[str, Any]:
        if isinstance(request, dict):
            if "prompt" not in request:
                raise ValueError("request dict needs a 'prompt' (list of token ids)")
            return dict(request)
        if isinstance(request, (list, tuple)):
            return {"prompt": list(request)}
        raise TypeError(
            f"request must be a dict or token list, got {type(request).__name__}"
        )

    def generate(self, request) -> Iterator[int]:
        """Streaming entry (call with ``num_returns="streaming"`` /
        ``handle.stream(..., _method="generate")``): yields token ids as
        they decode. Request fields: prompt (required), max_new_tokens,
        temperature, priority, eos_token, request_id, seed, resume_from.

        ``resume_from`` (stamped by the serve router for resumable
        streams; absent for direct callers) switches to seq-numbered
        mode: the prompt carries ``resume_from`` already-delivered
        tokens of an interrupted stream, and items become
        ``(seq, token)`` pairs so the router can suppress replayed
        duplicates at the failover boundary. ``max_new_tokens`` stays
        the ORIGINAL request's cap — the replica subtracts what was
        already delivered, so the client-visible stream length never
        changes across failovers."""
        r = self._parse(request)
        resume_from = r.get("resume_from")
        if resume_from is None:
            yield from self.engine.generate(
                r["prompt"],
                max_new_tokens=r.get("max_new_tokens"),
                temperature=float(r.get("temperature", 0.0)),
                priority=int(r.get("priority", 0)),
                eos_token=r.get("eos_token"),
                request_id=r.get("request_id"),
                seed=r.get("seed"),
            )
            return
        seq = int(resume_from)
        max_new = r.get("max_new_tokens")
        if max_new is None:
            max_new = self.engine.engine_cfg.max_new_tokens_default
        # the cap the ORIGINAL run actually obeyed: the engine clamps
        # max_new_tokens to the context room (max_seq_len - prompt), so
        # a room-clamped stream ends early — resume math must use the
        # clamped cap, or a death exactly after the last clamped token
        # would resubmit with remaining>0 and a full-context prompt,
        # raising "prompt >= max_seq_len" instead of closing cleanly
        orig_prompt_len = len(r["prompt"]) - seq
        effective_cap = min(
            int(max_new), max(0, self.engine.cfg.max_seq_len - orig_prompt_len)
        )
        remaining = effective_cap - seq
        if remaining <= 0:
            # the whole (clamped) budget was delivered before the
            # failover: the resume covers only the end-of-stream signal
            return
        eos = r.get("eos_token")
        if eos is not None and seq > 0 and r["prompt"][-1] == eos:
            # the stream already ENDED at this EOS — it was delivered,
            # then the replica died before the end-of-stream signal. The
            # engine's EOS check applies only to SAMPLED tokens, so
            # decoding past the replayed EOS would emit tokens an
            # undisturbed run never produced.
            return
        for tok in self.engine.generate(
            r["prompt"],
            max_new_tokens=remaining,
            temperature=float(r.get("temperature", 0.0)),
            priority=int(r.get("priority", 0)),
            eos_token=r.get("eos_token"),
            request_id=r.get("request_id"),
            seed=r.get("seed"),
        ):
            yield (seq, tok)
            seq += 1

    def __call__(self, request) -> Dict[str, Any]:
        """Non-streaming: returns the full generation in one reply."""
        return {"tokens": list(self.generate(request))}

    def cancel(self, request_id: str) -> bool:
        """Cancel a queued/running request by id; frees its KV blocks.
        The serve stream-close path usually beats callers to it (an
        abandoned stream cancels its producer task, which closes the
        generator and cancels the engine request) — this is the explicit
        escape hatch for callers that tracked only the request id."""
        return self.engine.cancel(str(request_id))

    # -- introspection ----------------------------------------------------
    def engine_stats(self) -> Dict[str, Any]:
        return self.engine.stats()

    def routing_stats(self) -> Dict[str, Any]:
        """Load + prefix-digest gossip consumed by the serve router's
        cache-affinity scoring. The presence of this method is what opts
        a deployment's replicas into the gossip reporter
        (``serve/replica.py``) — plain deployments never pay for it."""
        return self.engine.routing_stats()

    def metrics_address(self) -> Optional[str]:
        if self._metrics_server is None:
            return None
        return f"{self._metrics_server.host}:{self._metrics_server.port}"

    def begin_drain(self, grace_s: Optional[float] = None) -> None:
        """Test/ops hook: drain without a node event."""
        self.engine.begin_drain(grace_s)

    def check_health(self) -> bool:
        """Polled by the serve controller (replica.health): False once
        the engine's step loop is dead or wedged — the signal that gets
        a stalled replica proactively restarted (engine.healthy())."""
        return self.engine.healthy()

    def testing_arm_replica_chaos(self, spec: str, seed: int) -> int:
        """Test hook: install a ReplicaFaultPlan on THIS replica only
        (the env/config plan arms every replica including controller
        replacements — surgical tests target one). Returns the seed."""
        from ray_tpu.util.chaos import ReplicaFaultPlan

        self.engine.testing_fault_plan = ReplicaFaultPlan(spec, seed)
        return seed

    def __del__(self):
        try:
            self.engine.stop()
            if self._metrics_server is not None:
                self._metrics_server.stop()
        except Exception:
            pass


def llm_deployment(
    model_cfg=None,
    *,
    engine: Any = None,
    name: str = "llm",
    num_replicas: int = 1,
    max_concurrent_queries: int = 32,
    ray_actor_options: Optional[Dict[str, Any]] = None,
    route_prefix: Optional[str] = "/llm",
    seed: int = 0,
    autoscaling_config=None,
    version: Optional[str] = None,
):
    """Build a Serve deployment serving ``model_cfg`` through a
    continuous-batching engine (the ``serve.llm`` entry point).

    ``serve.run(llm_deployment(cfg).bind())`` → DeploymentHandle whose
    ``stream(request, _method="generate")`` yields tokens and whose
    ``remote(request)`` returns the whole generation. ``num_replicas``
    scales out: each replica hosts its own engine (same ``seed`` → same
    params → identical generations), the router scores replicas by
    outstanding tokens blended with prefix-cache affinity, and
    ``autoscaling_config`` reacts to serve ongoing counts PLUS the
    engines' gossiped admission-queue depth. Pin ``version`` to make a
    num_replicas redeploy an in-place scale instead of a rolling
    replacement (model code rarely changes between scale events; a
    fresh replica warmup per scale step would)."""
    from ray_tpu import serve

    dep = serve.deployment(
        name=name,
        num_replicas=num_replicas,
        max_concurrent_queries=max_concurrent_queries,
        ray_actor_options=ray_actor_options,
        route_prefix=route_prefix,
        autoscaling_config=autoscaling_config,
        version=version,
    )(LLMServer)

    class _BoundDeployment:
        """Deployment with the model/engine config pre-bound."""

        def __init__(self, inner):
            self._inner = inner

        def bind(self, **overrides):
            kwargs = {"seed": seed, **overrides}
            return self._inner.bind(model_cfg, engine, **kwargs)

        def __getattr__(self, item):
            return getattr(self._inner, item)

    return _BoundDeployment(dep)
