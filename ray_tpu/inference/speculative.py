"""Speculative decoding: draft proposers for the paged-KV engine.

The engine's decode loop amortizes per-token step overhead by letting a
cheap *proposer* guess k tokens ahead, then verifying all k+1 positions
in ONE bucketed jitted target step (``models.llama.paged_verify_step`` —
chunked-prefill-shaped, all-position logits). Because PR 10's
(request_seed, absolute-position) RNG pins the whole output stream given
(seed, prompt), acceptance is **exact-match**: the target's
deterministically-realized token at each position is computed from the
verify logits with the engine's own sampler, drafts are accepted while
they match it, and the first mismatch position emits the target's token
instead (the "bonus/correction" token) — so every speculative step emits
at least one token and the emitted stream is byte-identical to plain
decode by construction, for greedy AND seeded temperature>0 sampling.
The proposer therefore only affects THROUGHPUT (acceptance rate), never
content: any drafting strategy is sound.

Two proposers:

* :class:`NgramProposer` — model-free prompt-lookup decoding: find the
  most recent previous occurrence of the context's trailing n-gram and
  propose the tokens that followed it. Zero device cost, no extra
  compile footprint; wins exactly on repetitive continuations (code,
  structured text, resumed prefixes).
* :class:`DraftModelProposer` — a scaled-down same-tokenizer draft
  model running greedy decode on its OWN paged runner + block pool.
  Catch-up is incremental: the proposer tracks which token history its
  draft cache actually holds and re-feeds only the diverged tail
  (rejected drafts overwrite in place — the paged layout addresses K/V
  purely by position, so stale slots past the committed context are
  inert until rewritten).

Both expose the same surface the engine drives: ``propose(ctx, k)``,
``release(request_id)``, ``compile_count()`` /
``recompiles_after_warmup()`` for the zero-recompile gate.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from ray_tpu.inference.kv_cache import PagedBlockManager


class NgramProposer:
    """Prompt-lookup drafting: propose the continuation of the most
    recent previous occurrence of the context's trailing n-gram.

    Tries the longest configured n-gram first (stronger evidence) and
    falls back to shorter ones; returns ``[]`` when nothing in the
    context repeats — the engine then runs that slot as plain decode.
    """

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1):
        if min_ngram < 1 or max_ngram < min_ngram:
            raise ValueError("need max_ngram >= min_ngram >= 1")
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram

    def propose(
        self, ctx: Sequence[int], k: int, request_id: str = ""
    ) -> List[int]:
        L = len(ctx)
        if k <= 0 or L < self.min_ngram + 1:
            return []
        for n in range(min(self.max_ngram, L - 1), self.min_ngram - 1, -1):
            pattern = tuple(ctx[L - n :])
            # scan right-to-left for the most recent PRIOR occurrence
            # (the trailing occurrence itself is excluded)
            for i in range(L - n - 1, -1, -1):
                if tuple(ctx[i : i + n]) == pattern:
                    return list(ctx[i + n : i + n + k])
        return []

    def release(self, request_id: str) -> None:  # stateless
        pass

    def compile_count(self) -> int:
        return 0

    def recompiles_after_warmup(self) -> int:
        return 0


class DraftModelProposer:
    """Greedy k-step drafting with a scaled-down model on its own paged
    runner. Per-request draft-cache state is tracked host-side as the
    exact token history whose K/V the draft cache holds; every propose
    call re-feeds only the diverged tail (after a rollback that is the
    rejected drafts' positions, overwritten in place)."""

    def __init__(
        self,
        cfg,
        params,
        *,
        num_blocks: int,
        block_size: int,
        prefill_buckets: Sequence[int],
        decode_buckets: Sequence[int] = (1,),
        cache_dtype=None,
    ):
        from ray_tpu.inference.model_runner import PagedModelRunner

        self.cfg = cfg
        self.runner = PagedModelRunner(
            cfg,
            params,
            num_blocks=num_blocks,
            block_size=block_size,
            prefill_buckets=prefill_buckets,
            decode_buckets=decode_buckets,
            cache_dtype=cache_dtype,
        )
        self.blocks = PagedBlockManager(num_blocks, block_size)
        #: request -> tokens whose K/V the draft cache holds at
        #: positions 0..len-1 (includes stale speculative tails until
        #: the next propose overwrites them)
        self._written: Dict[str, List[int]] = {}

    # -- warmup / compile accounting (ride the engine's gates) ----------
    def warmup(self) -> None:
        self.runner.warmup()

    def mark_warm(self) -> None:
        self.runner.mark_warm()

    def compile_count(self) -> int:
        return self.runner.compile_count()

    def recompiles_after_warmup(self) -> int:
        return self.runner.recompiles_after_warmup()

    # -- drafting -------------------------------------------------------
    def propose(self, ctx: Sequence[int], k: int, request_id: str = "") -> List[int]:
        import numpy as np

        L = len(ctx)
        if k <= 0 or L < 1:
            return []
        k = min(k, self.cfg.max_seq_len - L)
        if k <= 0:
            return []
        rid = request_id or "draft"
        held = self._written.get(rid, [])
        # longest prefix of the draft cache that is still the truth
        p = 0
        limit = min(len(held), L - 1)
        while p < limit and held[p] == ctx[p]:
            p += 1
        # decode writes K/V at positions L-1 .. L+k-2: need L-1+k covered
        if not self.blocks.grow_to(rid, L - 1 + k):
            return []  # draft pool dry: skip speculation this step
        row = self.blocks.table_row(rid, self.runner.max_blocks_per_seq)
        # catch-up prefill of the diverged tail ctx[p:L-1], bucketed
        max_chunk = self.runner.prefill_buckets[-1]
        pos = p
        while pos < L - 1:
            chunk = list(ctx[pos : min(pos + max_chunk, L - 1)])
            self.runner.prefill_chunk(chunk, row, pos)
            pos += len(chunk)
        # greedy draft decode from the last committed token
        drafts: List[int] = []
        tok = int(ctx[L - 1])
        for i in range(k):
            cur = L - 1 + i
            logits = self.runner.decode([tok], [cur], [row], [cur + 1])
            tok = int(np.argmax(logits[0]))
            drafts.append(tok)
        # cache now holds ctx[:L] plus all drafts except the last (whose
        # K/V was never written)
        self._written[rid] = list(ctx[:L]) + drafts[:-1]
        return drafts

    def release(self, request_id: str) -> None:
        rid = request_id or "draft"
        self._written.pop(rid, None)
        self.blocks.free(rid)
