"""ray_tpu.job — job submission: REST API + supervisor actors.

Reference: ``python/ray/dashboard/modules/job/`` — ``JobManager``
(``job_manager.py:59``) spawns a per-job ``JobSupervisor`` actor
(``job_supervisor.py:54``) that runs the entrypoint as a subprocess,
streams its logs, and drives PENDING → RUNNING → SUCCEEDED/FAILED/
STOPPED; clients speak REST via ``JobSubmissionClient`` (``sdk.py:125``).

    from ray_tpu.job import JobSubmissionClient

    client = JobSubmissionClient("http://127.0.0.1:8265")
    job_id = client.submit_job(entrypoint="python my_script.py")
    client.get_job_status(job_id)   # JobStatus.RUNNING ...
    print(client.get_job_logs(job_id))
"""

from ray_tpu.job.manager import JobManager, JobStatus
from ray_tpu.job.sdk import JobSubmissionClient
from ray_tpu.job.server import start_job_server, stop_job_server

__all__ = [
    "JobManager",
    "JobStatus",
    "JobSubmissionClient",
    "start_job_server",
    "stop_job_server",
]
