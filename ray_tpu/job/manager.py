"""JobManager: driver-side orchestration of job supervisors.

Reference: ``dashboard/modules/job/job_manager.py:59`` — allocates
submission ids, spawns the per-job supervisor actor, reads status/logs
(from KV once the supervisor is gone), stops jobs.
"""

from __future__ import annotations

import uuid
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.job.supervisor import (
    JobSupervisor,
    read_job_status,
    read_persisted_logs,
)

_SUPERVISOR_NAME = "_job_supervisor_%s"


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"

    TERMINAL = frozenset({SUCCEEDED, FAILED, STOPPED})


def _derive_cluster_address() -> str:
    """The connected cluster's ``host:cport:dport`` (what entrypoints
    get as RAY_TPU_ADDRESS) — empty for local/in-process backends."""
    try:
        from ray_tpu.core.api import _global_worker

        be = _global_worker().backend
        c, d = getattr(be, "controller", None), getattr(be, "daemon", None)
        if c is not None and d is not None:
            return f"{c.host}:{c.port}:{d.port}"
    except Exception:
        pass
    return ""


class JobManager:
    def __init__(self, cluster_address: str = ""):
        self.cluster_address = cluster_address or _derive_cluster_address()
        # serializes the duplicate-id check against the PENDING write —
        # concurrent REST submits share one manager. (Submits from
        # DIFFERENT processes could still race; a KV compare-and-set
        # would be needed for that.)
        import threading

        self._submit_lock = threading.Lock()

    def submit_job(
        self,
        *,
        entrypoint: str,
        submission_id: Optional[str] = None,
        env: Optional[Dict[str, str]] = None,
        entrypoint_num_retries: int = 0,
        working_dir: Optional[str] = None,
    ) -> str:
        job_id = submission_id or f"raytpu-job-{uuid.uuid4().hex[:10]}"
        # write PENDING synchronously — the supervisor spawn is async and
        # a status poll racing it must see the job, not a 404 (reference:
        # JobManager records the job info row before starting the actor)
        from ray_tpu.job.supervisor import write_job_status

        with self._submit_lock:
            if read_job_status(job_id) is not None:
                raise ValueError(f"job {job_id!r} already exists")
            write_job_status(job_id, entrypoint, JobStatus.PENDING)
        JobSupervisor.options(
            name=_SUPERVISOR_NAME % job_id,
            lifetime="detached",
            num_cpus=0,
        ).remote(
            job_id,
            entrypoint,
            cluster_address=self.cluster_address,
            env=env,
            num_retries=entrypoint_num_retries,
            working_dir=working_dir,
        )
        return job_id

    def _supervisor(self, job_id: str):
        try:
            return ray_tpu.get_actor(_SUPERVISOR_NAME % job_id)
        except Exception:
            return None

    def get_job_status(self, job_id: str) -> Optional[Dict[str, Any]]:
        return read_job_status(job_id)

    def get_job_logs(self, job_id: str) -> str:
        sup = self._supervisor(job_id)
        if sup is not None:
            try:
                return ray_tpu.get(sup.logs.remote(), timeout=30)
            except Exception:
                pass  # supervisor died — fall back to persisted logs
        return read_persisted_logs(job_id) or ""

    def stop_job(self, job_id: str) -> bool:
        sup = self._supervisor(job_id)
        if sup is None:
            return False
        try:
            return ray_tpu.get(sup.stop.remote(), timeout=30)
        except Exception:
            return False

    def list_jobs(self) -> List[Dict[str, Any]]:
        from ray_tpu.core.api import _global_worker

        backend = _global_worker().backend
        keys = backend.kv_keys(b"job:")
        out = []
        for k in keys:
            if k.endswith(b":status"):
                import json

                raw = backend.kv_get(k)
                if raw:
                    out.append(json.loads(raw))
        return sorted(out, key=lambda j: j.get("start_time", 0))

    def delete_job(self, job_id: str) -> bool:
        """Remove a TERMINAL job's records (reference delete semantics)."""
        status = read_job_status(job_id)
        if status is None or status.get("status") not in JobStatus.TERMINAL:
            return False
        from ray_tpu.core.api import _global_worker

        backend = _global_worker().backend
        backend.kv_del(f"job:{job_id}:status".encode())
        backend.kv_del(f"job:{job_id}:logs".encode())
        sup = self._supervisor(job_id)
        if sup is not None:
            try:
                ray_tpu.kill(sup)
            except Exception:
                pass
        return True
