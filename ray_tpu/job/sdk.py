"""JobSubmissionClient: HTTP client for the job REST API.

Reference: ``dashboard/modules/job/sdk.py:125`` (``submit_job``) — the
operator-facing entry: submit an entrypoint over HTTP, poll status,
fetch/tail logs, stop.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from typing import Any, Dict, Iterator, List, Optional


class JobSubmissionClient:
    def __init__(self, address: str = "http://127.0.0.1:8265"):
        self.address = address.rstrip("/")

    def _request(self, method: str, path: str, body: Optional[dict] = None) -> dict:
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            self.address + path,
            data=data,
            method=method,
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=60) as resp:
                return json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            payload = e.read()
            try:
                detail = json.loads(payload).get("error", payload.decode())
            except Exception:
                detail = payload.decode(errors="replace")
            raise RuntimeError(f"{method} {path}: {e.code} {detail}") from None

    def submit_job(
        self,
        *,
        entrypoint: str,
        submission_id: Optional[str] = None,
        env: Optional[Dict[str, str]] = None,
        entrypoint_num_retries: int = 0,
        working_dir: Optional[str] = None,
    ) -> str:
        body: Dict[str, Any] = {"entrypoint": entrypoint}
        if submission_id:
            body["submission_id"] = submission_id
        if env:
            body["env"] = env
        if entrypoint_num_retries:
            body["entrypoint_num_retries"] = entrypoint_num_retries
        if working_dir:
            body["working_dir"] = working_dir
        return self._request("POST", "/api/jobs/", body)["submission_id"]

    def get_job_status(self, job_id: str) -> str:
        return self._request("GET", f"/api/jobs/{job_id}")["status"]

    def get_job_info(self, job_id: str) -> Dict[str, Any]:
        return self._request("GET", f"/api/jobs/{job_id}")

    def get_job_logs(self, job_id: str) -> str:
        return self._request("GET", f"/api/jobs/{job_id}/logs")["logs"]

    def stop_job(self, job_id: str) -> bool:
        return self._request("POST", f"/api/jobs/{job_id}/stop")["stopped"]

    def delete_job(self, job_id: str) -> bool:
        return self._request("DELETE", f"/api/jobs/{job_id}")["deleted"]

    def list_jobs(self) -> List[Dict[str, Any]]:
        return self._request("GET", "/api/jobs/")["jobs"]

    def wait_until_terminal(self, job_id: str, timeout: float = 300.0) -> str:
        deadline = time.monotonic() + timeout
        status = None
        while time.monotonic() < deadline:
            status = self.get_job_status(job_id)
            if status in ("SUCCEEDED", "FAILED", "STOPPED"):
                return status
            time.sleep(0.5)
        raise TimeoutError(f"job {job_id} still {status!r} after {timeout}s")

    def tail_job_logs(self, job_id: str, poll_s: float = 0.5) -> Iterator[str]:
        """Yield log increments until the job reaches a terminal state
        (reference async tail, polled over plain HTTP here)."""
        seen = 0
        while True:
            logs = self.get_job_logs(job_id)
            if len(logs) > seen:
                yield logs[seen:]
                seen = len(logs)
            if self.get_job_status(job_id) in ("SUCCEEDED", "FAILED", "STOPPED"):
                logs = self.get_job_logs(job_id)
                if len(logs) > seen:
                    yield logs[seen:]
                return
            time.sleep(poll_s)
