"""Job REST API: aiohttp server exposing reference-compatible routes.

Reference: ``dashboard/modules/job/job_head.py`` — the dashboard-hosted
REST surface the ``JobSubmissionClient`` speaks:

  POST /api/jobs/                      submit
  GET  /api/jobs/                      list
  GET  /api/jobs/{submission_id}       status
  GET  /api/jobs/{submission_id}/logs  logs
  POST /api/jobs/{submission_id}/stop  stop
  DELETE /api/jobs/{submission_id}     delete

Runs on a thread inside a connected driver process (mirrors
``serve/proxy.py``), or standalone: ``python -m ray_tpu.job.server
--address <cluster> --port 8265``.
"""

from __future__ import annotations

import asyncio
import json
import threading
from typing import Optional

from ray_tpu.job.manager import JobManager

_server = None
_lock = threading.Lock()


class JobServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 8265, cluster_address: str = ""):
        self.manager = JobManager(cluster_address)
        self.host = host
        self.port = port
        self._started = threading.Event()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread = threading.Thread(target=self._serve, daemon=True, name="job-server")
        self._thread.start()
        if not self._started.wait(15):
            raise RuntimeError("job REST server failed to start")

    async def _submit(self, request):
        from aiohttp import web

        try:
            body = json.loads(await request.read() or b"{}")
            entrypoint = body["entrypoint"]
        except (json.JSONDecodeError, KeyError):
            return web.json_response(
                {"error": "body must be JSON with an 'entrypoint'"}, status=400
            )
        loop = asyncio.get_event_loop()
        try:
            job_id = await loop.run_in_executor(
                None,
                lambda: self.manager.submit_job(
                    entrypoint=entrypoint,
                    submission_id=body.get("submission_id"),
                    env=body.get("env"),
                    entrypoint_num_retries=int(body.get("entrypoint_num_retries", 0)),
                    working_dir=body.get("working_dir"),
                ),
            )
        except ValueError as e:
            return web.json_response({"error": str(e)}, status=409)
        return web.json_response({"submission_id": job_id})

    async def _list(self, request):
        from aiohttp import web

        loop = asyncio.get_event_loop()
        jobs = await loop.run_in_executor(None, self.manager.list_jobs)
        return web.json_response({"jobs": jobs})

    async def _status(self, request):
        from aiohttp import web

        job_id = request.match_info["job_id"]
        loop = asyncio.get_event_loop()
        status = await loop.run_in_executor(None, self.manager.get_job_status, job_id)
        if status is None:
            return web.json_response({"error": f"no job {job_id!r}"}, status=404)
        return web.json_response(status)

    async def _logs(self, request):
        from aiohttp import web

        job_id = request.match_info["job_id"]
        loop = asyncio.get_event_loop()
        if await loop.run_in_executor(None, self.manager.get_job_status, job_id) is None:
            return web.json_response({"error": f"no job {job_id!r}"}, status=404)
        logs = await loop.run_in_executor(None, self.manager.get_job_logs, job_id)
        return web.json_response({"logs": logs})

    async def _stop(self, request):
        from aiohttp import web

        job_id = request.match_info["job_id"]
        loop = asyncio.get_event_loop()
        ok = await loop.run_in_executor(None, self.manager.stop_job, job_id)
        return web.json_response({"stopped": ok})

    async def _delete(self, request):
        from aiohttp import web

        job_id = request.match_info["job_id"]
        loop = asyncio.get_event_loop()
        ok = await loop.run_in_executor(None, self.manager.delete_job, job_id)
        status = 200 if ok else 400
        return web.json_response({"deleted": ok}, status=status)

    def _serve(self) -> None:
        from aiohttp import web

        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop
        app = web.Application()
        app.router.add_post("/api/jobs/", self._submit)
        app.router.add_get("/api/jobs/", self._list)
        app.router.add_get("/api/jobs/{job_id}", self._status)
        app.router.add_get("/api/jobs/{job_id}/logs", self._logs)
        app.router.add_post("/api/jobs/{job_id}/stop", self._stop)
        app.router.add_delete("/api/jobs/{job_id}", self._delete)
        runner = web.AppRunner(app)

        async def _start():
            await runner.setup()
            site = web.TCPSite(runner, self.host, self.port)
            await site.start()
            # resolve the real port when started with port=0
            for sock in site._server.sockets:  # type: ignore[union-attr]
                self.port = sock.getsockname()[1]
                break
            self._started.set()

        loop.run_until_complete(_start())
        loop.run_forever()

    def stop(self) -> None:
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)


def start_job_server(host: str = "127.0.0.1", port: int = 8265, cluster_address: str = "") -> JobServer:
    global _server
    with _lock:
        if _server is None:
            _server = JobServer(host, port, cluster_address)
        return _server


def stop_job_server() -> None:
    global _server
    with _lock:
        if _server is not None:
            _server.stop()
            _server = None


def main() -> None:
    import argparse
    import time

    import ray_tpu

    parser = argparse.ArgumentParser()
    parser.add_argument("--address", required=True, help="cluster address host:cport:dport")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8265)
    args = parser.parse_args()
    ray_tpu.init(address=args.address)
    server = start_job_server(args.host, args.port, args.address)
    print(json.dumps({"job_server_port": server.port}), flush=True)
    while True:
        time.sleep(3600)


if __name__ == "__main__":
    main()
