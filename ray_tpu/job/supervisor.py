"""JobSupervisor: a detached actor running one job's entrypoint.

Reference: ``dashboard/modules/job/job_supervisor.py:54`` — the
supervisor subprocess-spawns the entrypoint with the cluster address in
its env, pumps combined stdout/stderr to a log file, publishes status
transitions to the controller KV (so status survives the supervisor),
honors stop requests (SIGTERM → SIGKILL), and retries the entrypoint
``entrypoint_num_retries`` times on nonzero exit.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import threading
import time
from typing import Any, Dict, Optional

import ray_tpu

_STATUS_KEY = "job:%s:status"
_LOGS_KEY = "job:%s:logs"


def _kv():
    from ray_tpu.core.api import _global_worker

    return _global_worker().backend


def read_job_status(job_id: str) -> Optional[Dict[str, Any]]:
    raw = _kv().kv_get((_STATUS_KEY % job_id).encode())
    return json.loads(raw) if raw else None


def read_persisted_logs(job_id: str) -> Optional[str]:
    raw = _kv().kv_get((_LOGS_KEY % job_id).encode())
    return raw.decode(errors="replace") if raw is not None else None


def write_job_status(
    job_id: str, entrypoint: str, status: str, message: str = ""
) -> None:
    """THE status-row writer (shared by manager-submit and supervisor —
    one schema, no drift)."""
    entry = read_job_status(job_id) or {
        "job_id": job_id,
        "entrypoint": entrypoint,
        "start_time": time.time(),
    }
    entry["status"] = status
    entry["message"] = message
    if status in ("SUCCEEDED", "FAILED", "STOPPED"):
        entry["end_time"] = time.time()
    _kv().kv_put((_STATUS_KEY % job_id).encode(), json.dumps(entry).encode())


class _JobSupervisor:
    """One per submitted job; ``lifetime="detached"`` + named
    ``_job_supervisor_{id}`` so SDK/REST find it after the submitting
    driver exits."""

    def __init__(
        self,
        job_id: str,
        entrypoint: str,
        *,
        cluster_address: str = "",
        env: Optional[Dict[str, str]] = None,
        num_retries: int = 0,
        working_dir: Optional[str] = None,
    ):
        self.job_id = job_id
        self.entrypoint = entrypoint
        self.cluster_address = cluster_address
        self.env = dict(env or {})
        self.num_retries = max(0, num_retries)
        self.working_dir = working_dir
        self.log_path = os.path.join(
            "/tmp/ray_tpu_jobs", f"{job_id}.log"
        )
        os.makedirs(os.path.dirname(self.log_path), exist_ok=True)
        self._proc: Optional[subprocess.Popen] = None
        self._stop_requested = False
        # serializes stop() against the run loop's Popen assignment — a
        # stop racing the spawn must either kill the fresh process or be
        # seen by the loop before it spawns (no orphaned 600s entrypoint)
        self._proc_lock = threading.Lock()
        self._set_status("PENDING")
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"job-{job_id}"
        )
        self._thread.start()

    # -- state -----------------------------------------------------------
    def _set_status(self, status: str, message: str = "") -> None:
        write_job_status(self.job_id, self.entrypoint, status, message)

    def _persist_logs(self) -> None:
        """Terminal state: copy the log file into KV so logs outlive
        this actor (the reference streams to GCS-backed files)."""
        try:
            with open(self.log_path, "rb") as f:
                data = f.read()
            _kv().kv_put((_LOGS_KEY % self.job_id).encode(), data[-2_000_000:])
        except OSError:
            pass

    # -- run loop --------------------------------------------------------
    def _run(self) -> None:
        attempts = self.num_retries + 1
        code = -1
        for attempt in range(attempts):
            if self._stop_requested:
                break
            env = dict(os.environ)
            env.update(self.env)
            if self.cluster_address:
                env["RAY_TPU_ADDRESS"] = self.cluster_address
            env["RAY_TPU_JOB_ID"] = self.job_id
            log_f = open(self.log_path, "ab")
            if attempt:
                log_f.write(
                    f"\n--- entrypoint retry {attempt}/{self.num_retries} ---\n".encode()
                )
                log_f.flush()
            self._set_status("RUNNING")
            try:
                with self._proc_lock:
                    if self._stop_requested:
                        log_f.close()
                        break  # stop raced the spawn: never start it
                    self._proc = subprocess.Popen(
                        self.entrypoint,
                        shell=True,
                        stdout=log_f,
                        stderr=subprocess.STDOUT,
                        cwd=self.working_dir or None,
                        env=env,
                        start_new_session=True,  # stop() kills the whole tree
                    )
            except OSError as e:
                log_f.close()
                self._set_status("FAILED", f"failed to spawn entrypoint: {e!r}")
                self._persist_logs()
                return
            code = self._proc.wait()
            log_f.close()
            if self._stop_requested:
                break
            if code == 0:
                self._set_status("SUCCEEDED")
                self._persist_logs()
                return
        if self._stop_requested:
            self._set_status("STOPPED", "stopped by user")
        else:
            self._set_status("FAILED", f"entrypoint exited with code {code}")
        self._persist_logs()

    # -- API -------------------------------------------------------------
    def status(self) -> Dict[str, Any]:
        return read_job_status(self.job_id) or {"status": "PENDING"}

    def logs(self) -> str:
        try:
            with open(self.log_path, "r", errors="replace") as f:
                return f.read()
        except OSError:
            return ""

    def stop(self) -> bool:
        """Request a stop. True = the stop took effect (either a live
        entrypoint was signalled or the run loop will see the flag
        before/without spawning); False only if the job ALREADY reached
        a terminal state."""
        with self._proc_lock:
            already_done = not self._thread.is_alive()
            self._stop_requested = True
            proc = self._proc
        if proc is not None and proc.poll() is None:
            try:
                os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
            except OSError:
                pass

            def _escalate():
                time.sleep(3.0)
                if proc.poll() is None:
                    try:
                        os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
                    except OSError:
                        pass

            threading.Thread(target=_escalate, daemon=True).start()
            return True
        return not already_done

    def done(self) -> bool:
        return not self._thread.is_alive()


JobSupervisor = ray_tpu.remote(_JobSupervisor)
