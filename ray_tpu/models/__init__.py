"""ray_tpu.models — the TPU-native model zoo.

The reference keeps models inside libraries (RLlib catalogs, Train
examples); here the flagship LM family is first-class so Train/Serve/RL
and the benchmarks share one implementation.
"""

from ray_tpu.models.llama import (
    LlamaConfig,
    forward,
    init_params,
    init_sharded,
    logical_axes,
    make_train_step,
    next_token_loss,
    param_count,
    param_shardings,
    partition_rules,
)

__all__ = [
    "LlamaConfig",
    "forward",
    "init_params",
    "init_sharded",
    "logical_axes",
    "make_train_step",
    "next_token_loss",
    "param_count",
    "param_shardings",
    "partition_rules",
]
