"""Llama-family decoder LM, TPU-native (pure JAX + pallas flash attention).

The flagship model path (SURVEY §7 step 7 north star). Design:
  * pure-function model — params are a plain dict pytree; no flax Module
    state to fight GSPMD;
  * every parameter has *logical* axis names (``logical_axes``); a
    ``ShardingRules`` table (``ray_tpu.parallel.sharding``) maps them to
    mesh axes, so DP/FSDP/TP/SP re-parallelization is a table swap;
  * attention is ``ray_tpu.ops.flash_attention`` (pallas on TPU, XLA
    fallback elsewhere), GQA mapped in-kernel (K/V stay at n_kv_heads);
  * bf16-friendly: matmuls in the param dtype, softmax/logits/loss in
    fp32 (MXU wants bf16 inputs + f32 accumulation).

The reference has no JAX model zoo (torch-only, e.g. RLlib models and
Train examples); this is build-new per SURVEY §2.4.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from ray_tpu.ops.attention import flash_attention
from ray_tpu.parallel.sharding import constrain


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    mlp_hidden: int = 11008
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.float32
    #: attention impl: "auto" | "pallas" | "xla" (dense local) or
    #: "ring" | "ulysses" (sequence-parallel over the mesh's seq axis —
    #: pass the mesh to ``forward``/``make_train_step``)
    attention_impl: str = "auto"
    #: >0 turns every MLP block into a MoE FFN with this many experts
    #: (expert dim shards over the ``expert`` mesh axis — see ops/moe.py)
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_loss_coeff: float = 0.01

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @staticmethod
    def llama2_7b(**overrides) -> "LlamaConfig":
        base = dict(
            vocab_size=32000, dim=4096, n_layers=32, n_heads=32,
            n_kv_heads=32, mlp_hidden=11008, max_seq_len=4096,
            dtype=jnp.bfloat16,
        )
        base.update(overrides)
        return LlamaConfig(**base)

    @staticmethod
    def tiny(**overrides) -> "LlamaConfig":
        """CI-sized config (dryrun / unit tests)."""
        base = dict(
            vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
            mlp_hidden=128, max_seq_len=64,
        )
        base.update(overrides)
        return LlamaConfig(**base)


# ---------------------------------------------------------------------------
# params + logical sharding axes


def _layer_shapes(cfg: LlamaConfig) -> Dict[str, Tuple[int, ...]]:
    hd = cfg.head_dim
    shapes = {
        "attn_norm": (cfg.dim,),
        "wq": (cfg.dim, cfg.n_heads, hd),
        "wk": (cfg.dim, cfg.n_kv_heads, hd),
        "wv": (cfg.dim, cfg.n_kv_heads, hd),
        "wo": (cfg.n_heads, hd, cfg.dim),
        "mlp_norm": (cfg.dim,),
    }
    if cfg.moe_experts > 0:
        shapes.update(
            {
                "router": (cfg.dim, cfg.moe_experts),
                "w_gate": (cfg.moe_experts, cfg.dim, cfg.mlp_hidden),
                "w_up": (cfg.moe_experts, cfg.dim, cfg.mlp_hidden),
                "w_down": (cfg.moe_experts, cfg.mlp_hidden, cfg.dim),
            }
        )
    else:
        shapes.update(
            {
                "w_gate": (cfg.dim, cfg.mlp_hidden),
                "w_up": (cfg.dim, cfg.mlp_hidden),
                "w_down": (cfg.mlp_hidden, cfg.dim),
            }
        )
    return shapes


def logical_axes(cfg: LlamaConfig) -> Dict[str, Any]:
    """Pytree (same structure as params) of logical-axis-name tuples."""
    layer = {
        "attn_norm": (None,),
        "wq": ("embed", "heads", "head_dim"),
        "wk": ("embed", "kv_heads", "head_dim"),
        "wv": ("embed", "kv_heads", "head_dim"),
        "wo": ("heads", "head_dim", "embed"),
        "mlp_norm": (None,),
    }
    if cfg.moe_experts > 0:
        from ray_tpu.ops.moe import moe_logical_axes

        layer.update(moe_logical_axes())
    else:
        layer.update(
            {
                "w_gate": ("embed", "mlp"),
                "w_up": ("embed", "mlp"),
                "w_down": ("mlp", "embed"),
            }
        )
    return {
        "embed": ("vocab", "embed"),
        "layers": [dict(layer) for _ in range(cfg.n_layers)],
        "final_norm": (None,),
        "lm_head": ("embed", "vocab"),
    }


def init_params(cfg: LlamaConfig, rng: jax.Array) -> Dict[str, Any]:
    # partitionable threefry, same as init_sharded: the legacy lowering
    # produces different values once XLA spatially partitions the RNG,
    # so this is the only mode where the single-chip reference and the
    # sharded init agree for the same seed (see init_sharded's docstring)
    with jax.threefry_partitionable(True):
        return _init_params(cfg, rng)


def _init_params(cfg: LlamaConfig, rng: jax.Array) -> Dict[str, Any]:
    keys = jax.random.split(rng, cfg.n_layers + 2)

    def dense(key, shape, fan_in):
        scale = 1.0 / math.sqrt(fan_in)
        return (jax.random.normal(key, shape, jnp.float32) * scale).astype(cfg.dtype)

    _MOE_PARAMS = ("router", "w_gate", "w_up", "w_down")

    def layer(key):
        shapes = _layer_shapes(cfg)
        ks = jax.random.split(key, len(shapes))
        out = {}
        moe = cfg.moe_experts > 0
        for (name, shape), k in zip(shapes.items(), ks):
            if name.endswith("norm"):
                out[name] = jnp.ones(shape, cfg.dtype)
            elif moe and name == "router":
                # routing logits are precision-sensitive: keep f32
                out[name] = jax.random.normal(k, shape, jnp.float32) / math.sqrt(shape[0])
            elif moe and name in _MOE_PARAMS:
                # (E, fan_in, fan_out): contraction dim is shape[-2]
                out[name] = dense(k, shape, shape[-2])
            else:
                out[name] = dense(k, shape, shape[0] if len(shape) == 2 else cfg.dim)
        return out

    return {
        "embed": dense(keys[0], (cfg.vocab_size, cfg.dim), cfg.dim),
        "layers": [layer(keys[i + 1]) for i in range(cfg.n_layers)],
        "final_norm": jnp.ones((cfg.dim,), cfg.dtype),
        "lm_head": dense(keys[-1], (cfg.dim, cfg.vocab_size), cfg.dim),
    }


def partition_rules(cfg: LlamaConfig, rules) -> list:
    """Ordered ``(regex, PartitionSpec)`` pairs covering every llama
    param — the regex-rule source of truth ``match_partition_rules``
    applies to params, grads, AND optimizer state (optax mu/nu mirror the
    param tree, so the same path suffixes match; scalar leaves like
    adam's ``count`` are skipped by the matcher). Specs derive from the
    ``ShardingRules`` table, so swapping ddp/fsdp/tp re-derives the whole
    set. Overrides go in FRONT (first ``re.search`` hit wins)."""
    sp = rules.spec
    out = [
        # factored second-moment stats (adafactor v_row/v_col) are
        # rank-REDUCED mirrors named after their param — the param's spec
        # cannot apply (and after trailing-None stripping it may even
        # have the right length for the wrong dims), so pin them
        # replicated by NAME, in front of the param rules
        (r"(^|/)v_(row|col)(/|$)", sp((None,))),
        (r"(^|/)embed$", sp(("vocab", "embed"))),
        (r"(attn_norm|mlp_norm|final_norm)$", sp((None,))),
        (r"wq$", sp(("embed", "heads", "head_dim"))),
        (r"(wk|wv)$", sp(("embed", "kv_heads", "head_dim"))),
        (r"wo$", sp(("heads", "head_dim", "embed"))),
        (r"lm_head$", sp(("embed", "vocab"))),
    ]
    if cfg.moe_experts > 0:
        out += [
            (r"router$", sp((None, None))),
            (r"(w_gate|w_up)$", sp(("expert", "embed", "mlp"))),
            (r"w_down$", sp(("expert", "mlp", "embed"))),
        ]
    else:
        out += [
            (r"(w_gate|w_up)$", sp(("embed", "mlp"))),
            (r"w_down$", sp(("mlp", "embed"))),
        ]
    return out


def param_count(cfg: LlamaConfig) -> int:
    shapes = list(_layer_shapes(cfg).values())
    per_layer = sum(math.prod(s) for s in shapes)
    return (
        cfg.vocab_size * cfg.dim * 2  # embed + lm_head
        + per_layer * cfg.n_layers
        + cfg.dim
    )


# ---------------------------------------------------------------------------
# forward


def rms_norm(x, weight, eps: float):
    x32 = x.astype(jnp.float32)
    inv = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (x32 * inv).astype(x.dtype) * weight


def rope_tables(cfg: LlamaConfig, seq_len: int, offset: int = 0):
    hd = cfg.head_dim
    inv_freq = 1.0 / (cfg.rope_theta ** (jnp.arange(0, hd, 2, jnp.float32) / hd))
    pos = jnp.arange(offset, offset + seq_len, dtype=jnp.float32)
    ang = jnp.outer(pos, inv_freq)  # [S, hd/2]
    return jnp.cos(ang), jnp.sin(ang)


def apply_rope(x, cos, sin):
    """x: [B, S, H, hd] — rotate pairs (even, odd)."""
    x1, x2 = x[..., ::2], x[..., 1::2]
    c = cos[None, :, None, :]
    s = sin[None, :, None, :]
    out1 = x1 * c - x2 * s
    out2 = x2 * c + x1 * s
    return jnp.stack([out1, out2], axis=-1).reshape(x.shape).astype(x.dtype)


def _attention_block(cfg: LlamaConfig, p, x, cos, sin, mesh=None, rules=None):
    B, S, _ = x.shape
    h = rms_norm(x, p["attn_norm"], cfg.norm_eps)
    q = jnp.einsum("bsd,dhk->bshk", h, p["wq"])
    k = jnp.einsum("bsd,dhk->bshk", h, p["wk"])
    v = jnp.einsum("bsd,dhk->bshk", h, p["wv"])
    # attention ENTRY pin: q/k/v leave the projection in the head-sharded
    # layout the attention impl expects (ring attention's shard_map specs
    # are exactly these) — without it GSPMD picks per-op and the bwd
    # disagrees with the fwd across the remat boundary
    q = constrain(q, mesh, rules, ("act_batch", "act_seq", "act_heads", None))
    k = constrain(k, mesh, rules, ("act_batch", "act_seq", "act_kv_heads", None))
    v = constrain(v, mesh, rules, ("act_batch", "act_seq", "act_kv_heads", None))
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    rep = cfg.n_heads // cfg.n_kv_heads
    if cfg.attention_impl in ("ring", "ulysses"):
        if mesh is None:
            raise ValueError(
                f"attention_impl={cfg.attention_impl!r} is sequence-parallel: "
                "pass the mesh to forward()/make_train_step()"
            )
        from ray_tpu.ops.ring_attention import (
            ring_attention_sharded,
            ulysses_attention_sharded,
        )
        from ray_tpu.parallel.mesh import TENSOR

        # [B, S, H, hd] → [B, H, S, hd]; K/V stay at n_kv_heads — the
        # seq-parallel impls rotate/exchange the small GQA heads and
        # repeat locally, keeping collective volume at 1/rep.
        qt, kt, vt = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
        tensor_size = dict(zip(mesh.axis_names, mesh.devices.shape)).get(TENSOR, 1)
        if rep > 1 and cfg.n_kv_heads % tensor_size != 0:
            # Too few KV heads for the tensor axis: pre-repeat (rare).
            kt = jnp.repeat(kt, rep, axis=1)
            vt = jnp.repeat(vt, rep, axis=1)
            rep = 1
        if cfg.attention_impl == "ring":
            o = ring_attention_sharded(qt, kt, vt, mesh, causal=True, kv_repeat=rep)
        else:
            o = ulysses_attention_sharded(qt, kt, vt, mesh, causal=True)
    else:
        # GQA K/V stay at n_kv_heads — the flash kernel maps q-head →
        # kv-head in its index map, so the repeat never touches HBM.
        qt, kt, vt = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
        o = flash_attention(qt, kt, vt, causal=True, impl=cfg.attention_impl)
    o = o.transpose(0, 2, 1, 3)  # [B, S, H, hd]
    # attention EXIT pin + name: the flash output is the expensive tensor
    # the selective-remat policy saves (recompute elementwise, never the
    # attention itself)
    o = constrain(o, mesh, rules, ("act_batch", "act_seq", "act_heads", None))
    o = checkpoint_name(o, "flash_attn_out")
    out = x + jnp.einsum("bshk,hkd->bsd", o.astype(x.dtype), p["wo"])
    return constrain(out, mesh, rules, ("act_batch", "act_seq", "act_embed"))


def _mlp_block(cfg: LlamaConfig, p, x, mesh=None, rules=None):
    """Dense or MoE FFN. Returns (x, aux_loss)."""
    h = rms_norm(x, p["mlp_norm"], cfg.norm_eps)
    if cfg.moe_experts > 0:
        from ray_tpu.ops.moe import moe_ffn

        # entry/exit pins bracket the expert compute (interior shardings
        # over the ``expert`` axis are moe_ffn's own business) so the
        # MoE FFN keeps the same replicated-residual contract as the
        # dense branch and fwd/bwd agree across the remat boundary
        h = constrain(h, mesh, rules, ("act_batch", "act_seq", "act_embed"))
        out, aux = moe_ffn(
            {k: p[k] for k in ("router", "w_gate", "w_up", "w_down")},
            h,
            top_k=cfg.moe_top_k,
            capacity_factor=cfg.moe_capacity_factor,
        )
        out = x + out
        out = constrain(out, mesh, rules, ("act_batch", "act_seq", "act_embed"))
        return out, aux["aux_loss"]
    gate = jnp.einsum("bsd,dm->bsm", h, p["w_gate"])
    up = jnp.einsum("bsd,dm->bsm", h, p["w_up"])
    # Megatron split: the hidden activation shards over tensor, the
    # down-projection's output all-reduces back to the replicated stream
    gate = constrain(gate, mesh, rules, ("act_batch", "act_seq", "act_mlp"))
    up = constrain(up, mesh, rules, ("act_batch", "act_seq", "act_mlp"))
    out = x + jnp.einsum("bsm,md->bsd", jax.nn.silu(gate) * up, p["w_down"])
    return constrain(out, mesh, rules, ("act_batch", "act_seq", "act_embed")), 0.0


def _remat_policy(remat):
    """``remat``: False (no checkpointing), True/"full" (recompute
    everything — the pre-unified default), or "selective" (save matmul
    outputs and the flash-attention output, recompute only the cheap
    elementwise tail: norms, rope, silu, residual adds). Selective remat
    trades a little memory for skipping the expensive recompute — on the
    stable shardings it is what closes the fwd-vs-fwd+bwd MFU cliff."""
    if remat in (False, None):
        return None, False
    if remat is True or remat == "full":
        return None, True
    if remat == "selective":
        pol = jax.checkpoint_policies.save_from_both_policies(
            jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
            jax.checkpoint_policies.save_only_these_names("flash_attn_out"),
        )
        return pol, True
    raise ValueError(f"remat must be False, True, 'full', or 'selective'; got {remat!r}")


def forward(cfg: LlamaConfig, params, tokens, *, remat=False, mesh=None,
            rules=None, return_aux: bool = False):
    """tokens [B, S] int32 → logits [B, S, vocab] (f32).

    ``mesh`` is required for the sequence-parallel attention impls
    ("ring"/"ulysses"), which shard_map over its ``seq`` axis. With
    ``rules`` (a ``ShardingRules``) AND a mesh, every intermediate is
    pinned via ``with_sharding_constraint`` so fwd and bwd agree on one
    sharding per tensor (the multichip involuntary-remat fix); without
    them the function is bit-identical to the unconstrained reference.
    ``remat``: False | True/"full" | "selective" (see ``_remat_policy``).
    With ``return_aux`` also returns the summed MoE load-balance loss."""
    B, S = tokens.shape
    # Embedding lookup: gathering from a vocab/embed-sharded table leaves
    # the output embed-dim-sharded, and SPMD cannot reshard D-over-fsdp →
    # batch-over-fsdp without a full rematerialization (the exact
    # involuntary-remat warning MULTICHIP_r05 logged). Pin the table
    # REPLICATED for the lookup instead — the all-gather becomes
    # voluntary (ZeRO-3 semantics: params materialize for compute) and
    # the batch/seq constraint on the output is a cheap slice.
    emb = constrain(params["embed"], mesh, rules, (None, None))
    x = emb[tokens]
    x = constrain(x, mesh, rules, ("act_batch", "act_seq", "act_embed"))
    cos, sin = rope_tables(cfg, S)

    def block(carry, p):
        x, aux = carry
        # remat-boundary pin: the carry is the tensor saved at every
        # checkpoint boundary — its fwd sharding must be explicit so the
        # recompute and the bwd accumulation land on the same layout
        x = constrain(x, mesh, rules, ("act_batch", "act_seq", "act_embed"))
        x = _attention_block(cfg, p, x, cos, sin, mesh=mesh, rules=rules)
        x, layer_aux = _mlp_block(cfg, p, x, mesh=mesh, rules=rules)
        return x, aux + layer_aux

    policy, do_remat = _remat_policy(remat)
    if do_remat:
        block = jax.checkpoint(block, policy=policy)
    carry = (x, jnp.zeros((), jnp.float32))
    for p in params["layers"]:
        carry = block(carry, p)
    x, aux = carry
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = jnp.einsum("bsd,dv->bsv", x, params["lm_head"]).astype(jnp.float32)
    logits = constrain(logits, mesh, rules, ("act_batch", "act_seq", "act_vocab"))
    if return_aux:
        return logits, aux
    return logits


def next_token_loss(cfg: LlamaConfig, params, tokens, targets, *, remat=False,
                    mesh=None, rules=None):
    logits, aux = forward(
        cfg, params, tokens, remat=remat, mesh=mesh, rules=rules, return_aux=True
    )
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None].astype(jnp.int32), axis=-1)
    return nll.mean() + cfg.moe_aux_loss_coeff * aux


# ---------------------------------------------------------------------------
# sharded training step


def param_shardings(cfg: LlamaConfig, mesh, rules):
    from jax.sharding import NamedSharding

    return jax.tree_util.tree_map(
        lambda axes: NamedSharding(mesh, rules.spec(axes)),
        logical_axes(cfg),
        is_leaf=lambda x: isinstance(x, tuple),
    )


def batch_sharding(mesh, rules):
    from jax.sharding import NamedSharding

    return NamedSharding(mesh, rules.spec(("batch", "seq")))


def init_sharded(cfg: LlamaConfig, mesh, rules, rng, optimizer=None):
    """Init params (and optimizer state) directly onto the mesh: the init
    computation is jitted with explicit out_shardings so no host has to
    hold a full replica (how 7B+ params fit a v4-32 host).

    Runs under partitionable threefry: the legacy (non-partitionable)
    RNG lowering produces DIFFERENT values when XLA spatially partitions
    it, so the same seed gave different params per rules table — sharded
    init silently diverged from the single-chip reference (measured
    max-abs 0.6 on the tiny config). Partitionable threefry is
    sharding-invariant, so init values match the unsharded path exactly
    whatever the mesh."""
    from jax.sharding import NamedSharding, PartitionSpec

    from ray_tpu.parallel.sharding import match_partition_rules

    shardings = param_shardings(cfg, mesh, rules)
    with jax.threefry_partitionable(True):
        params = jax.jit(partial(init_params, cfg), out_shardings=shardings)(rng)
    if optimizer is None:
        return params
    # Optimizer state inits pinned to the SAME matched rule table the
    # train step constrains it to (mu/nu mirror the params; adam's count
    # stays replicated). Without explicit out_shardings the jitted init
    # hands back single-device state, and the step's first call would
    # emit rule-sharded state — a guaranteed one-step recompile (and on
    # real HBM, a full unsharded optimizer replica). partial() gives
    # THIS call its own jit identity: callers reuse one optax optimizer
    # across meshes (the multichip dryrun inits on two), and a bare
    # ``optimizer.init`` would share one C++ jit cache across them — the
    # PR 6 ``copy_paged_blocks`` cache-pollution class.
    abstract = jax.eval_shape(optimizer.init, params)
    ospecs = match_partition_rules(partition_rules(cfg, rules), abstract)
    oshard = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s),
        ospecs,
        is_leaf=lambda x: isinstance(x, PartitionSpec),
    )
    opt_state = jax.jit(partial(optimizer.init), out_shardings=oshard)(params)
    return params, opt_state


# ---------------------------------------------------------------------------
# paged-KV autoregressive decode (inference engine path)
#
# Layout (vLLM-style, GQA-aware): one K and one V tensor of shape
#   [n_layers, num_blocks, block_size, n_kv_heads, head_dim]
# shared by every request. A request owns a list of block ids (its block
# table row); token position p lives at (blocks[p // block_size],
# p % block_size) in EVERY layer — block ids are layer-agnostic so the
# host-side allocator hands out one id per block_size tokens, not one per
# layer. K/V stay at n_kv_heads (GQA kept compressed in HBM, exactly as
# the flash kernel does): queries are grouped [n_kv, rep] at score time,
# so cache traffic is 1/rep of the repeated layout.
#
# Block id 0 is the NULL block: never allocated, padding positions write
# into it and masked reads from it never reach the softmax. Keeping the
# trash in-band is what lets every step run with fully static shapes.


def init_paged_kv_cache(
    cfg: LlamaConfig, num_blocks: int, block_size: int, dtype=None
) -> Dict[str, jax.Array]:
    """Device-side paged KV cache (zeros; block 0 reserved as null)."""
    shape = (cfg.n_layers, num_blocks, block_size, cfg.n_kv_heads, cfg.head_dim)
    dt = dtype or cfg.dtype
    return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}


def copy_paged_blocks(cache, src, dst):
    """Duplicate whole cache blocks device-side (prefix-cache COW):
    ``src``/``dst`` are [P] int32 block ids; every layer's K/V rows at
    ``dst`` become copies of ``src``. Padding pairs point both ids at
    the null block (0) — writing the null block's own trash back onto
    itself keeps the shape static and the content inert."""
    return {
        "k": cache["k"].at[:, dst].set(cache["k"][:, src]),
        "v": cache["v"].at[:, dst].set(cache["v"][:, src]),
    }


def gather_paged_blocks(cache, blocks):
    """Pull whole cache blocks off the device (KV-cache migration
    export): ``blocks`` is [P] int32 block ids (padded with 0 = null);
    returns one stacked array ``[2, n_layers, P, block_size, n_kv,
    head_dim]`` (K at index 0, V at 1) — the contiguous host window the
    transfer path ships replica→replica. Padding rows carry null-block
    trash the caller slices off host-side."""
    return jnp.stack([cache["k"][:, blocks], cache["v"][:, blocks]])


def scatter_paged_blocks(cache, blocks, kv):
    """Write migrated KV blocks into the device cache (import side of
    KV-cache migration): ``kv`` is the ``gather_paged_blocks`` layout
    ``[2, n_layers, P, block_size, n_kv, head_dim]``. Padding entries
    point at the null block — duplicate index-0 writes land trash on
    trash, keeping the compiled shape static and the content inert."""
    return {
        "k": cache["k"].at[:, blocks].set(kv[0]),
        "v": cache["v"].at[:, blocks].set(kv[1]),
    }


def _rope_at(cfg: LlamaConfig, positions):
    """cos/sin tables at arbitrary int positions: [N] -> ([N, hd/2] x2)."""
    hd = cfg.head_dim
    inv_freq = 1.0 / (cfg.rope_theta ** (jnp.arange(0, hd, 2, jnp.float32) / hd))
    ang = positions.astype(jnp.float32)[:, None] * inv_freq[None, :]
    return jnp.cos(ang), jnp.sin(ang)


def _apply_rope_flat(x, cos, sin):
    """x: [N, H, hd] with per-row position tables [N, hd/2]."""
    x1, x2 = x[..., ::2], x[..., 1::2]
    c = cos[:, None, :]
    s = sin[:, None, :]
    out1 = x1 * c - x2 * s
    out2 = x2 * c + x1 * s
    return jnp.stack([out1, out2], axis=-1).reshape(x.shape).astype(x.dtype)


def _scatter_kv(cache, layer: int, blk, off, k, v):
    """Write per-token K/V into their cache slots. blk/off: [N] int32,
    k/v: [N, n_kv, hd]. Padding rows target the null block — colliding
    trash writes are fine, nothing masked-in ever reads them."""
    return {
        "k": cache["k"].at[layer, blk, off].set(k),
        "v": cache["v"].at[layer, blk, off].set(v),
    }


def paged_prefill_step(
    cfg: LlamaConfig, params, cache, tokens, block_table, ctx_len, true_len
):
    """One prefill chunk for ONE request, fixed shapes.

    tokens: [C] int32 (right-padded chunk), block_table: [M] int32 (padded
    with 0 = null), ctx_len: scalar int32 tokens ALREADY cached (chunked
    prefill: >0 from the second chunk on), true_len: scalar int32 valid
    tokens in this chunk. Writes the chunk's K/V into the cache, attends
    causally over cached-context + chunk, and returns
    ``(cache, logits[vocab])`` for the chunk's last valid token.
    """
    if cfg.moe_experts > 0:
        raise NotImplementedError("paged decode does not support MoE FFNs yet")
    C = tokens.shape[0]
    M = block_table.shape[0]
    bs = cache["k"].shape[2]
    rep = cfg.n_heads // cfg.n_kv_heads
    scale = 1.0 / math.sqrt(cfg.head_dim)

    idx = jnp.arange(C, dtype=jnp.int32)
    pos = ctx_len + idx  # global positions of the chunk's tokens
    valid = idx < true_len
    blk = jnp.where(valid, block_table[jnp.minimum(pos // bs, M - 1)], 0)
    off = pos % bs
    cos, sin = _rope_at(cfg, pos)
    # key j (global position) visible to chunk query i iff j <= ctx_len+i
    key_pos = jnp.arange(M * bs, dtype=jnp.int32)
    mask = key_pos[None, :] <= pos[:, None]  # [C, M*bs]

    x = params["embed"][tokens]  # [C, D]
    for layer, p in enumerate(params["layers"]):
        h = rms_norm(x, p["attn_norm"], cfg.norm_eps)
        q = jnp.einsum("cd,dhk->chk", h, p["wq"])
        k = jnp.einsum("cd,dhk->chk", h, p["wk"])
        v = jnp.einsum("cd,dhk->chk", h, p["wv"])
        q = _apply_rope_flat(q, cos, sin)
        k = _apply_rope_flat(k, cos, sin)
        cache = _scatter_kv(cache, layer, blk, off, k, v)
        # gather AFTER the scatter so the chunk attends to itself
        ks = cache["k"][layer, block_table].reshape(M * bs, cfg.n_kv_heads, -1)
        vs = cache["v"][layer, block_table].reshape(M * bs, cfg.n_kv_heads, -1)
        qg = q.reshape(C, cfg.n_kv_heads, rep, -1)
        s = jnp.einsum("cgrh,sgh->cgrs", qg, ks).astype(jnp.float32) * scale
        s = jnp.where(mask[:, None, None, :], s, -1e30)
        pattn = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("cgrs,sgh->cgrh", pattn.astype(vs.dtype), vs)
        o = o.reshape(C, cfg.n_heads, -1)
        x = x + jnp.einsum("chk,hkd->cd", o.astype(x.dtype), p["wo"])
        hm = rms_norm(x, p["mlp_norm"], cfg.norm_eps)
        gate = jnp.einsum("cd,dm->cm", hm, p["w_gate"])
        up = jnp.einsum("cd,dm->cm", hm, p["w_up"])
        x = x + jnp.einsum("cm,md->cd", jax.nn.silu(gate) * up, p["w_down"])
    last = jnp.maximum(true_len - 1, 0)
    h_last = rms_norm(x[last], params["final_norm"], cfg.norm_eps)
    logits = jnp.einsum("d,dv->v", h_last, params["lm_head"]).astype(jnp.float32)
    return cache, logits


def paged_verify_step(
    cfg: LlamaConfig, params, cache, tokens, block_tables, ctx_lens, true_lens
):
    """Speculative verification for a BATCH of slots, fixed shapes.

    The batched cross between :func:`paged_prefill_step` (a window of C
    positions per sequence, ``key_pos <= pos`` causal masking, K/V
    written as it goes) and :func:`paged_decode_step` (a batch axis over
    independent slots sharing one jit call). tokens: [B, C] int32
    (right-padded verify windows ``[last_committed, d_1..d_k]`` per
    slot), block_tables: [B, M] int32, ctx_lens: [B] int32 tokens
    already cached per slot, true_lens: [B] int32 valid window lengths
    (0 for padding slots: every position masks invalid, writes land on
    the null block). Returns logits for EVERY window position,
    ``(cache, logits [B, C, vocab])``, so the host accepts or rejects
    each drafted token independently — B slots verify k+1 positions each
    in ONE step, where plain decode would spend B*(k+1) batched steps.

    Rejected tail positions leave stale K/V behind; that is safe by
    construction (decode masks on ``key_pos < ctx_len`` and
    prefill/verify on ``key_pos <= pos``, so nothing past the committed
    context is ever read, and re-verification overwrites in place).
    """
    if cfg.moe_experts > 0:
        raise NotImplementedError("paged decode does not support MoE FFNs yet")
    B, C = tokens.shape
    M = block_tables.shape[1]
    bs = cache["k"].shape[2]
    rep = cfg.n_heads // cfg.n_kv_heads
    scale = 1.0 / math.sqrt(cfg.head_dim)

    idx = jnp.arange(C, dtype=jnp.int32)
    pos = ctx_lens[:, None] + idx[None, :]  # [B, C] global positions
    valid = idx[None, :] < true_lens[:, None]
    brange = jnp.arange(B, dtype=jnp.int32)
    blk = jnp.where(
        valid,
        block_tables[brange[:, None], jnp.minimum(pos // bs, M - 1)],
        0,
    )
    off = pos % bs
    flat_pos = pos.reshape(B * C)
    cos, sin = _rope_at(cfg, flat_pos)
    key_pos = jnp.arange(M * bs, dtype=jnp.int32)
    mask = key_pos[None, None, :] <= pos[:, :, None]  # [B, C, M*bs]

    x = params["embed"][tokens]  # [B, C, D]
    for layer, p in enumerate(params["layers"]):
        h = rms_norm(x, p["attn_norm"], cfg.norm_eps)
        q = jnp.einsum("bcd,dhk->bchk", h, p["wq"])
        k = jnp.einsum("bcd,dhk->bchk", h, p["wk"])
        v = jnp.einsum("bcd,dhk->bchk", h, p["wv"])
        hd = q.shape[-1]
        q = _apply_rope_flat(q.reshape(B * C, cfg.n_heads, hd), cos, sin)
        k = _apply_rope_flat(k.reshape(B * C, cfg.n_kv_heads, hd), cos, sin)
        cache = _scatter_kv(
            cache, layer, blk.reshape(B * C), off.reshape(B * C),
            k, v.reshape(B * C, cfg.n_kv_heads, hd),
        )
        # gather AFTER the scatter so each window attends to itself
        ks = cache["k"][layer, block_tables].reshape(B, M * bs, cfg.n_kv_heads, -1)
        vs = cache["v"][layer, block_tables].reshape(B, M * bs, cfg.n_kv_heads, -1)
        qg = q.reshape(B, C, cfg.n_kv_heads, rep, hd)
        s = jnp.einsum("bcgrh,bsgh->bcgrs", qg, ks).astype(jnp.float32) * scale
        s = jnp.where(mask[:, :, None, None, :], s, -1e30)
        pattn = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bcgrs,bsgh->bcgrh", pattn.astype(vs.dtype), vs)
        o = o.reshape(B, C, cfg.n_heads, -1)
        x = x + jnp.einsum("bchk,hkd->bcd", o.astype(x.dtype), p["wo"])
        hm = rms_norm(x, p["mlp_norm"], cfg.norm_eps)
        gate = jnp.einsum("bcd,dm->bcm", hm, p["w_gate"])
        up = jnp.einsum("bcd,dm->bcm", hm, p["w_up"])
        x = x + jnp.einsum("bcm,md->bcd", jax.nn.silu(gate) * up, p["w_down"])
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    return cache, jnp.einsum("bcd,dv->bcv", x, params["lm_head"]).astype(jnp.float32)


def paged_decode_step(
    cfg: LlamaConfig, params, cache, tokens, positions, block_tables, ctx_lens
):
    """One decode step for a BATCH of slots, fixed shapes.

    tokens: [B] int32 (this step's input token per slot), positions: [B]
    int32 (its global position), block_tables: [B, M] int32, ctx_lens: [B]
    int32 (visible context length INCLUDING this token = positions+1 for
    active slots; inactive padding slots carry ctx_len=1 and null blocks
    so the softmax stays finite). Writes K/V, returns
    ``(cache, logits [B, vocab])``.
    """
    if cfg.moe_experts > 0:
        raise NotImplementedError("paged decode does not support MoE FFNs yet")
    B, M = block_tables.shape
    bs = cache["k"].shape[2]
    rep = cfg.n_heads // cfg.n_kv_heads
    scale = 1.0 / math.sqrt(cfg.head_dim)

    brange = jnp.arange(B, dtype=jnp.int32)
    blk = block_tables[brange, jnp.minimum(positions // bs, M - 1)]
    off = positions % bs
    cos, sin = _rope_at(cfg, positions)
    key_pos = jnp.arange(M * bs, dtype=jnp.int32)
    mask = key_pos[None, :] < ctx_lens[:, None]  # [B, M*bs]

    x = params["embed"][tokens]  # [B, D]
    for layer, p in enumerate(params["layers"]):
        h = rms_norm(x, p["attn_norm"], cfg.norm_eps)
        q = jnp.einsum("bd,dhk->bhk", h, p["wq"])
        k = jnp.einsum("bd,dhk->bhk", h, p["wk"])
        v = jnp.einsum("bd,dhk->bhk", h, p["wv"])
        q = _apply_rope_flat(q, cos, sin)
        k = _apply_rope_flat(k, cos, sin)
        cache = _scatter_kv(cache, layer, blk, off, k, v)
        ks = cache["k"][layer, block_tables].reshape(B, M * bs, cfg.n_kv_heads, -1)
        vs = cache["v"][layer, block_tables].reshape(B, M * bs, cfg.n_kv_heads, -1)
        qg = q.reshape(B, cfg.n_kv_heads, rep, -1)
        s = jnp.einsum("bgrh,bsgh->bgrs", qg, ks).astype(jnp.float32) * scale
        s = jnp.where(mask[:, None, None, :], s, -1e30)
        pattn = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bgrs,bsgh->bgrh", pattn.astype(vs.dtype), vs)
        o = o.reshape(B, cfg.n_heads, -1)
        x = x + jnp.einsum("bhk,hkd->bd", o.astype(x.dtype), p["wo"])
        hm = rms_norm(x, p["mlp_norm"], cfg.norm_eps)
        gate = jnp.einsum("bd,dm->bm", hm, p["w_gate"])
        up = jnp.einsum("bd,dm->bm", hm, p["w_up"])
        x = x + jnp.einsum("bm,md->bd", jax.nn.silu(gate) * up, p["w_down"])
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    return cache, jnp.einsum("bd,dv->bv", x, params["lm_head"]).astype(jnp.float32)


def make_train_step(cfg: LlamaConfig, optimizer, *, remat=False, donate: bool = True,
                    mesh=None, rules=None):
    """Returns jitted ``step((params, opt_state), batch) → (state, loss)``.

    Gradient reduction over data/fsdp axes is inserted by GSPMD from the
    input shardings — there is no hand-written psum (scaling-book recipe:
    annotate, compile, let XLA place collectives on ICI).

    With ``rules`` (a ``ShardingRules``) and ``mesh``, the UNIFIED
    named-sharding path engages: params, grads, optimizer updates, and
    optimizer state are all pinned to the ONE spec table
    (``partition_rules`` + ``match_partition_rules``), and the forward
    pins its intermediates — fwd, bwd, and the optimizer update agree on
    every tensor, so the multichip compile has zero involuntary
    rematerializations. Without ``rules`` the step is the legacy
    unconstrained one (``mesh`` alone is still needed for the
    sequence-parallel attention impls). ``remat``: False | True/"full" |
    "selective" (save dots + flash outputs, recompute the elementwise
    tail)."""
    import optax

    from ray_tpu.parallel.sharding import constrain_tree

    prules = partition_rules(cfg, rules) if rules is not None else None
    act = rules if mesh is not None else None

    def step(state, batch):
        params, opt_state = state
        params = constrain_tree(params, mesh, prules)
        tokens = constrain(batch["tokens"], mesh, act, ("act_batch", "act_seq"))
        targets = constrain(batch["targets"], mesh, act, ("act_batch", "act_seq"))
        loss, grads = jax.value_and_grad(
            lambda p: next_token_loss(
                cfg, p, tokens, targets, remat=remat, mesh=mesh, rules=act
            )
        )(params)
        # grad → optimizer handoff: grads carry the params' specs (one
        # table), so adamw's elementwise update never repartitions
        grads = constrain_tree(grads, mesh, prules)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        updates = constrain_tree(updates, mesh, prules)
        opt_state = constrain_tree(opt_state, mesh, prules)
        params = optax.apply_updates(params, updates)
        params = constrain_tree(params, mesh, prules)
        return (params, opt_state), loss

    return jax.jit(step, donate_argnums=(0,) if donate else ())
