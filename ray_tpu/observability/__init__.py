"""Observability: task timeline / profiling events.

Reference: ``python/ray/_private/profiling.py`` + the task-event pipeline
(``core_worker/task_event_buffer.h`` → ``gcs_server/gcs_task_manager.h``).
Redesign: a per-process lock-free-ish ring buffer of profile events
(``record_event``), aggregated on demand into a chrome://tracing JSON dump
(``dump_timeline``). Worker processes ship their buffers to the driver via
the controller KV on exit; in-process events are always available.
"""

from ray_tpu.observability.timeline import (
    ProfileEvent,
    dump_timeline,
    profile,
    record_event,
    timeline_events,
)

__all__ = [
    "ProfileEvent",
    "dump_timeline",
    "profile",
    "record_event",
    "timeline_events",
    "tracing",
]


def __getattr__(name: str):
    # lazy: tracing pulls in core.config/ids — load on first touch
    if name == "tracing":
        import importlib

        return importlib.import_module("ray_tpu.observability.tracing")
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
