"""Event-loop instrumentation + stall watchdog (the hang-defense core).

Reference: ``src/ray/common/event_stats.h`` — every asio handler in the
reference runtime is wrapped with queueing/run timing, and the stats are
dumped into debug state so a wedged process self-diagnoses. This module
is the asyncio equivalent, plus the piece the reference keeps separate
(``GcsHealthCheckManager``-style liveness) folded into the same layer:

* :class:`EventStats` — per-process registry of per-handler stats
  (call count, queueing delay, run latency, max run latency), exported
  through ``observability/metrics.py`` as Prometheus series.
* :class:`LoopMonitor` — a heartbeat coroutine on one asyncio loop plus
  a watchdog *thread* that notices when the heartbeat stops. A loop
  stalled past ``event_loop_stall_threshold_s`` gets every thread's
  stack plus the loop's pending asyncio task names dumped to the log
  (faulthandler-style), so "the suite wedged" becomes "handler X blocked
  in frame Y". In test mode (``watchdog_abort_after_s > 0``) a stall
  that persists hard-aborts the process — a crashed test names its
  killer; a frozen one wedges the whole suite.

The watchdog runs OFF the loop it guards (a stalled loop cannot run its
own diagnostics) and keeps no strong refs to handlers, so installing it
costs one timer wakeup per ``event_loop_tick_s``.
"""

from __future__ import annotations

import asyncio
import logging
import os
import sys
import threading
import time
import traceback
from typing import Callable, Dict, List, Optional

from ray_tpu.core.config import GLOBAL_CONFIG

logger = logging.getLogger(__name__)

#: exit code for a watchdog hard-abort (distinct from crashes/SIGKILL so
#: the reaping layer can attribute the death)
WATCHDOG_ABORT_EXIT_CODE = 70

#: process-local hard-abort override: a test DRIVER (pytest) sets this so
#: its own loop stalls dump-but-never-abort — killing the driver kills
#: the whole suite, and the per-test faulthandler timeout already bounds
#: driver wedges. Spawned runtime processes (which don't run conftest)
#: keep the abort. Config (``watchdog_abort_after_s``) can't express
#: this: the driver serializes its config into every child it spawns.
ABORT_DISABLED_IN_PROCESS = False


class _HandlerStats:
    __slots__ = ("count", "queue_total_s", "run_total_s", "run_max_s")

    def __init__(self):
        self.count = 0
        self.queue_total_s = 0.0
        self.run_total_s = 0.0
        self.run_max_s = 0.0


class EventStats:
    """Per-process handler timing registry (``event_stats.h`` analogue)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._handlers: Dict[str, _HandlerStats] = {}
        self._metrics_registered = False

    def record(self, handler: str, queue_s: float, run_s: float) -> None:
        with self._lock:
            st = self._handlers.get(handler)
            if st is None:
                st = self._handlers[handler] = _HandlerStats()
            st.count += 1
            st.queue_total_s += max(0.0, queue_s)
            st.run_total_s += max(0.0, run_s)
            if run_s > st.run_max_s:
                st.run_max_s = run_s

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {
                name: {
                    "count": st.count,
                    "queue_total_s": st.queue_total_s,
                    "run_total_s": st.run_total_s,
                    "run_max_s": st.run_max_s,
                }
                for name, st in self._handlers.items()
            }

    def ensure_metrics(self) -> None:
        """Register Prometheus series lazily (first instrumented handler)
        so importing this module never touches the metrics registry."""
        with self._lock:
            if self._metrics_registered:
                return
            self._metrics_registered = True
        from ray_tpu.observability.metrics import Counter, Gauge, on_collect

        c_calls = Counter(
            "raytpu_handler_calls_total", "instrumented handler invocations", ("handler",)
        )
        g_queue = Gauge(
            "raytpu_handler_queue_seconds_total",
            "cumulative handler queueing delay (enqueue to run)",
            ("handler",),
        )
        g_run = Gauge(
            "raytpu_handler_run_seconds_total", "cumulative handler run time", ("handler",)
        )
        g_max = Gauge(
            "raytpu_handler_run_max_seconds", "max single-invocation run time", ("handler",)
        )
        seen_counts: Dict[str, float] = {}

        def sample() -> None:
            for name, st in self.snapshot().items():
                labels = {"handler": name}
                prev = seen_counts.get(name, 0.0)
                if st["count"] > prev:
                    c_calls.inc(st["count"] - prev, labels)
                    seen_counts[name] = st["count"]
                g_queue.set(st["queue_total_s"], labels)
                g_run.set(st["run_total_s"], labels)
                g_max.set(st["run_max_s"], labels)

        on_collect(sample)


#: process-wide registry — every RpcServer dispatch in this process
#: records here regardless of which loop it runs on
GLOBAL_EVENT_STATS = EventStats()


def format_stall_dump(loop: Optional[asyncio.AbstractEventLoop], loop_thread_ident: Optional[int], name: str, silent_s: float) -> str:
    """All-thread stack dump + pending asyncio task names, with the
    stalled loop's thread called out (its top frame IS the blocking
    handler)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    lines: List[str] = [
        f"=== ray_tpu watchdog: event loop {name!r} stalled for {silent_s:.1f}s "
        f"(pid={os.getpid()}) ===",
    ]
    for ident, frame in sys._current_frames().items():
        marker = ""
        if loop_thread_ident is not None and ident == loop_thread_ident:
            marker = "  <<< STALLED EVENT LOOP — blocking handler below"
        lines.append(f"--- thread {names.get(ident, '?')} (ident={ident}){marker} ---")
        lines.extend(l.rstrip("\n") for l in traceback.format_stack(frame))
    if loop is not None:
        try:
            # best-effort from another thread: the WeakSet iteration can
            # race task creation — a diagnostics dump must never throw
            tasks = [t for t in asyncio.all_tasks(loop) if not t.done()]
            lines.append(f"--- {len(tasks)} pending asyncio tasks on {name!r} ---")
            for t in tasks[:200]:
                coro = t.get_coro()
                lines.append(f"  {t.get_name()}: {getattr(coro, '__qualname__', coro)!r}")
        except Exception:
            lines.append("--- pending task listing unavailable (racing loop) ---")
    lines.append("=== end watchdog dump ===")
    return "\n".join(lines)


class LoopMonitor:
    """Heartbeat + watchdog for one asyncio loop.

    The heartbeat coroutine wakes every ``event_loop_tick_s``, measures
    its own scheduling lag (how late the wakeup fired — the loop-lag
    gauge) and stamps ``_last_beat``. The watchdog thread declares a
    stall when the stamp goes silent past
    ``event_loop_stall_threshold_s`` and dumps diagnostics; with
    ``watchdog_abort_after_s > 0`` a persistent stall hard-exits the
    process (test mode: convert a wedge into an attributable crash)."""

    def __init__(self, loop: asyncio.AbstractEventLoop, name: str):
        self.loop = loop
        self.name = name
        self.stall_count = 0
        self.last_dump_text = ""
        self.max_lag_s = 0.0
        self.on_stall: List[Callable[[str, float], None]] = []
        self._last_beat = time.monotonic()
        self._loop_thread_ident: Optional[int] = None
        self._stall_started: Optional[float] = None
        self._last_dump_at = 0.0
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._beat_task: Optional[asyncio.Task] = None
        self._window_lag_s = 0.0  # max since last scrape (gauge source)
        self._g_lag = None
        self._c_stalls = None
        self._metrics_cb = None

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "LoopMonitor":
        from ray_tpu.observability.metrics import Counter, Gauge, on_collect

        self._g_lag = Gauge(
            "raytpu_event_loop_lag_seconds",
            "heartbeat scheduling lag of the process event loop (max since last scrape)",
            ("loop",),
        )
        self._c_stalls = Counter(
            "raytpu_event_loop_stalls_total",
            "event-loop stalls detected by the watchdog",
            ("loop",),
        )

        def _sample() -> None:
            # windowed max, reset per scrape — a one-off startup stall
            # must not pin the gauge at its historical peak forever
            self._g_lag.set(self._window_lag_s, {"loop": self.name})
            self._window_lag_s = 0.0

        self._metrics_cb = on_collect(_sample)
        def _schedule() -> None:
            self._beat_task = asyncio.ensure_future(self._beat())

        self.loop.call_soon_threadsafe(_schedule)
        self._thread = threading.Thread(
            target=self._watch, daemon=True, name=f"loop-watchdog-{self.name}"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stopped.set()
        if self._metrics_cb is not None:
            from ray_tpu.observability.metrics import remove_collect

            remove_collect(self._metrics_cb)
            self._metrics_cb = None
        task = self._beat_task
        if task is not None and not self.loop.is_closed():
            try:
                self.loop.call_soon_threadsafe(task.cancel)
            except RuntimeError:
                pass  # loop already closing

    # -- heartbeat (runs ON the guarded loop) ----------------------------
    async def _beat(self) -> None:
        self._loop_thread_ident = threading.get_ident()
        while not self._stopped.is_set():
            tick = max(0.01, GLOBAL_CONFIG.event_loop_tick_s)
            t0 = time.monotonic()
            try:
                await asyncio.sleep(tick)
            except asyncio.CancelledError:
                return
            now = time.monotonic()
            lag = max(0.0, (now - t0) - tick)
            if lag > self.max_lag_s:
                self.max_lag_s = lag  # lifetime max (debug snapshot)
            if lag > self._window_lag_s:
                self._window_lag_s = lag  # per-scrape max (gauge)
            self._last_beat = now
            self._stall_started = None  # loop is alive again

    # -- watchdog (its own thread) ---------------------------------------
    def _watch(self) -> None:
        while not self._stopped.wait(max(0.05, GLOBAL_CONFIG.event_loop_tick_s)):
            if self.loop.is_closed() or not self.loop.is_running():
                continue  # startup/shutdown windows are not stalls
            if self._loop_thread_ident is None:
                continue  # heartbeat not scheduled yet
            threshold = GLOBAL_CONFIG.event_loop_stall_threshold_s
            if threshold <= 0:
                continue
            silent = time.monotonic() - self._last_beat
            if silent <= threshold + GLOBAL_CONFIG.event_loop_tick_s:
                self._stall_started = None
                continue
            now = time.monotonic()
            # local snapshot: the loop thread clears _stall_started on
            # recovery concurrently — `now - None` would kill this thread
            # and silently remove the safety net
            started = self._stall_started
            if started is None:
                started = self._stall_started = now
                self.stall_count += 1
                if self._c_stalls is not None:
                    self._c_stalls.inc(labels={"loop": self.name})
            if now - self._last_dump_at >= GLOBAL_CONFIG.event_loop_stall_dump_interval_s:
                self._last_dump_at = now
                self._dump(silent)
            abort_after = GLOBAL_CONFIG.watchdog_abort_after_s
            if (
                abort_after > 0
                and not ABORT_DISABLED_IN_PROCESS
                and now - started >= abort_after
            ):
                self._abort(silent)

    @staticmethod
    def _dump_path() -> str:
        return f"/tmp/ray_tpu/watchdog-{os.getpid()}.log"

    def _write_dump_file(self, text: str) -> None:
        """Post-mortem file: stderr may be swallowed (pytest fd capture
        dies with the process on a hard abort) — the file survives."""
        try:
            os.makedirs("/tmp/ray_tpu", exist_ok=True)
            with open(self._dump_path(), "a") as f:
                f.write(text + "\n")
        except Exception:
            pass

    def _dump(self, silent: float) -> None:
        try:
            text = format_stall_dump(
                self.loop, self._loop_thread_ident, self.name, silent
            )
        except Exception:  # noqa: BLE001 — diagnostics must never raise
            text = f"watchdog: loop {self.name!r} stalled {silent:.1f}s (dump failed)"
        self.last_dump_text = text
        print(text, file=sys.stderr, flush=True)
        self._write_dump_file(text)
        logger.warning("event loop stall detected:\n%s", text)
        for cb in list(self.on_stall):
            try:
                cb(text, silent)
            except Exception:
                pass

    def _abort(self, silent: float) -> None:
        msg = (
            f"ray_tpu watchdog: loop {self.name!r} stalled {silent:.1f}s > "
            f"watchdog_abort_after_s={GLOBAL_CONFIG.watchdog_abort_after_s}; aborting pid {os.getpid()}"
        )
        print(msg, file=sys.stderr, flush=True)
        self._write_dump_file(msg)
        try:
            import faulthandler

            faulthandler.dump_traceback(file=sys.stderr, all_threads=True)
            with open(self._dump_path(), "a") as f:
                faulthandler.dump_traceback(file=f, all_threads=True)
        except Exception:
            pass
        os._exit(WATCHDOG_ABORT_EXIT_CODE)


# -- per-process monitor registry ---------------------------------------
_MONITORS_LOCK = threading.Lock()
_MONITORS: Dict[int, LoopMonitor] = {}


def install_loop_monitor(loop: asyncio.AbstractEventLoop, name: str) -> Optional[LoopMonitor]:
    """Attach a LoopMonitor to ``loop`` (idempotent per loop). Returns
    None when monitoring is disabled by config."""
    if not GLOBAL_CONFIG.event_loop_monitor_enabled:
        return None
    with _MONITORS_LOCK:
        existing = _MONITORS.get(id(loop))
        if existing is not None:
            return existing
        monitor = _MONITORS[id(loop)] = LoopMonitor(loop, name)
    return monitor.start()


def remove_loop_monitor(loop: asyncio.AbstractEventLoop) -> None:
    with _MONITORS_LOCK:
        monitor = _MONITORS.pop(id(loop), None)
    if monitor is not None:
        monitor.stop()


def get_loop_monitors() -> List[LoopMonitor]:
    with _MONITORS_LOCK:
        return list(_MONITORS.values())


def debug_snapshot() -> Dict[str, object]:
    """The process's event-stats debug state (reference DebugString):
    served verbatim by the controller's and daemons' ``event_stats``
    RPCs — one definition so the payload cannot drift between them."""
    return {
        "handlers": GLOBAL_EVENT_STATS.snapshot(),
        "loops": [
            {
                "name": m.name,
                "max_lag_s": m.max_lag_s,
                "stall_count": m.stall_count,
            }
            for m in get_loop_monitors()
        ],
    }
