"""Metrics: counters/gauges + Prometheus text exposition.

Reference: ``src/ray/stats/metric_defs.cc`` (system metric definitions),
``_private/metrics_agent.py`` + ``_private/prometheus_exporter.py`` (the
per-node agent exposing Prometheus text). Here each daemon/controller
process runs a tiny stdlib HTTP endpoint serving ``/metrics`` in the
Prometheus exposition format; user code gets the same Counter/Gauge
API as ``ray.util.metrics``."""

from __future__ import annotations

import bisect
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

_REGISTRY_LOCK = threading.Lock()
_METRICS: Dict[str, "Metric"] = {}
_COLLECT_CALLBACKS: List[Callable[[], None]] = []


class Metric:
    """Base: name + help + labelled values."""

    TYPE = "untyped"

    def __init__(self, name: str, description: str = "", labelnames: Tuple[str, ...] = ()):
        self.name = name
        self.description = description
        self.labelnames = tuple(labelnames)
        self._values: Dict[Tuple[str, ...], float] = {}
        self._lock = threading.Lock()
        with _REGISTRY_LOCK:
            existing = _METRICS.get(name)
            if existing is not None:
                if existing.labelnames != self.labelnames:
                    raise ValueError(
                        f"metric {name!r} re-registered with different labels "
                        f"{self.labelnames} != {existing.labelnames}"
                    )
                # re-registration returns the same underlying metric
                self._values = existing._values
                self._lock = existing._lock
            else:
                _METRICS[name] = self

    def _key(self, labels: Optional[Dict[str, str]]) -> Tuple[str, ...]:
        labels = labels or {}
        return tuple(str(labels.get(k, "")) for k in self.labelnames)

    def set(self, value: float, labels: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._values[self._key(labels)] = float(value)

    def collect(self) -> List[str]:
        lines = [
            f"# HELP {self.name} {self.description}",
            f"# TYPE {self.name} {self.TYPE}",
        ]
        with self._lock:
            for key, value in sorted(self._values.items()):
                if self.labelnames:
                    label_str = ",".join(
                        f'{n}="{v}"' for n, v in zip(self.labelnames, key)
                    )
                    lines.append(f"{self.name}{{{label_str}}} {value}")
                else:
                    lines.append(f"{self.name} {value}")
        return lines


class Counter(Metric):
    TYPE = "counter"

    def inc(self, amount: float = 1.0, labels: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            k = self._key(labels)
            self._values[k] = self._values.get(k, 0.0) + amount


class Gauge(Metric):
    TYPE = "gauge"


def bucket_quantile(
    buckets: Sequence[float], counts: Sequence[float], q: float
) -> Optional[float]:
    """Estimate the ``q``-quantile from histogram bucket counts
    (``counts[i]`` = observations with value <= ``buckets[i]``;
    ``counts[len(buckets)]`` is the +Inf overflow). Linear interpolation
    inside the winning bucket — the Prometheus ``histogram_quantile``
    estimator — so with log-spaced buckets of width ratio ``r`` the
    relative error is bounded by ~``(r-1)/2``. This is what makes
    histograms AGGREGATABLE: counts from any number of processes sum
    element-wise and the quantile of the sum is exact to bucket
    resolution, which no set of per-process quantile gauges can offer.
    Returns None when the histogram is empty."""
    total = sum(counts)
    if total <= 0:
        return None
    rank = q * total
    cum = 0.0
    for i, c in enumerate(counts):
        if c > 0 and cum + c >= rank:
            lo = buckets[i - 1] if i > 0 else 0.0
            # the +Inf bucket has no upper bound: clamp to the last
            # finite bound (size the table so p99.9 never lands here)
            hi = buckets[i] if i < len(buckets) else buckets[-1]
            if hi <= lo:
                return hi
            return lo + (hi - lo) * max(0.0, rank - cum) / c
        cum += c
    return float(buckets[-1])


#: default latency buckets (seconds): sub-ms submit stages through
#: multi-second transfers — the envelopes this runtime actually spans
_DEFAULT_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


class Histogram(Metric):
    """Prometheus histogram: cumulative ``_bucket{le=...}`` series plus
    ``_sum``/``_count``. ``observe`` is a short lock + linear bucket scan
    (≤ ~16 comparisons) — cheap enough for per-task stage timings."""

    TYPE = "histogram"

    def __init__(
        self,
        name: str,
        description: str = "",
        labelnames: Tuple[str, ...] = (),
        buckets: Optional[Tuple[float, ...]] = None,
    ):
        self.buckets = tuple(sorted(buckets or _DEFAULT_BUCKETS))
        super().__init__(name, description, labelnames)

    def observe(self, value: float, labels: Optional[Dict[str, str]] = None) -> None:
        value = float(value)
        with self._lock:
            k = self._key(labels)
            ent = self._values.get(k)
            if ent is None:
                # [per-bucket counts..., +Inf count, sum, count]
                ent = self._values[k] = [0] * (len(self.buckets) + 1) + [0.0, 0]
            # bisect, not a linear scan: the SLO latency histograms run
            # ~150 log-spaced buckets and observe() sits on the engine's
            # per-token path (bisect_left lands on the first bound >= v;
            # past-the-end = the +Inf slot, which IS index len(buckets))
            ent[bisect.bisect_left(self.buckets, value)] += 1
            ent[-2] += value
            ent[-1] += 1

    def counts(self, labels: Optional[Dict[str, str]] = None) -> Optional[List[float]]:
        """Raw per-bucket counts (incl. the +Inf slot; sum and count
        trail) for one label set — the cross-process merge unit."""
        with self._lock:
            ent = self._values.get(self._key(labels))
            return list(ent) if ent is not None else None

    def quantiles(
        self, qs: Iterable[float], labels: Optional[Dict[str, str]] = None
    ) -> Dict[float, Optional[float]]:
        """Quantile estimates for one label set via
        :func:`bucket_quantile` (None when nothing was observed)."""
        ent = self.counts(labels)
        counts = ent[: len(self.buckets) + 1] if ent is not None else ()
        return {q: bucket_quantile(self.buckets, counts, q) for q in qs}

    def collect(self) -> List[str]:
        lines = [
            f"# HELP {self.name} {self.description}",
            f"# TYPE {self.name} {self.TYPE}",
        ]
        with self._lock:
            for key, ent in sorted(self._values.items()):
                base = list(zip(self.labelnames, key))

                def _fmt(extra: List[Tuple[str, str]]) -> str:
                    pairs = base + extra
                    if not pairs:
                        return ""
                    return "{" + ",".join(f'{n}="{v}"' for n, v in pairs) + "}"

                cum = 0
                for i, b in enumerate(self.buckets):
                    cum += ent[i]
                    lines.append(
                        f"{self.name}_bucket{_fmt([('le', repr(float(b)))])} {cum}"
                    )
                cum += ent[len(self.buckets)]
                lines.append(f"{self.name}_bucket{_fmt([('le', '+Inf')])} {cum}")
                lines.append(f"{self.name}_sum{_fmt([])} {ent[-2]}")
                lines.append(f"{self.name}_count{_fmt([])} {ent[-1]}")
        return lines


def on_collect(cb: Callable[[], None]) -> Callable[[], None]:
    """Register a callback run right before exposition (for gauges
    sampled from live state, e.g. store bytes). Returns ``cb`` so the
    owner can deregister it at shutdown."""
    with _REGISTRY_LOCK:
        _COLLECT_CALLBACKS.append(cb)
    return cb


def remove_collect(cb: Callable[[], None]) -> None:
    with _REGISTRY_LOCK:
        try:
            _COLLECT_CALLBACKS.remove(cb)
        except ValueError:
            pass


def render() -> str:
    with _REGISTRY_LOCK:
        callbacks = list(_COLLECT_CALLBACKS)
        metrics = list(_METRICS.values())
    for cb in callbacks:
        try:
            cb()
        except Exception:
            pass
    out: List[str] = []
    for m in metrics:
        out.extend(m.collect())
    return "\n".join(out) + "\n"


def inject_label(text: str, label: str, value: str) -> str:
    """Rewrite Prometheus exposition text so every series carries
    ``label="value"`` (federation relabeling: the controller stamps each
    scraped node's series with its node id). Comment lines pass through.
    A series that ALREADY carries the label keeps its own value —
    daemon-side gauges are registered with a ``node`` label, and a
    duplicated label name is a parse error for real Prometheus."""
    out: List[str] = []
    pair = f'{label}="{value}"'
    marker = f'{label}="'
    for line in text.splitlines():
        if not line or line.startswith("#"):
            out.append(line)
            continue
        name, _, rest = line.partition(" ")
        if "{" in name:
            head, _, tail = name.partition("{")
            # boundary-anchored so a label NAMED e.g. "mynode" doesn't
            # mask the injection (labels are comma-joined, no spaces)
            if tail.startswith(marker) or ("," + marker) in tail:
                out.append(line)  # series already carries the label
            else:
                out.append(f"{head}{{{pair},{tail} {rest}")
        else:
            out.append(f"{name}{{{pair}}} {rest}")
    return "\n".join(out)


class _Handler(BaseHTTPRequestHandler):
    #: extra GET routes (path -> () -> str), set per server instance via
    #: a subclass — the controller mounts /federate here
    _routes: Dict[str, Callable[[], str]] = {}

    def do_GET(self):  # noqa: N802
        path = self.path.rstrip("/")
        if path in self._routes:
            try:
                body = self._routes[path]().encode()
            except Exception:
                self.send_response(500)
                self.end_headers()
                return
            ctype = "text/plain; version=0.0.4"
        elif path == "/healthz":
            body = b"ok"
            ctype = "text/plain"
        elif path in ("", "/metrics"):
            body = render().encode()
            ctype = "text/plain; version=0.0.4"
        else:
            self.send_response(404)
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):  # silence per-request stderr noise
        pass


class MetricsServer:
    """Prometheus exposition endpoint for this process."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        routes: Optional[Dict[str, Callable[[], str]]] = None,
    ):
        # per-instance handler class: co-hosted servers (controller +
        # daemon in the head process) must not share extra routes
        handler = type("_BoundHandler", (_Handler,), {"_routes": dict(routes or {})})
        try:
            self._httpd = ThreadingHTTPServer((host, port), handler)
        except OSError:
            # fixed port already taken (e.g. controller + daemon
            # co-hosted): fall back to auto-assign rather than failing
            # cluster startup
            self._httpd = ThreadingHTTPServer((host, 0), handler)
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True, name="metrics"
        )
        self._thread.start()

    def stop(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:
            pass
