"""Metrics: counters/gauges + Prometheus text exposition.

Reference: ``src/ray/stats/metric_defs.cc`` (system metric definitions),
``_private/metrics_agent.py`` + ``_private/prometheus_exporter.py`` (the
per-node agent exposing Prometheus text). Here each daemon/controller
process runs a tiny stdlib HTTP endpoint serving ``/metrics`` in the
Prometheus exposition format; user code gets the same Counter/Gauge
API as ``ray.util.metrics``."""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Tuple

_REGISTRY_LOCK = threading.Lock()
_METRICS: Dict[str, "Metric"] = {}
_COLLECT_CALLBACKS: List[Callable[[], None]] = []


class Metric:
    """Base: name + help + labelled values."""

    TYPE = "untyped"

    def __init__(self, name: str, description: str = "", labelnames: Tuple[str, ...] = ()):
        self.name = name
        self.description = description
        self.labelnames = tuple(labelnames)
        self._values: Dict[Tuple[str, ...], float] = {}
        self._lock = threading.Lock()
        with _REGISTRY_LOCK:
            existing = _METRICS.get(name)
            if existing is not None:
                if existing.labelnames != self.labelnames:
                    raise ValueError(
                        f"metric {name!r} re-registered with different labels "
                        f"{self.labelnames} != {existing.labelnames}"
                    )
                # re-registration returns the same underlying metric
                self._values = existing._values
                self._lock = existing._lock
            else:
                _METRICS[name] = self

    def _key(self, labels: Optional[Dict[str, str]]) -> Tuple[str, ...]:
        labels = labels or {}
        return tuple(str(labels.get(k, "")) for k in self.labelnames)

    def set(self, value: float, labels: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._values[self._key(labels)] = float(value)

    def collect(self) -> List[str]:
        lines = [
            f"# HELP {self.name} {self.description}",
            f"# TYPE {self.name} {self.TYPE}",
        ]
        with self._lock:
            for key, value in sorted(self._values.items()):
                if self.labelnames:
                    label_str = ",".join(
                        f'{n}="{v}"' for n, v in zip(self.labelnames, key)
                    )
                    lines.append(f"{self.name}{{{label_str}}} {value}")
                else:
                    lines.append(f"{self.name} {value}")
        return lines


class Counter(Metric):
    TYPE = "counter"

    def inc(self, amount: float = 1.0, labels: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            k = self._key(labels)
            self._values[k] = self._values.get(k, 0.0) + amount


class Gauge(Metric):
    TYPE = "gauge"


def on_collect(cb: Callable[[], None]) -> Callable[[], None]:
    """Register a callback run right before exposition (for gauges
    sampled from live state, e.g. store bytes). Returns ``cb`` so the
    owner can deregister it at shutdown."""
    with _REGISTRY_LOCK:
        _COLLECT_CALLBACKS.append(cb)
    return cb


def remove_collect(cb: Callable[[], None]) -> None:
    with _REGISTRY_LOCK:
        try:
            _COLLECT_CALLBACKS.remove(cb)
        except ValueError:
            pass


def render() -> str:
    with _REGISTRY_LOCK:
        callbacks = list(_COLLECT_CALLBACKS)
        metrics = list(_METRICS.values())
    for cb in callbacks:
        try:
            cb()
        except Exception:
            pass
    out: List[str] = []
    for m in metrics:
        out.extend(m.collect())
    return "\n".join(out) + "\n"


class _Handler(BaseHTTPRequestHandler):
    def do_GET(self):  # noqa: N802
        if self.path.rstrip("/") not in ("", "/metrics", "/healthz"):
            self.send_response(404)
            self.end_headers()
            return
        if self.path.rstrip("/") == "/healthz":
            body = b"ok"
            ctype = "text/plain"
        else:
            body = render().encode()
            ctype = "text/plain; version=0.0.4"
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):  # silence per-request stderr noise
        pass


class MetricsServer:
    """Prometheus exposition endpoint for this process."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        try:
            self._httpd = ThreadingHTTPServer((host, port), _Handler)
        except OSError:
            # fixed port already taken (e.g. controller + daemon
            # co-hosted): fall back to auto-assign rather than failing
            # cluster startup
            self._httpd = ThreadingHTTPServer((host, 0), _Handler)
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True, name="metrics"
        )
        self._thread.start()

    def stop(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:
            pass
