"""RPC robustness counters (exported on every /metrics endpoint).

Retry storms, duplicate-suppression activity, and chaos injection rates
must be observable, not inferred from log archaeology: these counters are
bumped by the transport layer (``core/rpc.py``) and the control-plane
reconnect paths (``core/core_worker.py``, ``core/node_daemon.py``) and
ride the same per-process registry as every other metric, so any process
already serving /metrics (daemons, controller, serve replicas) exposes
them for free.
"""

from __future__ import annotations

from ray_tpu.observability.metrics import Counter

#: client-side RPC retry attempts (one inc per re-sent attempt)
RPC_RETRIES = Counter(
    "raytpu_rpc_retries_total",
    "client RPC retry attempts, by method",
    ("method",),
)

#: server-side duplicate requests answered from the reply cache — every
#: hit is a handler re-execution that did NOT happen
RPC_DEDUP_HITS = Counter(
    "raytpu_rpc_dedup_hits_total",
    "duplicate RPCs served from the server reply cache, by method",
    ("method",),
)

#: injected faults, by mode (request_drop/reply_drop/delay/disconnect;
#: the legacy testing_rpc_failure knob counts as request_drop)
RPC_CHAOS_INJECTIONS = Counter(
    "raytpu_rpc_chaos_injections_total",
    "chaos faults injected into RPC dispatch, by mode",
    ("mode",),
)

#: controller reconnect/re-register events (role: daemon|driver|worker)
CONTROLLER_RECONNECTS = Counter(
    "raytpu_controller_reconnects_total",
    "controller connection re-establishments (re-register/re-subscribe)",
    ("role",),
)
