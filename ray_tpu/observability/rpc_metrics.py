"""RPC robustness counters (exported on every /metrics endpoint).

Retry storms, duplicate-suppression activity, and chaos injection rates
must be observable, not inferred from log archaeology: these counters are
bumped by the transport layer (``core/rpc.py``) and the control-plane
reconnect paths (``core/core_worker.py``, ``core/node_daemon.py``) and
ride the same per-process registry as every other metric, so any process
already serving /metrics (daemons, controller, serve replicas) exposes
them for free.
"""

from __future__ import annotations

from ray_tpu.observability.metrics import Counter, Gauge, Histogram

#: client-side RPC retry attempts (one inc per re-sent attempt)
RPC_RETRIES = Counter(
    "raytpu_rpc_retries_total",
    "client RPC retry attempts, by method",
    ("method",),
)

#: server-side duplicate requests answered from the reply cache — every
#: hit is a handler re-execution that did NOT happen
RPC_DEDUP_HITS = Counter(
    "raytpu_rpc_dedup_hits_total",
    "duplicate RPCs served from the server reply cache, by method",
    ("method",),
)

#: injected faults, by mode (request_drop/reply_drop/delay/disconnect;
#: the legacy testing_rpc_failure knob counts as request_drop)
RPC_CHAOS_INJECTIONS = Counter(
    "raytpu_rpc_chaos_injections_total",
    "chaos faults injected into RPC dispatch, by mode",
    ("mode",),
)

#: RAW frames moved (direction: sent|received) — the zero-copy bulk
#: framing (core/rpc.py kind 5): chunk replies and stream-item pushes
#: whose payload travelled out-of-band instead of through pickle/msgpack
RAW_FRAMES = Counter(
    "raytpu_raw_frames_total",
    "RAW (zero-copy out-of-band payload) frames, by direction",
    ("direction",),
)
RAW_BYTES = Counter(
    "raytpu_raw_bytes_total",
    "bytes carried out-of-band by RAW frames, by direction",
    ("direction",),
)

#: controller reconnect/re-register events (role: daemon|driver|worker)
CONTROLLER_RECONNECTS = Counter(
    "raytpu_controller_reconnects_total",
    "controller connection re-establishments (re-register/re-subscribe)",
    ("role",),
)

# -- controller durability / failover (core/controller.py, core/wal.py) -----
# The zero-loss control plane: WAL volume (appends/bytes), compaction
# cadence (truncations at snapshot points), recovery depth (records
# replayed at start/takeover), the fencing epoch, standby promotions,
# and how many stale-epoch writes daemons rejected (each one is a
# split-brain write that WOULD have corrupted tables).

#: records appended to the controller WAL (one per acked table mutation)
CONTROLLER_WAL_APPENDS = Counter(
    "raytpu_controller_wal_appends_total",
    "controller WAL records appended (one per acked table mutation)",
)
#: framed bytes appended to the controller WAL
CONTROLLER_WAL_BYTES = Counter(
    "raytpu_controller_wal_bytes_total",
    "framed bytes appended to the controller WAL",
)
#: WAL records replayed during controller recovery (restart or takeover)
CONTROLLER_WAL_REPLAYS = Counter(
    "raytpu_controller_wal_replays_total",
    "controller WAL records replayed at recovery (restart/takeover)",
)
#: WAL compactions: snapshot commits that truncated the log
CONTROLLER_WAL_TRUNCATIONS = Counter(
    "raytpu_controller_wal_truncations_total",
    "controller WAL truncations (snapshot compaction points)",
)
#: this controller's incarnation epoch (the fencing token daemons check)
CONTROLLER_EPOCH = Gauge(
    "raytpu_controller_epoch",
    "controller incarnation epoch (fencing token; bumps every start/takeover)",
)
#: hot-standby promotions (lease expiry observed → replayed → serving)
CONTROLLER_TAKEOVERS = Counter(
    "raytpu_controller_takeovers_total",
    "standby controller takeovers (lease-expiry promotions)",
)
#: stale-epoch controller writes rejected by a daemon's fencing gate
CONTROLLER_FENCED_WRITES = Counter(
    "raytpu_controller_fenced_writes_total",
    "stale-epoch controller writes rejected by epoch fencing",
)

# -- pull manager (core/pull_manager.py) ------------------------------------
# The data plane's fault-tolerance activity: how many chunks moved, how
# often a chunk was retried (and why), how often a transfer failed over
# to another source mid-flight, and whether integrity checks ever fired.
# Counters are per-process (the pulling daemon); the gauges expose the
# admission controller's live state.

#: chunks fetched and VERIFIED (crc match) by the pull manager
PULL_CHUNKS = Counter(
    "raytpu_pull_chunks_total",
    "object-transfer chunks fetched and verified by the pull manager",
)

#: chunks received ZERO-COPY: the RAW reply landed straight in the
#: destination segment's unsealed window (vs the legacy copy fallback
#: when a source answered with a pickled reply) — the copy-count guard
#: in tests/test_perf_smoke.py pins PULL_RAW_CHUNKS == PULL_CHUNKS
PULL_RAW_CHUNKS = Counter(
    "raytpu_pull_raw_chunks_total",
    "object-transfer chunks received zero-copy into the destination segment",
)

#: chunk attempts retried, by reason (timeout | transport | integrity |
#: chaos — chaos covers injected chunk_drop/chunk_stall faults)
PULL_CHUNK_RETRIES = Counter(
    "raytpu_pull_chunk_retries_total",
    "object-transfer chunk fetch retries, by reason",
    ("reason",),
)

#: mid-transfer source failovers that RESUMED from the last verified
#: offset on another source (instead of restarting from byte 0)
PULL_RESUMES = Counter(
    "raytpu_pull_resumes_total",
    "mid-transfer source failovers resumed from the last verified offset",
)

#: chunks whose content digest did not match — detected BEFORE the data
#: could reach the destination segment (each one is re-fetched)
PULL_INTEGRITY_FAILURES = Counter(
    "raytpu_pull_integrity_failures_total",
    "object-transfer chunks rejected by integrity verification",
)

#: concurrent pulls of one object coalesced onto an in-flight transfer
PULL_COALESCED = Counter(
    "raytpu_pull_coalesced_total",
    "duplicate concurrent pulls coalesced onto one in-flight transfer",
)

#: transfers that exhausted every source (structured failure returned)
PULL_FAILURES = Counter(
    "raytpu_pull_failures_total",
    "pulls that failed after exhausting every source",
)

#: bytes of transfers currently admitted (in flight) / parked FIFO
#: behind the pull_max_inflight_bytes budget
PULL_INFLIGHT_BYTES = Gauge(
    "raytpu_pull_inflight_bytes",
    "bytes of object transfers currently in flight (admitted)",
)
PULL_QUEUED_BYTES = Gauge(
    "raytpu_pull_queued_bytes",
    "bytes of object transfers queued behind the admission budget",
)

# -- per-stage latency envelopes --------------------------------------------
# The measured (not inferred) scheduler and pull-manager envelopes: where
# a task's (or transfer's) time actually goes, as Prometheus histograms.
# Observed on the process DOING the stage: task stages land in the owner
# (queue/lease/push/total) and the executing worker (execute); pull
# stages land in the pulling daemon, so they federate with node labels.

#: normal-task submission stages (seconds): queue = submit→popped by a
#: lease pump; lease = worker-lease acquisition; push = push RPC round
#: trip (execution included); execute = worker-side run; total =
#: submit→finalize including retries
TASK_STAGE_SECONDS = Histogram(
    "raytpu_task_stage_seconds",
    "task lifecycle stage latency (queue/lease/push/execute/total)",
    ("stage",),
)

#: object-transfer stages (seconds): admit = admission-queue wait;
#: probe = transfer-head probe (object_info); transfer = chunk
#: streaming incl. retries/failover; total = whole pull
PULL_STAGE_SECONDS = Histogram(
    "raytpu_pull_stage_seconds",
    "pull-manager stage latency (admit/probe/transfer/total)",
    ("stage",),
)

# -- serve router decisions (serve/router.py) -------------------------------
# Routing policy behavior must be observable per process that routes
# (drivers, proxies, replicas calling other deployments): which policy
# actually fired, and how often cache affinity found a warm replica.

#: replica choices by policy (affinity = scored cache-affinity +
#: least-outstanding-tokens; pow2 = the stale-signal/plain fallback;
#: single = only one candidate)
ROUTER_DECISIONS = Counter(
    "raytpu_router_decisions_total",
    "serve router replica choices, by deployment and policy",
    ("deployment", "policy"),
)

#: scored choices whose winner already held cached prefix blocks for
#: the request — every hit is prefill work the cluster skipped
ROUTER_AFFINITY_HITS = Counter(
    "raytpu_router_affinity_hits_total",
    "scored routing decisions that landed on a prefix-warm replica",
    ("deployment",),
)

# -- resumable streams (serve/router.py exactly-once token delivery) --------
# Every resume is a mid-stream replica death the client never saw: the
# router re-dispatched to a survivor with the delivered prefix replayed
# and the SeqGate suppressed the boundary duplicates.

#: mid-stream failovers of resumable streams (one inc per re-dispatch)
STREAM_RESUMES = Counter(
    "raytpu_stream_resumes_total",
    "resumable serve streams re-dispatched after mid-stream replica death",
    ("deployment",),
)

#: already-delivered tokens replayed as prompt extension on resume —
#: the work the survivor's prefix cache absorbs (vs a cold re-prefill)
STREAM_RESUME_REPLAY_TOKENS = Counter(
    "raytpu_stream_resume_replay_tokens_total",
    "delivered tokens replayed as prompt extension by stream resumes",
)

#: ready serve replicas killed for replacement, by reason — death =
#: observed dead (SIGKILL, crash); unhealthy = the replica ANSWERED but
#: its check_health reported a wedged engine (proactive restart)
SERVE_REPLICA_RESTARTS = Counter(
    "raytpu_serve_replica_restarts_total",
    "serve replicas killed for replacement, by reason (death|unhealthy)",
    ("reason",),
)

#: autoscale target changes the controller actually APPLIED (post
#: delay gating), by decision reason — queue_depth (legacy signal),
#: ttft_burn / ttft_relax (SLO-autopilot budget burn), token_mix
#: (disagg prefill:decode pool-ratio adaptation)
SERVE_AUTOSCALE_DECISIONS = Counter(
    "raytpu_serve_autoscale_decisions_total",
    "serve autoscaler target changes applied, by deployment and reason",
    ("deployment", "reason"),
)

# -- HTTP/SSE ingress (serve/ingress.py) ------------------------------------
# The front door's overload behavior must be first-class telemetry: how
# much traffic each tenant class brought and what happened to it, how
# much was shed BEFORE consuming an engine queue slot (and why), and the
# client-observed time-to-first-byte SLO distribution. Counters live in
# the ingress replica processes (each exports /metrics like any serve
# replica host process would).

#: terminal outcome per request: ok | shed | bad_request | error |
#: disconnect (client went away mid-stream — its engine work was
#: cancelled, not completed)
INGRESS_REQUESTS = Counter(
    "raytpu_ingress_requests_total",
    "HTTP ingress requests, by tenant class and terminal outcome",
    ("tenant_class", "outcome"),
)

#: requests refused with 429 BEFORE any downstream dispatch, by reason:
#: rate_limit (tenant token bucket dry), load (gossiped outstanding
#: tokens above the class watermark), queue_pressure (engine admission
#: queues filling — only the top class may still queue)
INGRESS_SHED = Counter(
    "raytpu_ingress_shed_total",
    "ingress requests shed before reaching an engine, by reason",
    ("reason",),
)

#: request arrival to first streamed byte (SSE) / full reply (JSON) —
#: the client-observed TTFT envelope the shed policy protects
INGRESS_TTFB = Histogram(
    "raytpu_ingress_ttfb_seconds",
    "ingress request arrival to first response byte",
)

# -- cluster-wide KV prefix tier (inference/kv_transfer.py tier layer) ------
# The warm-recovery plane: blocks written back into daemon-owned tier
# storage (spill or explicit write-back), faulted in by replicas on
# resume/restart, and the fallback-ladder rungs taken when the tier
# could not serve (chaos, reaped entries, digest rejections).

#: full prefix blocks written back into the tier, by trigger — prefill
#: (prompt blocks at prefill completion), decode (newly-completed
#: generation blocks mid-stream), evict (the spill half of the
#: spill-vs-drop eviction policy), migrate (drain-time handoff flush)
KV_TIER_PUBLISHES = Counter(
    "raytpu_kv_tier_publishes_total",
    "KV prefix blocks written back into the cluster tier, by trigger",
    ("trigger",),
)

#: tier blocks successfully faulted in and committed into a replica's
#: paged cache (each one is a block of prefill the cluster skipped)
KV_TIER_HITS = Counter(
    "raytpu_kv_tier_hits_total",
    "KV prefix blocks faulted in from the tier and committed",
)

#: tier fault-in attempts that fell down the ladder, by reason —
#: missing (entry gone / no source), digest (integrity gate refused the
#: payload), transfer (pull failed), import (scatter/commit failed),
#: chaos_kill (migration killed mid-scatter). Every inc is one rung
#: down toward PR 10 prefix replay, which stays byte-exact regardless.
KV_TIER_FALLBACKS = Counter(
    "raytpu_kv_tier_fallbacks_total",
    "tier fault-in attempts degraded to the next fallback rung, by reason",
    ("reason",),
)

#: tier adverts EXPLICITLY retracted from the routing gossip by a live
#: holder (eviction/drop), counted router-side — death-TTL expiries are
#: not retractions (the daemon may still hold the bytes)
KV_TIER_RETRACTIONS = Counter(
    "raytpu_kv_tier_retractions_total",
    "tier prefix adverts retracted from router directories by holders",
)

#: tier bytes moved, by direction (publish = write-back into the tier,
#: fault_in = pulled into a replica's cache)
KV_TIER_BYTES = Counter(
    "raytpu_kv_tier_bytes_total",
    "KV bytes moved through the cluster tier, by direction",
    ("direction",),
)

# -- speculative decoding (inference/speculative.py + engine verify) --------
# The propose→verify→accept/rollback loop's books: how many draft
# tokens were proposed, how many the target's one-step verification
# accepted (every accepted draft is a decode step the engine skipped),
# and how often a mismatch forced a rollback of the drafted tail. The
# SLO goodput counter is unaffected by design — only ACCEPTED tokens
# ever enter ``generated``, so rejected draft work can never inflate
# the tok/s books.

#: draft tokens proposed (n-gram lookup or draft-model decode)
LLM_SPEC_PROPOSED = Counter(
    "raytpu_llm_spec_proposed_tokens_total",
    "speculative draft tokens proposed for verification",
)

#: proposed drafts that matched the target's deterministic sample and
#: were committed — byte-identical to what plain decode would emit
LLM_SPEC_ACCEPTED = Counter(
    "raytpu_llm_spec_accepted_tokens_total",
    "speculative draft tokens accepted by target verification",
)

#: verify windows whose drafted tail was (partially) rejected: the
#: write cursor rewound and the over-grown KV blocks were trimmed back
LLM_SPEC_ROLLBACKS = Counter(
    "raytpu_llm_spec_rollbacks_total",
    "speculative verify steps that rolled back rejected draft tokens",
)

#: windowed acceptance rate (accepted/proposed over the gauge-refresh
#: window) — the signal the adaptive-k controller steers on
LLM_SPEC_ACCEPTANCE = Gauge(
    "raytpu_llm_spec_acceptance_rate",
    "trailing-window speculative draft acceptance rate",
)
