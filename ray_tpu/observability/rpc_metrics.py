"""RPC robustness counters (exported on every /metrics endpoint).

Retry storms, duplicate-suppression activity, and chaos injection rates
must be observable, not inferred from log archaeology: these counters are
bumped by the transport layer (``core/rpc.py``) and the control-plane
reconnect paths (``core/core_worker.py``, ``core/node_daemon.py``) and
ride the same per-process registry as every other metric, so any process
already serving /metrics (daemons, controller, serve replicas) exposes
them for free.
"""

from __future__ import annotations

from ray_tpu.observability.metrics import Counter

#: client-side RPC retry attempts (one inc per re-sent attempt)
RPC_RETRIES = Counter(
    "raytpu_rpc_retries_total",
    "client RPC retry attempts, by method",
    ("method",),
)

#: server-side duplicate requests answered from the reply cache — every
#: hit is a handler re-execution that did NOT happen
RPC_DEDUP_HITS = Counter(
    "raytpu_rpc_dedup_hits_total",
    "duplicate RPCs served from the server reply cache, by method",
    ("method",),
)

#: injected faults, by mode (request_drop/reply_drop/delay/disconnect;
#: the legacy testing_rpc_failure knob counts as request_drop)
RPC_CHAOS_INJECTIONS = Counter(
    "raytpu_rpc_chaos_injections_total",
    "chaos faults injected into RPC dispatch, by mode",
    ("mode",),
)

#: controller reconnect/re-register events (role: daemon|driver|worker)
CONTROLLER_RECONNECTS = Counter(
    "raytpu_controller_reconnects_total",
    "controller connection re-establishments (re-register/re-subscribe)",
    ("role",),
)

# -- serve router decisions (serve/router.py) -------------------------------
# Routing policy behavior must be observable per process that routes
# (drivers, proxies, replicas calling other deployments): which policy
# actually fired, and how often cache affinity found a warm replica.

#: replica choices by policy (affinity = scored cache-affinity +
#: least-outstanding-tokens; pow2 = the stale-signal/plain fallback;
#: single = only one candidate)
ROUTER_DECISIONS = Counter(
    "raytpu_router_decisions_total",
    "serve router replica choices, by deployment and policy",
    ("deployment", "policy"),
)

#: scored choices whose winner already held cached prefix blocks for
#: the request — every hit is prefill work the cluster skipped
ROUTER_AFFINITY_HITS = Counter(
    "raytpu_router_affinity_hits_total",
    "scored routing decisions that landed on a prefix-warm replica",
    ("deployment",),
)
