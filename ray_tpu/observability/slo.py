"""SLO ledger: per-request lifecycle accounting for the serving path.

The north-star workloads are scored against SLOs (TTFT/ITL p99/p99.9,
goodput), not raw throughput — but quantile GAUGES cannot be aggregated
across processes (the p99 of two p99s is meaningless), and a slow
request used to be unattributable: nothing said whether its time went to
ingress shed/queueing, router dispatch/resume, engine queue wait,
prefill chunks, decode gaps, or a disaggregated KV export/import. This
module is the measurement substrate that fixes both:

* **fixed log-bucket latency histograms** — ``raytpu_llm_ttft_seconds``
  / ``raytpu_llm_itl_seconds`` / ``raytpu_llm_e2e_seconds``, labeled
  ``{deployment, tenant_class}``, on :data:`SLO_BUCKETS` (ratio-1.10
  log-spaced bounds, so any quantile — p99.9 included — interpolates
  from SUMMED per-process counts at ~5% relative error). Resumable
  serve streams are observed by the ROUTER (the client-perceived
  timeline: failover stalls count as slow gaps, and the samples
  survive replica SIGKILLs); direct engine callers and non-resumable
  streams are observed by the engine. Every process's counts merge
  element-wise in :func:`build_report`.
* **goodput vs fault-cost counters** — delivered-useful tokens
  (``raytpu_llm_goodput_tokens_total``) split from token work faults
  forced (``raytpu_llm_fault_cost_tokens_total{reason}``: cancelled /
  failed decode work, preemption re-prefill, resume replay) plus
  ``raytpu_llm_deadline_expired_total`` — the counters that let the
  traffic simulator (ROADMAP item 8) separate fault cost from capacity
  cost, reconciling exactly against the engine/ingress intake books
  (:func:`books_balanced`).
* **a flight recorder** — a bounded per-process ring
  (:class:`FlightRecorder`) holding the slowest-K requests by stage
  breakdown PLUS every flagged one (SLO-violating, resumed, preempted,
  failed, shed), each entry carrying the PR 9 trace id when the request
  was sampled. ``serve.slo_report()`` collects every tier's ring and
  JOINS entries by request id, so one call names the stage that made an
  outlier slow.

Everything here is jax-free and cheap by construction: a ledger stamp is
one ``time.monotonic()`` on an object the request already owns, a
histogram observe is one bisect + increment, and a recorder insert is a
bounded deque append / fixed-size heap replace.
"""

from __future__ import annotations

import bisect
import heapq
import itertools
import math
import re
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ray_tpu.core.config import GLOBAL_CONFIG


def _log_buckets(lo: float, hi: float, ratio: float) -> Tuple[float, ...]:
    """Log-spaced bucket bounds from ``lo`` to just past ``hi``, rounded
    to 4 significant digits (stable exposition text), strictly
    increasing."""
    out: List[float] = []
    v = lo
    while v < hi * ratio:
        r = round(v, 3 - int(math.floor(math.log10(v))))
        if not out or r > out[-1]:
            out.append(r)
        v *= ratio
    return tuple(out)


#: 100µs .. 120s at width ratio 1.10: linear interpolation inside a
#: bucket bounds the relative error by (ratio-1)/2 ≈ 5% at ANY quantile
#: — p99.9 of a merged cluster-wide distribution included — for ~150
#: buckets per label set. The span covers sub-ms decode gaps through
#: multi-minute stuck requests.
SLO_BUCKETS: Tuple[float, ...] = _log_buckets(1e-4, 120.0, 1.10)


# -- metrics (registered once per process) -----------------------------------

_METRICS = None
_METRICS_LOCK = threading.Lock()


def slo_metrics():
    """The SLO series (README Observability catalog). TTFT/ITL/e2e are
    HISTOGRAMS on :data:`SLO_BUCKETS` — the aggregatable replacement for
    the old per-engine quantile gauges."""
    global _METRICS
    if _METRICS is None:
        from ray_tpu.observability.metrics import Counter, Histogram

        with _METRICS_LOCK:
            if _METRICS is None:
                labels = ("deployment", "tenant_class")
                _METRICS = {
                    "ttft": Histogram(
                        "raytpu_llm_ttft_seconds",
                        "request submit to first streamed token",
                        labels, buckets=SLO_BUCKETS,
                    ),
                    "itl": Histogram(
                        "raytpu_llm_itl_seconds",
                        "inter-token gap between consecutive streamed tokens",
                        labels, buckets=SLO_BUCKETS,
                    ),
                    "e2e": Histogram(
                        "raytpu_llm_e2e_seconds",
                        "request submit to terminal state",
                        labels, buckets=SLO_BUCKETS,
                    ),
                    "goodput": Counter(
                        "raytpu_llm_goodput_tokens_total",
                        "tokens delivered by requests that finished cleanly",
                        labels,
                    ),
                    "fault": Counter(
                        "raytpu_llm_fault_cost_tokens_total",
                        "token work faults forced (cancelled|failed decode "
                        "work, preempt_replay re-prefill, resume_replay)",
                        ("deployment", "reason"),
                    ),
                    "deadline": Counter(
                        "raytpu_llm_deadline_expired_total",
                        "requests reaped because their deadline expired",
                        ("deployment",),
                    ),
                }
    return _METRICS


# -- per-engine latency tape -------------------------------------------------


class BucketCounts:
    """A bare log-bucket count vector (no registry, no labels): the
    per-engine TTFT tape backing ``stats()['ttft']`` back-compat, and
    the merge unit for snapshot aggregation. NOT thread-safe — callers
    own the locking (the engine observes under its own lock)."""

    __slots__ = ("buckets", "counts", "total")

    def __init__(
        self,
        buckets: Sequence[float] = SLO_BUCKETS,
        counts: Optional[Sequence[int]] = None,
    ):
        self.buckets = tuple(buckets)
        self.counts = list(counts) if counts else [0] * (len(self.buckets) + 1)
        self.total = sum(self.counts)

    def observe(self, value: float) -> None:
        self.counts[bisect.bisect_left(self.buckets, value)] += 1
        self.total += 1

    def merge(self, other: "BucketCounts") -> "BucketCounts":
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.total += other.total
        return self

    def quantile(self, q: float) -> Optional[float]:
        from ray_tpu.observability.metrics import bucket_quantile

        return bucket_quantile(self.buckets, self.counts, q)


# -- flight recorder ---------------------------------------------------------


class FlightRecorder:
    """Bounded per-process ring of request ledger entries: the slowest-K
    (fixed-size min-heap keyed by the caller's latency key — replace is
    O(log K) with K fixed, i.e. O(1)) plus EVERY flagged entry
    (SLO-violating / resumed / preempted / shed / failed) on a
    ``deque(maxlen)``. Inserts never allocate beyond the caps, so the
    recorder can run always-on under full serving load."""

    def __init__(self, slow_slots: Optional[int] = None, flagged_slots: Optional[int] = None):
        self._slow_cap = int(
            GLOBAL_CONFIG.slo_flight_recorder_slots if slow_slots is None else slow_slots
        )
        self._slow: List[Tuple[float, int, Dict[str, Any]]] = []
        self._flagged: deque = deque(
            maxlen=int(
                GLOBAL_CONFIG.slo_flight_flagged_slots
                if flagged_slots is None
                else flagged_slots
            )
        )
        self._seq = itertools.count()
        self._lock = threading.Lock()
        self.added = 0

    def add(
        self,
        entry: Dict[str, Any],
        *,
        flagged: bool = False,
        slow_key: Optional[float] = None,
    ) -> None:
        with self._lock:
            self.added += 1
            if flagged:
                self._flagged.append(entry)
            if slow_key is not None and self._slow_cap > 0:
                item = (float(slow_key), next(self._seq), entry)
                if len(self._slow) < self._slow_cap:
                    heapq.heappush(self._slow, item)
                elif item[0] > self._slow[0][0]:
                    heapq.heapreplace(self._slow, item)

    def snapshot(self) -> List[Dict[str, Any]]:
        """Every retained entry, deduped (an entry can sit in both the
        flagged ring and the slowest-K heap)."""
        with self._lock:
            seen: Dict[int, Dict[str, Any]] = {}
            for e in self._flagged:
                seen[id(e)] = e
            for _k, _s, e in self._slow:
                seen[id(e)] = e
            return [dict(e) for e in seen.values()]


_RECORDER: Optional[FlightRecorder] = None
_RECORDER_LOCK = threading.Lock()


def flight_recorder() -> FlightRecorder:
    """The process-wide recorder every tier (engine, router, ingress)
    in this process writes into."""
    global _RECORDER
    if _RECORDER is None:
        with _RECORDER_LOCK:
            if _RECORDER is None:
                _RECORDER = FlightRecorder()
    return _RECORDER


# -- snapshot / aggregation --------------------------------------------------

#: histogram + counter series a process snapshot carries (the SLO sinks
#: plus the router/ingress counters the report reconciles against)
_SNAP_HISTOGRAMS = (
    "raytpu_llm_ttft_seconds",
    "raytpu_llm_itl_seconds",
    "raytpu_llm_e2e_seconds",
)
_SNAP_COUNTERS = (
    "raytpu_llm_goodput_tokens_total",
    "raytpu_llm_fault_cost_tokens_total",
    "raytpu_llm_deadline_expired_total",
    "raytpu_llm_requests_total",
    "raytpu_stream_resumes_total",
    "raytpu_stream_resume_replay_tokens_total",
    "raytpu_ingress_requests_total",
    "raytpu_ingress_shed_total",
)


def snapshot() -> Dict[str, Any]:
    """This process's SLO state: raw histogram bucket counts (mergeable),
    counter values, and the flight-recorder ring. Callers (LLMServer /
    HttpIngress ``slo_snapshot``) attach their tier's ``books``."""
    from ray_tpu.observability.metrics import _METRICS as _REG

    out: Dict[str, Any] = {"histograms": {}, "counters": {}, "flight": []}
    for name in _SNAP_HISTOGRAMS:
        m = _REG.get(name)
        if m is None:
            continue
        with m._lock:  # noqa: SLF001 — the registry owns no dump API
            values = {k: list(v) for k, v in m._values.items()}  # noqa: SLF001
        out["histograms"][name] = {
            "labelnames": list(m.labelnames),
            "buckets": list(m.buckets),
            "values": values,
        }
    for name in _SNAP_COUNTERS:
        m = _REG.get(name)
        if m is None:
            continue
        with m._lock:  # noqa: SLF001
            out["counters"][name] = {
                "labelnames": list(m.labelnames),
                "values": dict(m._values),  # noqa: SLF001
            }
    out["flight"] = flight_recorder().snapshot()
    return out


def books_balanced(books: Dict[str, Any]) -> bool:
    """The exact-conservation identity for one tier's intake books.

    * engine: every submitted request is finished, failed, cancelled,
      or still in flight (queued/running) — nothing leaks, even across
      chaos kills, preemption churn, and drain cutoffs.
    * ingress: every request seen was shed, rejected as bad input, or
      forwarded downstream — a shed provably consumed nothing.

    The identity holds exactly at quiesce; mid-transition reads can be
    transiently short by the requests crossing a boundary (callers poll).
    """
    kind = books.get("kind")
    if kind == "engine":
        return int(books.get("submitted", 0)) == (
            int(books.get("finished", 0))
            + int(books.get("failed", 0))
            + int(books.get("cancelled", 0))
            + int(books.get("queued", 0))
            + int(books.get("running", 0))
        )
    if kind == "ingress":
        return int(books.get("seen", 0)) == (
            int(books.get("shed", 0))
            + int(books.get("bad_request", 0))
            + int(books.get("forwarded", 0))
        )
    return False


def _merge_values(dst: Dict[Any, Any], src: Dict[Any, Any]) -> None:
    for k, v in src.items():
        cur = dst.get(k)
        if cur is None:
            dst[k] = list(v) if isinstance(v, list) else v
        elif isinstance(v, list):
            for i, x in enumerate(v):
                cur[i] += x
        else:
            dst[k] = cur + v


#: the INTERNAL id suffixes the serving path itself appends — resume
#: attempts ride ``<rid>.rN`` (serve/router), disagg prefills
#: ``<rid>.pf`` (router handoff). Only these fold; a client-supplied id
#: that happens to contain a dot ("sess7.q1") is its own request and
#: must never merge with a sibling ("sess7.q2").
_INTERNAL_SUFFIX = re.compile(r"\.(r\d+|pf)$")

#: outcome-join precedence: the tier closest to the client wins
#: (within a tier, a clean terminal state beats an attempt's failure)
_TIER_RANK = {"engine": 0, "router": 1, "ingress": 2}


def _join_flight(entries: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Join per-tier recorder entries into per-request records by BASE
    request id (one logical request, several engine intakes — see
    :data:`_INTERNAL_SUFFIX`)."""
    by_base: Dict[str, Dict[str, Any]] = {}
    anon = itertools.count()
    for e in entries:
        rid = str(e.get("request_id") or "")
        base = _INTERNAL_SUFFIX.sub("", rid) if rid else f"anon{next(anon)}"
        rec = by_base.setdefault(
            base,
            {
                "request_id": base,
                "tiers": {},
                "stages": {},
                "resumes": 0,
                "replayed_tokens": 0,
                "flags": [],
            },
        )
        tier = str(e.get("tier") or "unknown")
        rec["tiers"].setdefault(tier, e)
        for key in ("deployment", "tenant_class", "trace_id"):
            if e.get(key) and not rec.get(key):
                rec[key] = e[key]
        # outcome: the tier CLOSEST to the client wins, regardless of
        # snapshot arrival order — a resumed stream leaves a 'failed'
        # engine entry for the killed attempt 0 next to the router's
        # 'ok'; the client got their tokens, so the joined record is ok
        if e.get("outcome"):
            new_rank = (
                _TIER_RANK.get(tier, 0),
                1 if e["outcome"] in ("ok", "finished") else 0,
            )
            if new_rank >= rec.get("_outcome_rank", (-1, -1)):
                rec["outcome"] = e["outcome"]
                rec["_outcome_rank"] = new_rank
        for key in ("ttft_s", "e2e_s"):
            if e.get(key) is not None:
                rec[key] = max(float(e[key]), float(rec.get(key) or 0.0))
        rec["resumes"] += int(e.get("resumes") or 0)
        rec["replayed_tokens"] += int(e.get("replayed_tokens") or 0)
        for f in e.get("flags") or ():
            if f not in rec["flags"]:
                rec["flags"].append(f)
        for stage, dur in (e.get("stages") or {}).items():
            key = f"{tier}.{stage}"
            rec["stages"][key] = round(
                max(float(dur), float(rec["stages"].get(key, 0.0))), 6
            )
    out = []
    for rec in by_base.values():
        rec.pop("_outcome_rank", None)
        if rec["stages"]:
            rec["slowest_stage"] = max(rec["stages"], key=rec["stages"].get)
        out.append(rec)
    out.sort(key=lambda r: float(r.get("e2e_s") or r.get("ttft_s") or 0.0), reverse=True)
    return out


_QS = (0.50, 0.99, 0.999)
_QNAMES = ("p50", "p99", "p999")


def _quantile_block(buckets, counts) -> Dict[str, Any]:
    from ray_tpu.observability.metrics import bucket_quantile

    n = int(sum(counts))
    block: Dict[str, Any] = {"count": n}
    for q, name in zip(_QS, _QNAMES):
        v = bucket_quantile(buckets, counts, q)
        if v is not None:
            block[name] = round(v, 6)
    return block


def build_report(
    snapshots: List[Dict[str, Any]],
    serve_status: Optional[Dict[str, Any]] = None,
    *,
    flight_limit: int = 100,
) -> Dict[str, Any]:
    """Fold per-process snapshots (each optionally tagged with
    ``deployment``/``tier``/``books`` by its producer) into the
    cluster-wide SLO report: aggregated TTFT/ITL/e2e quantiles per
    deployment (and per tenant class), goodput vs fault-cost counters,
    per-replica books with their conservation verdicts, and the joined
    flight-recorder dump, slowest first."""
    merged_hist: Dict[str, Dict[str, Any]] = {}
    merged_counters: Dict[str, Dict[str, Any]] = {}
    flight: List[Dict[str, Any]] = []
    books: List[Dict[str, Any]] = []
    for snap in snapshots:
        if not snap:
            continue
        for name, h in (snap.get("histograms") or {}).items():
            dst = merged_hist.setdefault(
                name,
                {"labelnames": h["labelnames"], "buckets": h["buckets"], "values": {}},
            )
            _merge_values(dst["values"], h["values"])
        for name, c in (snap.get("counters") or {}).items():
            dst = merged_counters.setdefault(
                name, {"labelnames": c["labelnames"], "values": {}}
            )
            _merge_values(dst["values"], c["values"])
        flight.extend(snap.get("flight") or ())
        if snap.get("books"):
            b = dict(snap["books"])
            b["deployment"] = snap.get("deployment", b.get("deployment", ""))
            b["balanced"] = books_balanced(b)
            books.append(b)

    deployments: Dict[str, Dict[str, Any]] = {}

    def _dep(name: str) -> Dict[str, Any]:
        return deployments.setdefault(
            name,
            {
                "ttft_s": {"count": 0},
                "itl_s": {"count": 0},
                "e2e_s": {"count": 0},
                "by_class": {},
                "goodput_tokens": 0,
                "fault_tokens": {},
                "deadline_expired": 0,
                "books": [],
            },
        )

    for name, key in (
        ("raytpu_llm_ttft_seconds", "ttft_s"),
        ("raytpu_llm_itl_seconds", "itl_s"),
        ("raytpu_llm_e2e_seconds", "e2e_s"),
    ):
        h = merged_hist.get(name)
        if h is None:
            continue
        buckets = h["buckets"]
        nb = len(buckets) + 1
        per_dep: Dict[str, List[float]] = {}
        for lk, ent in h["values"].items():
            dep, cls = (lk[0] or ""), (lk[1] or "")
            counts = ent[:nb]
            acc = per_dep.setdefault(dep, [0] * nb)
            for i, c in enumerate(counts):
                acc[i] += c
            _dep(dep)["by_class"].setdefault(cls, {})[key] = _quantile_block(
                buckets, counts
            )
        for dep, counts in per_dep.items():
            _dep(dep)[key] = _quantile_block(buckets, counts)

    gp = merged_counters.get("raytpu_llm_goodput_tokens_total", {}).get("values", {})
    for lk, v in gp.items():
        _dep(lk[0] or "")["goodput_tokens"] += int(v)
    ft = merged_counters.get("raytpu_llm_fault_cost_tokens_total", {}).get("values", {})
    for lk, v in ft.items():
        d = _dep(lk[0] or "")["fault_tokens"]
        d[lk[1]] = d.get(lk[1], 0) + int(v)
    dl = merged_counters.get("raytpu_llm_deadline_expired_total", {}).get("values", {})
    for lk, v in dl.items():
        _dep(lk[0] or "")["deadline_expired"] += int(v)
    for b in books:
        _dep(b.get("deployment") or "")["books"].append(b)

    for dep in deployments.values():
        fault = sum(dep["fault_tokens"].values())
        good = dep["goodput_tokens"]
        dep["goodput_fraction"] = (
            round(good / (good + fault), 6) if (good + fault) > 0 else None
        )
        dep["books_balanced"] = all(b["balanced"] for b in dep["books"]) if dep[
            "books"
        ] else None

    if serve_status:
        for name, st in serve_status.items():
            if name in deployments:
                deployments[name]["restarts"] = st.get("restarts")
                deployments[name]["shed_total"] = st.get("shed_total")
                # most recent applied autoscale decision (reason +
                # old/new target + wall ts) so a report alone is enough
                # to attribute autoscaler lag to a p99.9 miss window
                if st.get("last_scale") is not None:
                    deployments[name]["last_scale"] = st.get("last_scale")

    return {
        "generated_at": time.time(),
        "buckets": {"ratio": 1.10, "count": len(SLO_BUCKETS)},
        "deployments": deployments,
        "counters": {
            name: {
                ",".join(k) if isinstance(k, tuple) else str(k): v
                for k, v in c["values"].items()
            }
            for name, c in merged_counters.items()
        },
        "flight_recorder": _join_flight(flight)[:flight_limit],
    }
