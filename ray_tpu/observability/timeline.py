"""Task timeline: chrome://tracing dump of profile events.

Reference: ``ray.timeline`` (``python/ray/_private/profiling.py:124``,
``_private/state.py:948``) — emits chrome-tracing JSON of task lifecycle
events. Redesigned single-file equivalent: every process records
``ProfileEvent``s into a bounded in-memory ring buffer; the driver dumps
its own buffer plus any chunks workers exported to the controller's
BOUNDED export table (``export_events``/``collect_events`` RPCs —
byte-budgeted, reaped on node death; legacy/local backends fall back to
the raw KV prefix path) into one chrome-trace file loadable in
chrome://tracing or Perfetto. Events whose args carry trace ids
(``observability/tracing.py``) additionally yield flow events — the
cross-process causal arrows.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

_MAX_EVENTS = 100_000


@dataclass
class ProfileEvent:
    name: str
    category: str
    start_us: float
    end_us: float
    pid: int = field(default_factory=os.getpid)
    tid: int = 0
    args: Optional[Dict[str, Any]] = None


_events: "deque[ProfileEvent]" = deque(maxlen=_MAX_EVENTS)
_lock = threading.Lock()
_total_recorded = 0
_exporter_uid = uuid.uuid4().hex[:8]


def _now_us() -> float:
    # Wall clock, not perf_counter: events from many processes are merged
    # into one trace, so timestamps need a shared epoch.
    return time.time_ns() / 1e3


def record_event(
    name: str,
    category: str,
    start_us: float,
    end_us: float,
    args: Optional[Dict[str, Any]] = None,
) -> None:
    global _total_recorded
    ev = ProfileEvent(
        name=name,
        category=category,
        start_us=start_us,
        end_us=end_us,
        tid=threading.get_ident() % 1_000_000,
        args=args,
    )
    with _lock:
        _events.append(ev)
        _total_recorded += 1


@contextmanager
def profile(name: str, category: str = "task", **args):
    """Context manager recording one complete event (cf. ray.profiling)."""
    start = _now_us()
    try:
        yield
    finally:
        record_event(name, category, start, _now_us(), args=args or None)


def timeline_events() -> List[ProfileEvent]:
    with _lock:
        return list(_events)


def clear_events() -> None:
    with _lock:
        _events.clear()


_EVENTS_KV_PREFIX = b"ray_tpu:events:"
_export_count = 0
_export_chunk = 0


def _collect_remote_events() -> List[ProfileEvent]:
    """Pull worker-exported event chunks. Cluster backends serve them
    from the controller's BOUNDED export table (``collect_events`` RPC —
    oldest chunks past ``timeline_kv_max_bytes`` are dropped, a dead
    node's chunks are reaped with it); legacy/local backends fall back
    to the old KV prefix scan."""
    out: List[ProfileEvent] = []
    try:
        from ray_tpu.core import api

        worker = api.get_global_worker_or_none()
        if worker is None:
            return out
        backend = worker.backend
        collect = getattr(backend, "collect_timeline_chunks", None)
        if collect is not None:
            blobs = collect()
        else:
            blobs = [
                backend.kv_get(key) for key in backend.kv_keys(_EVENTS_KV_PREFIX)
            ]
        for blob in blobs:
            if blob:
                for d in json.loads(blob):
                    out.append(ProfileEvent(**d))
    except Exception:
        pass
    return out


def export_events_to_kv() -> None:
    """Worker-side: publish NEW events (since the last export) as one
    immutable chunk under a per-process key — writes are O(delta), and no
    cross-process read-modify-write exists anywhere. Retention is the
    CONTROLLER's job (bounded byte budget + node-death reap); legacy/
    local backends without the export RPC keep the raw KV path."""
    global _export_count, _export_chunk
    from ray_tpu.core import api

    worker = api.get_global_worker_or_none()
    if worker is None:
        return
    with _lock:
        fresh_n = min(_total_recorded - _export_count, len(_events))
        fresh = list(_events)[-fresh_n:] if fresh_n > 0 else []
        _export_count = _total_recorded
    if not fresh:
        return
    # Key on (startup-unique uuid, pid): bare pids collide across nodes in
    # a multi-node cluster and one worker's chunks would overwrite another's.
    key = f"{_exporter_uid}:{os.getpid()}:{_export_chunk:06d}"
    _export_chunk += 1
    blob = json.dumps([ev.__dict__ for ev in fresh]).encode()
    export = getattr(worker.backend, "export_timeline_chunk", None)
    if export is not None:
        export(key, blob)
    else:
        worker.backend.kv_put(_EVENTS_KV_PREFIX + key.encode(), blob)


def start_export_thread(period_s: float = 2.0) -> threading.Thread:
    """Background exporter for worker processes: ships new events to the
    controller KV so driver-side ``timeline()`` sees remote task spans
    without a worker round-trip. Idle workers cost nothing (delta export)."""

    def _loop():
        while True:
            time.sleep(period_s)
            try:
                export_events_to_kv()
            except Exception:
                pass

    t = threading.Thread(target=_loop, daemon=True, name="timeline-export")
    t.start()
    return t


def _flow_events(events: List[ProfileEvent]) -> List[Dict[str, Any]]:
    """Chrome-trace flow events for every resolvable trace edge: spans
    (events whose args carry ``span_id``) are indexed, and each child's
    ``parent_span_id`` found in the index yields an ``s``/``f`` pair —
    the arrows Perfetto draws from the parent's slice (any process) to
    the child's. Unresolvable parents (not exported yet) are skipped."""
    by_span: Dict[str, ProfileEvent] = {}
    for ev in events:
        sid = (ev.args or {}).get("span_id")
        if sid:
            by_span[sid] = ev
    out: List[Dict[str, Any]] = []
    for ev in events:
        args = ev.args or {}
        parent_id = args.get("parent_span_id")
        sid = args.get("span_id")
        if not parent_id or not sid:
            continue
        parent = by_span.get(parent_id)
        if parent is None:
            continue
        flow_id = int(sid[:12], 16)
        common = {"name": "trace", "cat": "trace", "id": flow_id}
        # start binds to the parent's slice, finish ("e" = enclosing
        # slice) to the child's — ts must fall inside each slice
        out.append(
            dict(common, ph="s", ts=parent.start_us, pid=parent.pid, tid=parent.tid)
        )
        out.append(
            dict(common, ph="f", bp="e", ts=ev.start_us, pid=ev.pid, tid=ev.tid)
        )
    return out


def dump_timeline(filename: Optional[str] = None) -> Any:
    """Dump chrome://tracing JSON (slices + trace flow arrows). Returns
    the trace list (and writes ``filename`` if given) — matches
    ``ray.timeline`` semantics; load in Perfetto / chrome://tracing."""
    events = timeline_events() + _collect_remote_events()
    trace = []
    for ev in events:
        trace.append(
            {
                "name": ev.name,
                "cat": ev.category,
                "ph": "X",
                "ts": ev.start_us,
                "dur": max(0.0, ev.end_us - ev.start_us),
                "pid": ev.pid,
                "tid": ev.tid,
                "args": ev.args or {},
            }
        )
    trace.extend(_flow_events(events))
    trace.sort(key=lambda e: e["ts"])
    if filename:
        with open(filename, "w") as f:
            json.dump(trace, f)
    return trace
