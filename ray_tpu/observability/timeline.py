"""Task timeline: chrome://tracing dump of profile events.

Reference: ``ray.timeline`` (``python/ray/_private/profiling.py:124``,
``_private/state.py:948``) — emits chrome-tracing JSON of task lifecycle
events. Redesigned single-file equivalent: every process records
``ProfileEvent``s into a bounded in-memory ring buffer; the driver dumps
its own buffer plus any events workers exported through the controller KV
(``ray_tpu:events:<worker>`` keys) into one chrome-trace file loadable in
chrome://tracing or Perfetto.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

_MAX_EVENTS = 100_000


@dataclass
class ProfileEvent:
    name: str
    category: str
    start_us: float
    end_us: float
    pid: int = field(default_factory=os.getpid)
    tid: int = 0
    args: Optional[Dict[str, Any]] = None


_events: "deque[ProfileEvent]" = deque(maxlen=_MAX_EVENTS)
_lock = threading.Lock()
_total_recorded = 0
_exporter_uid = uuid.uuid4().hex[:8]


def _now_us() -> float:
    # Wall clock, not perf_counter: events from many processes are merged
    # into one trace, so timestamps need a shared epoch.
    return time.time_ns() / 1e3


def record_event(
    name: str,
    category: str,
    start_us: float,
    end_us: float,
    args: Optional[Dict[str, Any]] = None,
) -> None:
    global _total_recorded
    ev = ProfileEvent(
        name=name,
        category=category,
        start_us=start_us,
        end_us=end_us,
        tid=threading.get_ident() % 1_000_000,
        args=args,
    )
    with _lock:
        _events.append(ev)
        _total_recorded += 1


@contextmanager
def profile(name: str, category: str = "task", **args):
    """Context manager recording one complete event (cf. ray.profiling)."""
    start = _now_us()
    try:
        yield
    finally:
        record_event(name, category, start, _now_us(), args=args or None)


def timeline_events() -> List[ProfileEvent]:
    with _lock:
        return list(_events)


def clear_events() -> None:
    with _lock:
        _events.clear()


_EVENTS_KV_PREFIX = b"ray_tpu:events:"
_export_count = 0
_export_chunk = 0


def _collect_remote_events() -> List[ProfileEvent]:
    """Pull worker-exported event chunks from the controller KV (prefix
    scan — no shared index, so concurrent exporters can't race)."""
    out: List[ProfileEvent] = []
    try:
        from ray_tpu.core import api

        worker = api.get_global_worker_or_none()
        if worker is None:
            return out
        backend = worker.backend
        for key in backend.kv_keys(_EVENTS_KV_PREFIX):
            blob = backend.kv_get(key)
            if blob:
                for d in json.loads(blob):
                    out.append(ProfileEvent(**d))
    except Exception:
        pass
    return out


def export_events_to_kv() -> None:
    """Worker-side: publish NEW events (since the last export) as one
    immutable chunk under a per-process key — writes are O(delta), and no
    cross-process read-modify-write exists anywhere."""
    global _export_count, _export_chunk
    from ray_tpu.core import api

    worker = api.get_global_worker_or_none()
    if worker is None:
        return
    with _lock:
        fresh_n = min(_total_recorded - _export_count, len(_events))
        fresh = list(_events)[-fresh_n:] if fresh_n > 0 else []
        _export_count = _total_recorded
    if not fresh:
        return
    # Key on (startup-unique uuid, pid): bare pids collide across nodes in
    # a multi-node cluster and one worker's chunks would overwrite another's.
    key = f"ray_tpu:events:{_exporter_uid}:{os.getpid()}:{_export_chunk:06d}"
    _export_chunk += 1
    worker.backend.kv_put(key.encode(), json.dumps([ev.__dict__ for ev in fresh]).encode())


def start_export_thread(period_s: float = 2.0) -> threading.Thread:
    """Background exporter for worker processes: ships new events to the
    controller KV so driver-side ``timeline()`` sees remote task spans
    without a worker round-trip. Idle workers cost nothing (delta export)."""

    def _loop():
        while True:
            time.sleep(period_s)
            try:
                export_events_to_kv()
            except Exception:
                pass

    t = threading.Thread(target=_loop, daemon=True, name="timeline-export")
    t.start()
    return t


def dump_timeline(filename: Optional[str] = None) -> Any:
    """Dump chrome://tracing JSON. Returns the trace list (and writes
    ``filename`` if given) — matches ``ray.timeline`` semantics."""
    trace = []
    for ev in timeline_events() + _collect_remote_events():
        trace.append(
            {
                "name": ev.name,
                "cat": ev.category,
                "ph": "X",
                "ts": ev.start_us,
                "dur": max(0.0, ev.end_us - ev.start_us),
                "pid": ev.pid,
                "tid": ev.tid,
                "args": ev.args or {},
            }
        )
    trace.sort(key=lambda e: e["ts"])
    if filename:
        with open(filename, "w") as f:
            json.dump(trace, f)
    return trace
