"""Distributed tracing: causal spans from submit to TPU step.

Reference shape: OpenTelemetry-style ``trace_id``/``span_id``/
``parent_span_id`` context propagation, carried the same two ways
``core/deadline`` already travels:

* **in-process** — a ``contextvars.ContextVar`` holds the ambient
  :class:`TraceContext`; :func:`span` mints a child span, records it
  into the existing timeline ring buffer (``observability/timeline``)
  and makes it the ambient parent for everything nested under it.
* **cross-process** — task submission stamps ``(trace_id, span_id)``
  onto ``TaskSpec.trace_ctx`` (a per-call field, so template-spliced
  hot-path submits carry it too) and RPC requests append it to the
  dedup meta slot (``core/rpc.py``); the receiving side re-enters the
  context with :func:`scope`, so its spans parent to the sender's.

Spans are ordinary :class:`timeline.ProfileEvent`\\ s whose ``args``
carry ``trace_id``/``span_id``/``parent_span_id`` — ``dump_timeline``
emits chrome-trace *flow events* for every parent→child edge it can
resolve, which is what draws the cross-process arrows in Perfetto.

SAMPLING. Everything here is gated on ``trace_sample_rate`` (default
0.0): with no ambient context and a zero rate, every entry point is a
single attribute read + compare — the PR 3 submit hot path pays no span
allocation when unsampled (``test_perf_smoke.py`` floors this). A root
is sampled once at a request entry point (driver submit, serve router
dispatch) and the verdict is inherited causally: children of a sampled
request are always recorded, children of an unsampled one never are.

Trace ids are prefixed with a cluster-wide *trace epoch* (minted by the
driver, threaded through every spawned runtime process via the
``RAY_TPU_TRACE_EPOCH`` env var in ``cluster_backend``), so ids from
one cluster incarnation never collide with a restarted one's.
"""

from __future__ import annotations

import contextvars
import os
import random
from contextlib import contextmanager
from typing import Any, Dict, Iterator, Optional, Tuple

from ray_tpu.core.config import GLOBAL_CONFIG
from ray_tpu.observability import timeline as _timeline

#: env var carrying the cluster-wide trace epoch into spawned processes
TRACE_EPOCH_ENV = "RAY_TPU_TRACE_EPOCH"


class TraceContext:
    """Ambient trace position: which trace we are in, and which span is
    the current causal parent."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id

    def wire(self) -> Tuple[str, str]:
        return (self.trace_id, self.span_id)

    def __repr__(self) -> str:
        return f"TraceContext({self.trace_id}, {self.span_id})"


_current: contextvars.ContextVar[Optional[TraceContext]] = contextvars.ContextVar(
    "ray_tpu_trace", default=None
)

_epoch: Optional[str] = None


def trace_epoch() -> str:
    """Cluster-wide epoch prefix for trace ids: inherited from the
    spawning driver via env, minted once per process otherwise."""
    global _epoch
    if _epoch is None:
        _epoch = os.environ.get(TRACE_EPOCH_ENV) or os.urandom(4).hex()
    return _epoch


def _new_span_id() -> str:
    from ray_tpu.core.ids import random_bytes

    return random_bytes(8).hex()


def _new_trace_id() -> str:
    from ray_tpu.core.ids import random_bytes

    return trace_epoch() + random_bytes(8).hex()


def current() -> Optional[TraceContext]:
    return _current.get()


def current_wire() -> Optional[Tuple[str, str]]:
    """The ambient (trace_id, span_id) pair, or None when untraced —
    what travels on specs and RPC meta slots."""
    ctx = _current.get()
    return None if ctx is None else ctx.wire()


def sampled() -> bool:
    """Roll the sampling dice for a NEW root (no ambient context)."""
    rate = GLOBAL_CONFIG.trace_sample_rate
    if rate <= 0.0:
        return False
    return rate >= 1.0 or random.random() < rate


def _decode_wire(wire) -> Optional[Tuple[str, str]]:
    """Normalize a wire context that may have round-tripped through
    msgpack (str → bytes) or pickle (unchanged)."""
    if not wire:
        return None
    try:
        t, s = wire[0], wire[1]
        if isinstance(t, (bytes, bytearray)):
            t = bytes(t).decode()
        if isinstance(s, (bytes, bytearray)):
            s = bytes(s).decode()
        return (t, s)
    except Exception:
        return None


@contextmanager
def scope(wire) -> Iterator[Optional[TraceContext]]:
    """Re-enter a received trace context (no span recorded): spans
    opened inside parent to the sender's span. No-op for None."""
    decoded = _decode_wire(wire)
    if decoded is None:
        yield None
        return
    token = _current.set(TraceContext(decoded[0], decoded[1]))
    try:
        yield _current.get()
    finally:
        _current.reset(token)


def _record(
    name: str,
    category: str,
    start_us: float,
    end_us: float,
    trace_id: str,
    span_id: str,
    parent_span_id: Optional[str],
    args: Optional[Dict[str, Any]],
) -> None:
    ev_args: Dict[str, Any] = dict(args or {})
    ev_args["trace_id"] = trace_id
    ev_args["span_id"] = span_id
    if parent_span_id:
        ev_args["parent_span_id"] = parent_span_id
    _timeline.record_event(name, category, start_us, end_us, args=ev_args)


@contextmanager
def span(name: str, category: str = "trace", **args) -> Iterator[Optional[TraceContext]]:
    """Record one span under the ambient context. ZERO-COST when no
    context is ambient: nothing is minted, nothing is recorded."""
    parent = _current.get()
    if parent is None:
        yield None
        return
    child = TraceContext(parent.trace_id, _new_span_id())
    token = _current.set(child)
    start = _timeline._now_us()
    try:
        yield child
    finally:
        _current.reset(token)
        _record(
            name, category, start, _timeline._now_us(),
            child.trace_id, child.span_id, parent.span_id, args or None,
        )


@contextmanager
def root_span(name: str, category: str = "trace", **args) -> Iterator[Optional[TraceContext]]:
    """Span that STARTS a trace at a request entry point (serve router
    dispatch, driver-side API boundaries): a child span when a context
    is already ambient, a fresh sampled root otherwise, a no-op when the
    sampler says no."""
    if _current.get() is not None:
        with span(name, category, **args) as ctx:
            yield ctx
        return
    if not sampled():
        yield None
        return
    root = TraceContext(_new_trace_id(), _new_span_id())
    token = _current.set(root)
    start = _timeline._now_us()
    try:
        yield root
    finally:
        _current.reset(token)
        _record(
            name, category, start, _timeline._now_us(),
            root.trace_id, root.span_id, None, args or None,
        )


def record_span(
    wire,
    name: str,
    start_us: float,
    end_us: float,
    category: str = "trace",
    **args,
) -> Optional[str]:
    """Record a span parented to a WIRE context without entering it —
    for code that holds timestamps from another thread (the engine step
    loop stamping per-request spans). Returns the new span id."""
    decoded = _decode_wire(wire)
    if decoded is None:
        return None
    span_id = _new_span_id()
    _record(name, category, start_us, end_us, decoded[0], span_id, decoded[1], args or None)
    return span_id


def stamp_spec(spec) -> None:
    """Submission-side stamping (CoreWorker.submit_task /
    submit_actor_task / create_actor): inherit the ambient context, or
    sample a fresh root and record an instant ``submit::`` span for it.
    The spec's ``trace_ctx`` is a per-call field, so template-spliced
    submits carry it on the wire too. Unsampled + no ambient = one
    contextvar read and one float compare."""
    ctx = _current.get()
    if ctx is None:
        if not sampled():
            return
        trace_id = _new_trace_id()
        span_id = _new_span_id()
        now = _timeline._now_us()
        _record(
            f"submit::{spec.name}", "task", now, now,
            trace_id, span_id, None, {"task_id": spec.task_id.hex()[:16]},
        )
        spec.trace_ctx = (trace_id, span_id)
        return
    # inherit: the executing side's task span parents to the CURRENT
    # span (the submitting task / router dispatch / user span)
    spec.trace_ctx = ctx.wire()


async def carry(coro, wire):
    """Await ``coro`` inside ``scope(wire)`` — how ``IoThread.run``
    forwards the caller thread's ambient trace onto the io loop
    (run_coroutine_threadsafe does not propagate contextvars)."""
    with scope(wire):
        return await coro
