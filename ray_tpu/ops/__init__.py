"""TPU compute kernels (Pallas) + reference implementations."""

from ray_tpu.ops.attention import flash_attention, reference_attention
from ray_tpu.ops.layers import apply_rope, rms_norm, rope_frequencies
from ray_tpu.ops.ring_attention import (
    ring_attention,
    ring_attention_sharded,
    ulysses_attention,
    ulysses_attention_sharded,
)

__all__ = [
    "flash_attention",
    "reference_attention",
    "ring_attention",
    "ring_attention_sharded",
    "ulysses_attention",
    "ulysses_attention_sharded",
    "rms_norm",
    "apply_rope",
    "rope_frequencies",
]
