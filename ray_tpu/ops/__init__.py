"""TPU compute kernels (Pallas) + reference implementations."""

from ray_tpu.ops.attention import flash_attention, reference_attention
from ray_tpu.ops.layers import apply_rope, rms_norm, rope_frequencies

__all__ = [
    "flash_attention",
    "reference_attention",
    "rms_norm",
    "apply_rope",
    "rope_frequencies",
]
