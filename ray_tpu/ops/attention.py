"""Flash attention for TPU (Pallas).

The hot op of the model stack: blockwise attention with online softmax so
the S×S score matrix never materializes in HBM — O(S) memory, MXU-friendly
block matmuls, fp32 accumulators with bf16-friendly inputs.

Forward and backward are both Pallas kernels wired through
``jax.custom_vjp`` (FlashAttention-2 style backward: saved logsumexp,
D = rowsum(dO·O), split dq and dk/dv passes). On non-TPU backends the
kernels run in interpreter mode so CI exercises the same code path
(fake-ICI testing strategy, SURVEY §4.3).

The reference stack has no equivalent op — attention lives inside torch
models; this kernel is the TPU-native foundation the model zoo builds on.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

DEFAULT_BLOCK = 128
_NEG_INF = -1e30


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def reference_attention(q, k, v, *, causal: bool = True, sm_scale: Optional[float] = None):
    """Pure-XLA attention (O(S^2) memory) — correctness oracle + fallback."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * sm_scale
    if causal:
        qlen, klen = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((qlen, klen), bool), k=klen - qlen)
        s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k: int, causal: bool, sm_scale: float):
    from jax.experimental import pallas as pl

    q = q_ref[0].astype(jnp.float32) * sm_scale  # [bq, d]
    block_q, d = q.shape
    seq_k = k_ref.shape[1]
    qb = pl.program_id(1)

    m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)

    num_kb = seq_k // block_k
    if causal:
        # only blocks up to (and including) the diagonal contribute
        upper = jax.lax.div((qb + 1) * block_q + block_k - 1, block_k)
        upper = jnp.minimum(upper, num_kb)
    else:
        upper = num_kb

    def body(kb, carry):
        m, l, acc = carry
        kblk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        vblk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kblk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bq, bk]
        if causal:
            q_pos = qb * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = alpha * acc + jax.lax.dot_general(
            p, vblk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, upper, body, (m0, l0, acc0))
    o_ref[0] = (acc / l).astype(o_ref.dtype)
    lse_ref[0] = m + jnp.log(l)


def _flash_fwd(q, k, v, causal: bool, sm_scale: float, block_q: int, block_k: int):
    from jax.experimental import pallas as pl

    bh, seq_q, d = q.shape
    seq_k = k.shape[1]
    grid = (bh, seq_q // block_q)
    out_shape = [
        jax.ShapeDtypeStruct(q.shape, q.dtype),
        jax.ShapeDtypeStruct((bh, seq_q, 1), jnp.float32),
    ]
    kernel = functools.partial(
        _fwd_kernel, block_k=block_k, causal=causal, sm_scale=sm_scale
    )
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, seq_k, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, seq_k, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_shape=out_shape,
        interpret=_use_interpret(),
    )(q, k, v)
    return o, lse


# ---------------------------------------------------------------------------
# backward kernels (FlashAttention-2)
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *, block_k, causal, sm_scale):
    from jax.experimental import pallas as pl

    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0]  # [block_q, 1]
    delta = delta_ref[0]
    block_q, d = q.shape
    seq_k = k_ref.shape[1]
    qb = pl.program_id(1)
    num_kb = seq_k // block_k
    if causal:
        upper = jnp.minimum(jax.lax.div((qb + 1) * block_q + block_k - 1, block_k), num_kb)
    else:
        upper = num_kb

    def body(kb, dq):
        kblk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        vblk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q * sm_scale, kblk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if causal:
            q_pos = qb * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, vblk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta) * sm_scale
        return dq + jax.lax.dot_general(
            ds, kblk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    dq = jax.lax.fori_loop(0, upper, body, jnp.zeros((block_q, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref, *, block_q, causal, sm_scale):
    from jax.experimental import pallas as pl

    kblk = k_ref[0].astype(jnp.float32)  # [bk, d]
    vblk = v_ref[0].astype(jnp.float32)
    block_k, d = kblk.shape
    seq_q = q_ref.shape[1]
    kb = pl.program_id(1)
    num_qb = seq_q // block_q
    if causal:
        lower = jax.lax.div(kb * block_k, block_q)
    else:
        lower = 0

    def body(qb, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(qb * block_q, block_q), :]
        delta = delta_ref[0, pl.ds(qb * block_q, block_q), :]
        s = jax.lax.dot_general(
            q * sm_scale, kblk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if causal:
            q_pos = qb * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse)  # [bq, bk]
        dv_new = dv + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do, vblk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta) * sm_scale
        dk_new = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return dk_new, dv_new

    dk, dv = jax.lax.fori_loop(
        lower, num_qb, body, (jnp.zeros((block_k, d), jnp.float32), jnp.zeros((block_k, d), jnp.float32))
    )
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd(causal, sm_scale, block_q, block_k, res, g):
    from jax.experimental import pallas as pl

    q, k, v, o, lse = res
    do = g
    bh, seq_q, d = q.shape
    seq_k = k.shape[1]
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1, keepdims=True)  # [bh, seq_q, 1]

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, block_k=block_k, causal=causal, sm_scale=sm_scale),
        grid=(bh, seq_q // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, seq_k, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, seq_k, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=_use_interpret(),
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, block_q=block_q, causal=causal, sm_scale=sm_scale),
        grid=(bh, seq_k // block_k),
        in_specs=[
            pl.BlockSpec((1, seq_q, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, seq_q, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, seq_q, 1), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, seq_q, 1), lambda b, j: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        interpret=_use_interpret(),
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public op
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash3(q, k, v, causal, sm_scale, block_q, block_k):
    o, _ = _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k)
    return o


def _flash3_fwd(q, k, v, causal, sm_scale, block_q, block_k):
    o, lse = _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k)
    return o, (q, k, v, o, lse)


def _flash3_bwd(causal, sm_scale, block_q, block_k, res, g):
    return _flash_bwd(causal, sm_scale, block_q, block_k, res, g)


_flash3.defvjp(_flash3_fwd, _flash3_bwd)


def flash_attention(
    q,
    k,
    v,
    *,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    block_q: int = DEFAULT_BLOCK,
    block_k: int = DEFAULT_BLOCK,
    impl: str = "auto",
):
    """Multi-head attention. q/k/v: ``[batch, heads, seq, head_dim]``.

    ``impl``: "pallas" (flash kernel), "xla" (reference), or "auto"
    (pallas on TPU, xla elsewhere — CI still covers the kernel through
    interpret-mode tests). GQA: repeat kv heads before calling.
    """
    if impl == "auto":
        impl = "pallas" if jax.default_backend() == "tpu" else "xla"
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if impl == "xla":
        return reference_attention(q, k, v, causal=causal, sm_scale=sm_scale)

    b, h, seq_q, d = q.shape
    seq_k = k.shape[2]
    block_q = min(block_q, seq_q)
    block_k = min(block_k, seq_k)
    if seq_q % block_q or seq_k % block_k:
        raise ValueError(
            f"sequence lengths ({seq_q}, {seq_k}) must be divisible by "
            f"block sizes ({block_q}, {block_k})"
        )
    qf = q.reshape(b * h, seq_q, d)
    kf = k.reshape(b * h, seq_k, d)
    vf = v.reshape(b * h, seq_k, d)
    o = _flash3(qf, kf, vf, causal, sm_scale, block_q, block_k)
    return o.reshape(b, h, seq_q, d)
