"""Flash attention for TPU (Pallas).

The hot op of the model stack: blockwise attention with online softmax so
the S×S score matrix never materializes in HBM — O(S) memory, MXU-friendly
block matmuls, fp32 accumulators with bf16-friendly inputs.

Pipelining design (the part that makes it beat plain XLA): the K/V stream
is a *grid dimension*, not an in-kernel loop — each (1, block_k, d) K/V
tile is its own BlockSpec block, so Pallas double-buffers the HBM→VMEM
tile DMAs against the MXU work of the previous tile. The online-softmax
state (m, l, acc) lives in VMEM scratch that persists across the K grid
steps (grid dims are ("parallel", "parallel", "arbitrary")); the output
tile is written once on the last K step. For causal masking the K tile
index is *clamped* at the diagonal — Pallas skips the DMA when a block
index repeats, so the masked-out upper-triangle tiles cost neither
bandwidth nor (via ``pl.when``) compute.

Forward and backward are Pallas kernels wired through ``jax.custom_vjp``
(FlashAttention-2 backward: saved logsumexp, D = rowsum(dO·O), split dq
and dk/dv passes, both K/Q-streamed the same way). On non-TPU backends
the kernels run in interpreter mode so CI exercises the same code path
(fake-ICI testing strategy, SURVEY §4.3).

The reference stack has no equivalent op — attention lives inside torch
models; this kernel is the TPU-native foundation the model zoo builds on.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def _tpu_compiler_params(pltpu):
    """jax renamed ``TPUCompilerParams`` -> ``CompilerParams`` (~0.5):
    resolve whichever this jax ships so the kernels run on both."""
    cp = getattr(pltpu, "CompilerParams", None)
    return cp if cp is not None else pltpu.TPUCompilerParams



def default_blocks(seq_q: int) -> tuple:
    """FORWARD blocks, tuned on v5e (round-5 sweep): (512, 1024) wins at
    s=2048 (67 vs 57 TFLOP/s) AND s=8192 (61 vs 56). The backward has its
    own per-bucket table (``default_bwd_blocks``) — the custom_vjp
    threads them independently, so the fwd no longer has to run
    bwd-shaped blocks or vice versa."""
    return (512, 1024)


#: Backward blocks per sequence bucket: seq_q upper bound → (block_q,
#: block_k). The backward keeps ~3x the forward's VMEM live per tile
#: (dq/dk+dv fp32 accumulators plus q, k, v, do tiles and the lse/delta
#: rows), and the dkv pass streams Q tiles innermost — so the backward
#: wants SMALLER q tiles than the forward to keep double-buffering room,
#: while big K tiles keep the MXU fed. Running forward-shaped blocks in
#: the backward is where the r05 51% (fwd) → 28-34% (fwd+bwd) MFU cliff
#: lived. Table seeded from the v5e VMEM model; bench.py emits the
#: per-bucket choice + measured fwd+bwd MFU so real-chip sweeps can
#: re-anchor it.
BWD_BLOCK_BUCKETS = (
    (1024, (256, 512)),
    (2048, (256, 1024)),
    (4096, (256, 1024)),
)
#: fallback for sequences above the largest bucket
_BWD_BLOCKS_LONG = (128, 1024)


def default_bwd_blocks(seq_q: int) -> tuple:
    """Backward (block_q, block_k) for this sequence bucket."""
    for bound, blocks in BWD_BLOCK_BUCKETS:
        if seq_q <= bound:
            return blocks
    return _BWD_BLOCKS_LONG


def _pick_block(seq: int, want: int) -> Optional[int]:
    """Largest block ≤ ``want`` that divides ``seq`` (scanning every
    candidate ≥ 128, so e.g. seq=4160 picks 320). Sequences shorter than
    128 become a single block; longer ones with no ≥128 divisor return
    None — the caller raises rather than letting a seq-sized tile blow
    VMEM."""
    if seq < 128:
        return seq
    for b in range(min(want, seq), 127, -1):
        if seq % b == 0:
            return b
    if seq <= 1024:
        return seq  # single tile still fits VMEM comfortably
    return None


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def reference_attention(q, k, v, *, causal: bool = True, sm_scale: Optional[float] = None):
    """Pure-XLA attention (O(S^2) memory) — correctness oracle + fallback."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * sm_scale
    if causal:
        qlen, klen = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((qlen, klen), bool), k=klen - qlen)
        s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


def _last_kb(qb, block_q: int, block_k: int, num_kb: int):
    """Last K tile index a causal Q tile attends to."""
    return jnp.minimum(num_kb - 1, ((qb + 1) * block_q - 1) // block_k)


def _causal_mask(s, qb, kb, block_q: int, block_k: int):
    q_pos = qb * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(q_pos >= k_pos, s, _NEG_INF)


def _apply_causal_mask(s, qb, kb, block_q: int, block_k: int, num_inner: int):
    """Shared masking policy for all three kernels. Tiles strictly below
    the diagonal need no mask; branching per tile (lax.cond) only pays
    off when diagonal tiles are a small fraction of the work (>=8 inner
    tiles — measured on v5e); below that the branch overhead exceeds the
    saved iota/compare/select."""
    if num_inner >= 8:
        on_diag = (kb + 1) * block_k > qb * block_q
        return jax.lax.cond(
            on_diag,
            lambda s: _causal_mask(s, qb, kb, block_q, block_k),
            lambda s: s,
            s,
        )
    return _causal_mask(s, qb, kb, block_q, block_k)


# ---------------------------------------------------------------------------
# forward kernel — grid (bh, num_q, num_k), K innermost ("arbitrary")
# ---------------------------------------------------------------------------

def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
    *, block_q: int, block_k: int, num_kb: int, causal: bool, sm_scale: float,
):
    from jax.experimental import pallas as pl

    qb = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    run = (kb <= _last_kb(qb, block_q, block_k, num_kb)) if causal else True

    @pl.when(run)
    def _compute():
        # Matmul inputs stay in their storage dtype (bf16 on TPU runs the
        # MXU at full rate; an fp32 upcast would quarter it) — fp32 comes
        # from the accumulator via preferred_element_type.
        q = q_ref[0]
        kblk = k_ref[0]
        vblk = v_ref[0]
        s = jax.lax.dot_general(
            q, kblk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale  # [bq, bk] fp32
        if causal:
            s = _apply_causal_mask(s, qb, kb, block_q, block_k, num_kb)
        m_prev = m_ref[...]
        l_prev = l_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        m_ref[...] = m_new
        l_ref[...] = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = alpha * acc_ref[...] + jax.lax.dot_general(
            p.astype(vblk.dtype), vblk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(kb == num_kb - 1)
    def _finalize():
        l = l_ref[...]
        o_ref[0] = (acc_ref[...] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        lse_ref[0] = m_ref[...] + jnp.log(jnp.maximum(l, 1e-30))


def _kv_head_map(h: int, hk: int):
    """Flattened (batch*q_head) grid index → flattened (batch*kv_head)
    K/V block index. GQA never materializes repeated K/V — the index map
    re-reads the shared head (Pallas skips the DMA when the block index
    repeats across consecutive q-heads)."""
    if h == hk:
        return lambda bh: bh
    group = h // hk
    return lambda bh: (bh // h) * hk + (bh % h) // group


def _flash_fwd(q, k, v, causal: bool, sm_scale: float, block_q: int, block_k: int, h: int, hk: int):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, seq_q, d = q.shape
    seq_k = k.shape[1]
    num_qb = seq_q // block_q
    num_kb = seq_k // block_k
    grid = (bh, num_qb, num_kb)
    kvh = _kv_head_map(h, hk)

    if causal:
        # Clamp the K tile index at this Q tile's diagonal: repeated block
        # indices skip the DMA, so masked-out tiles cost no bandwidth.
        kv_idx = lambda b, i, j: (kvh(b), jnp.minimum(j, _last_kb(i, block_q, block_k, num_kb)), 0)
    else:
        kv_idx = lambda b, i, j: (kvh(b), j, 0)

    out_shape = [
        jax.ShapeDtypeStruct(q.shape, q.dtype),
        jax.ShapeDtypeStruct((bh, seq_q, 1), jnp.float32),
    ]
    kernel = functools.partial(
        _fwd_kernel,
        block_q=block_q,
        block_k=block_k,
        num_kb=num_kb,
        causal=causal,
        sm_scale=sm_scale,
    )
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), kv_idx),
            pl.BlockSpec((1, block_k, d), kv_idx),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        compiler_params=_tpu_compiler_params(pltpu)(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=_use_interpret(),
    )(q, k, v)
    return o, lse


# ---------------------------------------------------------------------------
# backward kernels (FlashAttention-2) — both streamed like the forward
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_acc_ref,
    *, block_q: int, block_k: int, num_kb: int, causal: bool, sm_scale: float,
):
    from jax.experimental import pallas as pl

    qb = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        dq_acc_ref[...] = jnp.zeros_like(dq_acc_ref)

    run = (kb <= _last_kb(qb, block_q, block_k, num_kb)) if causal else True

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]
        delta = delta_ref[0]
        kblk = k_ref[0]
        vblk = v_ref[0]
        s = jax.lax.dot_general(
            q, kblk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale
        if causal:
            s = _apply_causal_mask(s, qb, kb, block_q, block_k, num_kb)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, vblk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = (p * (dp - delta) * sm_scale).astype(kblk.dtype)
        dq_acc_ref[...] += jax.lax.dot_general(
            ds, kblk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(kb == num_kb - 1)
    def _finalize():
        dq_ref[0] = dq_acc_ref[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_acc_ref, dv_acc_ref,
    *, block_q: int, block_k: int, num_qb: int, causal: bool, sm_scale: float,
):
    # Inner grid dim is (group * num_qb): for GQA each kv head's dk/dv
    # accumulates over every q head in its group before the final write.
    from jax.experimental import pallas as pl

    kb = pl.program_id(1)
    inner = pl.program_id(2)
    qb = inner % num_qb

    @pl.when(inner == 0)
    def _init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

    run = (qb >= (kb * block_k) // block_q) if causal else True

    @pl.when(run)
    def _compute():
        kblk = k_ref[0]
        vblk = v_ref[0]
        q = q_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]
        delta = delta_ref[0]
        s = jax.lax.dot_general(
            q, kblk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale
        if causal:
            s = _apply_causal_mask(s, qb, kb, block_q, block_k, num_qb)
        p = jnp.exp(s - lse)  # [bq, bk]
        dv_acc_ref[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, vblk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = (p * (dp - delta) * sm_scale).astype(q.dtype)
        dk_acc_ref[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(inner == pl.num_programs(2) - 1)
    def _finalize():
        dk_ref[0] = dk_acc_ref[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc_ref[...].astype(dv_ref.dtype)


def _flash_bwd(causal, sm_scale, block_q, block_k, h, hk, res, g):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    q, k, v, o, lse = res
    do = g
    bh, seq_q, d = q.shape
    seq_k = k.shape[1]
    num_qb = seq_q // block_q
    num_kb = seq_k // block_k
    group = h // hk
    kvh = _kv_head_map(h, hk)
    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1, keepdims=True
    )  # [bh, seq_q, 1]

    if causal:
        kv_idx = lambda b, i, j: (kvh(b), jnp.minimum(j, _last_kb(i, block_q, block_k, num_kb)), 0)
    else:
        kv_idx = lambda b, i, j: (kvh(b), j, 0)
    q_idx = lambda b, i, j: (b, i, 0)

    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel,
            block_q=block_q, block_k=block_k, num_kb=num_kb,
            causal=causal, sm_scale=sm_scale,
        ),
        grid=(bh, num_qb, num_kb),
        in_specs=[
            pl.BlockSpec((1, block_q, d), q_idx),
            pl.BlockSpec((1, block_k, d), kv_idx),
            pl.BlockSpec((1, block_k, d), kv_idx),
            pl.BlockSpec((1, block_q, d), q_idx),
            pl.BlockSpec((1, block_q, 1), q_idx),
            pl.BlockSpec((1, block_q, 1), q_idx),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), q_idx),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_tpu_compiler_params(pltpu)(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=_use_interpret(),
    )(q, k, v, do, lse, delta)

    # dkv pass runs over KV heads; the inner dim walks every q head in
    # the GQA group × every q tile. bkv → base q-head block for the group.
    def q_head_base(bkv):
        return (bkv // hk) * h + (bkv % hk) * group if h != hk else bkv

    if causal:
        # Clamp the Q tile index from below at the diagonal: tiles above
        # it contribute nothing to this K tile's dk/dv.
        qd_idx = lambda b, j, i: (
            q_head_base(b) + i // num_qb,
            jnp.maximum(i % num_qb, (j * block_k) // block_q), 0,
        )
    else:
        qd_idx = lambda b, j, i: (q_head_base(b) + i // num_qb, i % num_qb, 0)
    kv2_idx = lambda b, j, i: (b, j, 0)

    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel,
            block_q=block_q, block_k=block_k, num_qb=num_qb,
            causal=causal, sm_scale=sm_scale,
        ),
        grid=(k.shape[0], num_kb, group * num_qb),
        in_specs=[
            pl.BlockSpec((1, block_q, d), qd_idx),
            pl.BlockSpec((1, block_k, d), kv2_idx),
            pl.BlockSpec((1, block_k, d), kv2_idx),
            pl.BlockSpec((1, block_q, d), qd_idx),
            pl.BlockSpec((1, block_q, 1), qd_idx),
            pl.BlockSpec((1, block_q, 1), qd_idx),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), kv2_idx),
            pl.BlockSpec((1, block_k, d), kv2_idx),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=_tpu_compiler_params(pltpu)(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=_use_interpret(),
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public op
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10))
def _flash3(q, k, v, causal, sm_scale, block_q, block_k, bwd_block_q, bwd_block_k, h, hk):
    o, _ = _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k, h, hk)
    return o


def _flash3_fwd(q, k, v, causal, sm_scale, block_q, block_k, bwd_block_q, bwd_block_k, h, hk):
    o, lse = _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k, h, hk)
    return o, (q, k, v, o, lse)


def _flash3_bwd(causal, sm_scale, block_q, block_k, bwd_block_q, bwd_block_k, h, hk, res, g):
    # the backward runs ITS tuned blocks — the fwd blocks only shaped the
    # saved residuals (q/k/v/o/lse are whole arrays, not tiles)
    return _flash_bwd(causal, sm_scale, bwd_block_q, bwd_block_k, h, hk, res, g)


_flash3.defvjp(_flash3_fwd, _flash3_bwd)


def flash_attention(
    q,
    k,
    v,
    *,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    block_q_bwd: Optional[int] = None,
    block_k_bwd: Optional[int] = None,
    impl: str = "auto",
):
    """Multi-head attention. q: ``[batch, heads, seq, head_dim]``;
    k/v: ``[batch, kv_heads, seq, head_dim]`` where ``heads`` is a
    multiple of ``kv_heads`` — GQA is handled *inside* the kernel by
    mapping each q head's K/V block index onto its shared kv head, so
    repeated K/V never hits HBM (reference pattern: KV-repeat before
    torch SDPA; here the index map replaces the repeat).

    ``block_q``/``block_k`` tile the FORWARD; ``block_q_bwd``/
    ``block_k_bwd`` tile the backward independently (default: the
    per-sequence-bucket table ``default_bwd_blocks`` — the backward's
    VMEM/streaming profile wants different tiles than the forward).

    ``impl``: "pallas" (flash kernel), "xla" (reference), or "auto"
    (pallas on TPU, xla elsewhere — CI still covers the kernel through
    interpret-mode tests).
    """
    if impl == "auto":
        impl = "pallas" if jax.default_backend() == "tpu" else "xla"
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    b, h, seq_q, d = q.shape
    hk = k.shape[1]
    if h % hk:
        raise ValueError(f"q heads ({h}) must be a multiple of kv heads ({hk})")
    if impl == "xla":
        if hk != h:
            k = jnp.repeat(k, h // hk, axis=1)
            v = jnp.repeat(v, h // hk, axis=1)
        return reference_attention(q, k, v, causal=causal, sm_scale=sm_scale)

    seq_k = k.shape[2]
    dbq, dbk = default_blocks(seq_q)
    bbq, bbk = default_bwd_blocks(seq_q)
    block_q = _pick_block(seq_q, block_q or dbq)
    block_k = _pick_block(seq_k, block_k or dbk)
    block_q_bwd = _pick_block(seq_q, block_q_bwd or bbq)
    block_k_bwd = _pick_block(seq_k, block_k_bwd or bbk)
    if None in (block_q, block_k, block_q_bwd, block_k_bwd):
        raise ValueError(
            f"sequence lengths ({seq_q}, {seq_k}) have no block divisor "
            f"≥128 — pad the sequence to a multiple of 128"
        )
    qf = q.reshape(b * h, seq_q, d)
    kf = k.reshape(b * hk, seq_k, d)
    vf = v.reshape(b * hk, seq_k, d)
    o = _flash3(
        qf, kf, vf, causal, sm_scale, block_q, block_k,
        block_q_bwd, block_k_bwd, h, hk,
    )
    return o.reshape(b, h, seq_q, d)
