"""Flash attention for TPU (Pallas).

The hot op of the model stack: blockwise attention with online softmax so
the S×S score matrix never materializes in HBM — O(S) memory, MXU-friendly
block matmuls, fp32 accumulators with bf16-friendly inputs.

Pipelining design (the part that makes it beat plain XLA): the K/V stream
is a *grid dimension*, not an in-kernel loop — each (1, block_k, d) K/V
tile is its own BlockSpec block, so Pallas double-buffers the HBM→VMEM
tile DMAs against the MXU work of the previous tile. The online-softmax
state (m, l, acc) lives in VMEM scratch that persists across the K grid
steps (grid dims are ("parallel", "parallel", "arbitrary")); the output
tile is written once on the last K step. For causal masking the K tile
index is *clamped* at the diagonal — Pallas skips the DMA when a block
index repeats, so the masked-out upper-triangle tiles cost neither
bandwidth nor (via ``pl.when``) compute.

Forward and backward are Pallas kernels wired through ``jax.custom_vjp``
(FlashAttention-2 backward: saved logsumexp, D = rowsum(dO·O), split dq
and dk/dv passes, both K/Q-streamed the same way). On non-TPU backends
the kernels run in interpreter mode so CI exercises the same code path
(fake-ICI testing strategy, SURVEY §4.3).

The reference stack has no equivalent op — attention lives inside torch
models; this kernel is the TPU-native foundation the model zoo builds on.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

DEFAULT_BLOCK_Q = 512  # tuned on v5e: (512, 1024) wins at s=2048..8192
DEFAULT_BLOCK_K = 1024
_NEG_INF = -1e30


def _pick_block(seq: int, want: int) -> Optional[int]:
    """Largest block ≤ ``want`` that divides ``seq`` (scanning every
    candidate ≥ 128, so e.g. seq=4160 picks 320). Sequences shorter than
    128 become a single block; longer ones with no ≥128 divisor return
    None — the caller raises rather than letting a seq-sized tile blow
    VMEM."""
    if seq < 128:
        return seq
    for b in range(min(want, seq), 127, -1):
        if seq % b == 0:
            return b
    if seq <= 1024:
        return seq  # single tile still fits VMEM comfortably
    return None


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def reference_attention(q, k, v, *, causal: bool = True, sm_scale: Optional[float] = None):
    """Pure-XLA attention (O(S^2) memory) — correctness oracle + fallback."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * sm_scale
    if causal:
        qlen, klen = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((qlen, klen), bool), k=klen - qlen)
        s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


def _last_kb(qb, block_q: int, block_k: int, num_kb: int):
    """Last K tile index a causal Q tile attends to."""
    return jnp.minimum(num_kb - 1, ((qb + 1) * block_q - 1) // block_k)


def _causal_mask(s, qb, kb, block_q: int, block_k: int):
    q_pos = qb * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(q_pos >= k_pos, s, _NEG_INF)


# ---------------------------------------------------------------------------
# forward kernel — grid (bh, num_q, num_k), K innermost ("arbitrary")
# ---------------------------------------------------------------------------

def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
    *, block_q: int, block_k: int, num_kb: int, causal: bool, sm_scale: float,
):
    from jax.experimental import pallas as pl

    qb = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    run = (kb <= _last_kb(qb, block_q, block_k, num_kb)) if causal else True

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * sm_scale
        kblk = k_ref[0].astype(jnp.float32)
        vblk = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kblk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bq, bk]
        if causal:
            s = _causal_mask(s, qb, kb, block_q, block_k)
        m_prev = m_ref[...]
        l_prev = l_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        m_ref[...] = m_new
        l_ref[...] = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = alpha * acc_ref[...] + jax.lax.dot_general(
            p, vblk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(kb == num_kb - 1)
    def _finalize():
        l = l_ref[...]
        o_ref[0] = (acc_ref[...] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        lse_ref[0] = m_ref[...] + jnp.log(jnp.maximum(l, 1e-30))


def _flash_fwd(q, k, v, causal: bool, sm_scale: float, block_q: int, block_k: int):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, seq_q, d = q.shape
    seq_k = k.shape[1]
    num_qb = seq_q // block_q
    num_kb = seq_k // block_k
    grid = (bh, num_qb, num_kb)

    if causal:
        # Clamp the K tile index at this Q tile's diagonal: repeated block
        # indices skip the DMA, so masked-out tiles cost no bandwidth.
        kv_idx = lambda b, i, j: (b, jnp.minimum(j, _last_kb(i, block_q, block_k, num_kb)), 0)
    else:
        kv_idx = lambda b, i, j: (b, j, 0)

    out_shape = [
        jax.ShapeDtypeStruct(q.shape, q.dtype),
        jax.ShapeDtypeStruct((bh, seq_q, 1), jnp.float32),
    ]
    kernel = functools.partial(
        _fwd_kernel,
        block_q=block_q,
        block_k=block_k,
        num_kb=num_kb,
        causal=causal,
        sm_scale=sm_scale,
    )
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), kv_idx),
            pl.BlockSpec((1, block_k, d), kv_idx),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=_use_interpret(),
    )(q, k, v)
    return o, lse


# ---------------------------------------------------------------------------
# backward kernels (FlashAttention-2) — both streamed like the forward
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_acc_ref,
    *, block_q: int, block_k: int, num_kb: int, causal: bool, sm_scale: float,
):
    from jax.experimental import pallas as pl

    qb = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        dq_acc_ref[...] = jnp.zeros_like(dq_acc_ref)

    run = (kb <= _last_kb(qb, block_q, block_k, num_kb)) if causal else True

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]
        delta = delta_ref[0]
        kblk = k_ref[0].astype(jnp.float32)
        vblk = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q * sm_scale, kblk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if causal:
            s = _causal_mask(s, qb, kb, block_q, block_k)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, vblk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta) * sm_scale
        dq_acc_ref[...] += jax.lax.dot_general(
            ds, kblk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(kb == num_kb - 1)
    def _finalize():
        dq_ref[0] = dq_acc_ref[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_acc_ref, dv_acc_ref,
    *, block_q: int, block_k: int, num_qb: int, causal: bool, sm_scale: float,
):
    from jax.experimental import pallas as pl

    kb = pl.program_id(1)
    qb = pl.program_id(2)

    @pl.when(qb == 0)
    def _init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

    run = (qb >= (kb * block_k) // block_q) if causal else True

    @pl.when(run)
    def _compute():
        kblk = k_ref[0].astype(jnp.float32)
        vblk = v_ref[0].astype(jnp.float32)
        q = q_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]
        delta = delta_ref[0]
        s = jax.lax.dot_general(
            q * sm_scale, kblk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if causal:
            s = _causal_mask(s, qb, kb, block_q, block_k)
        p = jnp.exp(s - lse)  # [bq, bk]
        dv_acc_ref[...] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do, vblk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta) * sm_scale
        dk_acc_ref[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(qb == num_qb - 1)
    def _finalize():
        dk_ref[0] = dk_acc_ref[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc_ref[...].astype(dv_ref.dtype)


def _flash_bwd(causal, sm_scale, block_q, block_k, res, g):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    q, k, v, o, lse = res
    do = g
    bh, seq_q, d = q.shape
    seq_k = k.shape[1]
    num_qb = seq_q // block_q
    num_kb = seq_k // block_k
    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1, keepdims=True
    )  # [bh, seq_q, 1]

    if causal:
        kv_idx = lambda b, i, j: (b, jnp.minimum(j, _last_kb(i, block_q, block_k, num_kb)), 0)
    else:
        kv_idx = lambda b, i, j: (b, j, 0)
    q_idx = lambda b, i, j: (b, i, 0)

    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel,
            block_q=block_q, block_k=block_k, num_kb=num_kb,
            causal=causal, sm_scale=sm_scale,
        ),
        grid=(bh, num_qb, num_kb),
        in_specs=[
            pl.BlockSpec((1, block_q, d), q_idx),
            pl.BlockSpec((1, block_k, d), kv_idx),
            pl.BlockSpec((1, block_k, d), kv_idx),
            pl.BlockSpec((1, block_q, d), q_idx),
            pl.BlockSpec((1, block_q, 1), q_idx),
            pl.BlockSpec((1, block_q, 1), q_idx),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), q_idx),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=_use_interpret(),
    )(q, k, v, do, lse, delta)

    if causal:
        # Clamp the Q tile index from below at the diagonal: tiles above
        # it contribute nothing to this K tile's dk/dv.
        qd_idx = lambda b, j, i: (
            b, jnp.maximum(i, (j * block_k) // block_q), 0
        )
    else:
        qd_idx = lambda b, j, i: (b, i, 0)
    kv2_idx = lambda b, j, i: (b, j, 0)

    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel,
            block_q=block_q, block_k=block_k, num_qb=num_qb,
            causal=causal, sm_scale=sm_scale,
        ),
        grid=(bh, num_kb, num_qb),
        in_specs=[
            pl.BlockSpec((1, block_q, d), qd_idx),
            pl.BlockSpec((1, block_k, d), kv2_idx),
            pl.BlockSpec((1, block_k, d), kv2_idx),
            pl.BlockSpec((1, block_q, d), qd_idx),
            pl.BlockSpec((1, block_q, 1), qd_idx),
            pl.BlockSpec((1, block_q, 1), qd_idx),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), kv2_idx),
            pl.BlockSpec((1, block_k, d), kv2_idx),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=_use_interpret(),
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public op
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash3(q, k, v, causal, sm_scale, block_q, block_k):
    o, _ = _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k)
    return o


def _flash3_fwd(q, k, v, causal, sm_scale, block_q, block_k):
    o, lse = _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k)
    return o, (q, k, v, o, lse)


def _flash3_bwd(causal, sm_scale, block_q, block_k, res, g):
    return _flash_bwd(causal, sm_scale, block_q, block_k, res, g)


_flash3.defvjp(_flash3_fwd, _flash3_bwd)


def flash_attention(
    q,
    k,
    v,
    *,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    impl: str = "auto",
):
    """Multi-head attention. q/k/v: ``[batch, heads, seq, head_dim]``.

    ``impl``: "pallas" (flash kernel), "xla" (reference), or "auto"
    (pallas on TPU, xla elsewhere — CI still covers the kernel through
    interpret-mode tests). GQA: repeat kv heads before calling.
    """
    if impl == "auto":
        impl = "pallas" if jax.default_backend() == "tpu" else "xla"
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if impl == "xla":
        return reference_attention(q, k, v, causal=causal, sm_scale=sm_scale)

    b, h, seq_q, d = q.shape
    seq_k = k.shape[2]
    block_q = _pick_block(seq_q, block_q)
    block_k = _pick_block(seq_k, block_k)
    if block_q is None or block_k is None:
        raise ValueError(
            f"sequence lengths ({seq_q}, {seq_k}) have no block divisor "
            f"≥128 — pad the sequence to a multiple of 128"
        )
    qf = q.reshape(b * h, seq_q, d)
    kf = k.reshape(b * h, seq_k, d)
    vf = v.reshape(b * h, seq_k, d)
    o = _flash3(qf, kf, vf, causal, sm_scale, block_q, block_k)
    return o.reshape(b, h, seq_q, d)
