"""Model-layer ops: RMSNorm, rotary embeddings.

Plain-XLA implementations — these fuse into neighboring ops on TPU (XLA
handles elementwise fusion; Pallas is reserved for the ops XLA can't fuse
well, i.e. attention)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x, weight, eps: float = 1e-6):
    """RMSNorm in fp32 accumulation (Llama-style)."""
    dtype = x.dtype
    x = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    x = x * jax.lax.rsqrt(var + eps)
    return (x * weight.astype(jnp.float32)).astype(dtype)


def rope_frequencies(head_dim: int, max_seq: int, theta: float = 10000.0):
    """Precomputed cos/sin tables: ``[max_seq, head_dim//2]``."""
    inv = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_seq, dtype=jnp.float32)
    freqs = jnp.outer(t, inv)
    return jnp.cos(freqs), jnp.sin(freqs)


def apply_rope(x, cos, sin, positions=None):
    """Rotary position embedding. x: ``[batch, heads, seq, head_dim]``;
    cos/sin: ``[max_seq, head_dim//2]``; positions: ``[batch, seq]`` or
    None (implicit arange — supports sequence-parallel offsets)."""
    seq = x.shape[2]
    if positions is None:
        c = cos[:seq][None, None, :, :]
        s = sin[:seq][None, None, :, :]
    else:
        c = cos[positions][:, None, :, :]
        s = sin[positions][:, None, :, :]
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate(
        [x1 * c - x2 * s, x2 * c + x1 * s], axis=-1
    ).astype(x.dtype)
