"""Mixture-of-Experts FFN with expert parallelism.

Reference: absent (SURVEY §2.4 — EP is a build-new item). Design is the
GSPMD dense-dispatch recipe (Switch/GShard): top-k routing produces a
capacity-limited one-hot dispatch tensor; dispatch/combine are einsums,
expert FFNs run batched over the expert dim, and sharding the expert
dim over the ``expert`` mesh axis makes XLA insert the all-to-alls over
ICI — no hand-written collectives (scaling-book recipe).

Capacity semantics: each expert processes at most
``capacity = ceil(tokens/experts * capacity_factor)`` tokens; overflow
tokens pass through unchanged (their combine weight is zero) — the
standard Switch Transformer drop policy."""

from __future__ import annotations

import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp


def init_moe_params(
    rng: jax.Array,
    dim: int,
    hidden: int,
    num_experts: int,
    dtype=jnp.float32,
) -> Dict[str, Any]:
    """Router + per-expert SwiGLU FFN params (stacked over experts)."""
    kr, kg, ku, kd = jax.random.split(rng, 4)
    scale_in = 1.0 / math.sqrt(dim)
    scale_hid = 1.0 / math.sqrt(hidden)
    return {
        "router": (jax.random.normal(kr, (dim, num_experts), jnp.float32) * scale_in),
        "w_gate": (jax.random.normal(kg, (num_experts, dim, hidden), jnp.float32) * scale_in).astype(dtype),
        "w_up": (jax.random.normal(ku, (num_experts, dim, hidden), jnp.float32) * scale_in).astype(dtype),
        "w_down": (jax.random.normal(kd, (num_experts, hidden, dim), jnp.float32) * scale_hid).astype(dtype),
    }


def moe_logical_axes() -> Dict[str, Tuple[Optional[str], ...]]:
    """Logical axes for the params above (rules map "expert"→EXPERT mesh
    axis so expert FFNs shard with all-to-all dispatch inserted by XLA)."""
    return {
        "router": (None, None),
        "w_gate": ("expert", "embed", "mlp"),
        "w_up": ("expert", "embed", "mlp"),
        "w_down": ("expert", "mlp", "embed"),
    }


def moe_ffn(
    params: Dict[str, Any],
    x: jnp.ndarray,
    *,
    top_k: int = 2,
    capacity_factor: float = 1.25,
    router_noise: float = 0.0,
    rng: Optional[jax.Array] = None,
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """x: [B, S, d] → (out [B, S, d], aux dict with load-balance loss).

    Dense dispatch: one-hot [T, E, C] tensors route tokens to expert
    slots; dropped (over-capacity) tokens contribute zero and fall back
    to the residual stream."""
    B, S, d = x.shape
    E = params["router"].shape[1]
    T = B * S
    # GShard capacity: expected per-expert load is top_k*T/E assignments
    # under balanced routing — omitting top_k would silently drop
    # ~(1 - cf/top_k) of dispatches from step 0
    capacity = max(1, int(math.ceil(top_k * T / E * capacity_factor)))

    xt = x.reshape(T, d)
    logits = (xt.astype(jnp.float32) @ params["router"])  # [T, E]
    if router_noise > 0.0 and rng is not None:
        logits = logits + router_noise * jax.random.normal(rng, logits.shape)
    probs = jax.nn.softmax(logits, axis=-1)

    # top-k expert choices per token
    gate_vals, expert_idx = jax.lax.top_k(probs, top_k)  # [T, k]
    # renormalize the kept gates
    gate_vals = gate_vals / jnp.maximum(gate_vals.sum(-1, keepdims=True), 1e-9)

    # per-(token, choice) slot position within the chosen expert, by
    # arrival order: cumsum of one-hot over the flattened (T*k) axis
    choice_onehot = jax.nn.one_hot(expert_idx, E, dtype=jnp.int32)  # [T, k, E]
    flat_choice = choice_onehot.reshape(T * top_k, E)
    positions = jnp.cumsum(flat_choice, axis=0) - flat_choice  # slots before me
    slot = (positions * flat_choice).sum(-1).reshape(T, top_k)  # [T, k]
    kept = slot < capacity

    gate_vals = gate_vals * kept.astype(gate_vals.dtype)

    # dispatch [T, E, C] (bool) and combine [T, E, C] (weighted)
    slot_onehot = jax.nn.one_hot(slot, capacity, dtype=jnp.float32)  # [T, k, C]
    disp = jnp.einsum("tke,tkc->tec", choice_onehot.astype(jnp.float32), slot_onehot)
    combine = jnp.einsum("tk,tke,tkc->tec", gate_vals, choice_onehot.astype(jnp.float32), slot_onehot)

    # route tokens to expert slots: [E, C, d]
    expert_in = jnp.einsum("tec,td->ecd", disp, xt.astype(jnp.float32)).astype(x.dtype)

    # expert FFN batched over E (sharded over the expert mesh axis)
    h_gate = jnp.einsum("ecd,edh->ech", expert_in, params["w_gate"])
    h_up = jnp.einsum("ecd,edh->ech", expert_in, params["w_up"])
    expert_out = jnp.einsum("ech,ehd->ecd", jax.nn.silu(h_gate) * h_up, params["w_down"])

    out = jnp.einsum("tec,ecd->td", combine, expert_out.astype(jnp.float32))
    out = out.reshape(B, S, d).astype(x.dtype)

    # Switch load-balance aux loss: E * sum_e f_e * p_e
    me = probs.mean(axis=0)  # mean router prob per expert
    ce = choice_onehot[:, 0, :].astype(jnp.float32).mean(axis=0)  # top-1 fraction
    aux_loss = E * jnp.sum(me * ce)
    return out, {"aux_loss": aux_loss, "dropped_fraction": 1.0 - kept.astype(jnp.float32).mean()}
