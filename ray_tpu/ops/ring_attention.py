"""Sequence-parallel attention: ring attention + Ulysses all-to-all.

The long-context north star (SURVEY §5.7 — absent in the reference, which
has only the raw p2p collectives a user could hand-build this from,
``util/collective/collective.py:531,594``). Two first-class variants:

* **Ring attention** (``ring_attention`` / ``ring_attention_sharded``):
  Q stays put; K/V chunks rotate around the ``seq`` mesh axis via
  ``jax.lax.ppermute`` while each step's partial attention is merged with
  the running online-softmax state (m, l, acc). The S×S score matrix
  never exists — per device the working set is O(S_local²) per step and
  the K/V ring traffic rides ICI neighbor links. The rotation for step
  t+1 is issued before step t's compute so XLA's async collectives can
  overlap communication with the chunk matmuls.

* **Ulysses** (``ulysses_attention`` / ``ulysses_attention_sharded``):
  one ``all_to_all`` swaps the sharded axis from sequence to heads, each
  device then runs *dense local* attention (the pallas flash kernel) on
  full sequences for its head subset, and a second ``all_to_all`` swaps
  back. Cheaper collectives than the ring for moderate S (2 all-to-alls
  vs n-1 permutes) but caps the seq-parallel degree at n_kv_heads.

Both are differentiable: the ring scan body is ``jax.checkpoint``-ed so
the backward pass recomputes chunk scores instead of storing the
O(S_local·S) slices (blockwise-remat, the ring-attention paper recipe),
and ``ppermute``/``all_to_all`` transpose to their inverses.

The ``*_sharded`` wrappers apply ``jax.shard_map`` over the canonical
mesh axes (batch over data/fsdp, heads over tensor, sequence over seq)
so callers hand in global arrays under ``jit`` as usual.
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ray_tpu.parallel.mesh import shard_map_compat, DATA, FSDP, SEQUENCE, TENSOR

_NEG_INF = -1e30


def _chunk_attention(q, k, v, q_offset, k_offset, *, causal: bool, sm_scale: float):
    """Partial attention of a local Q block against one K/V chunk.

    q: [b, h, sq, d]; k/v: [b, h, sk, d]. Returns the *unnormalized*
    accumulator pv = P·V (f32), the row max m and row sum l of the
    masked, max-shifted scores — the online-softmax sufficient stats.
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32)
    s = s * sm_scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        q_pos = q_offset + jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        k_pos = k_offset + jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        mask = q_pos >= k_pos
        s = jnp.where(mask, s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)  # [b,h,sq,1]
    p = jnp.exp(s - m)
    if causal:
        # Rows with every position masked have m == _NEG_INF and would
        # otherwise get p == exp(0) == 1 on the masked entries.
        p = jnp.where(mask, p, 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    pv = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return pv, m, l


def _merge(acc, m, l, pv_i, m_i, l_i):
    """Merge one chunk's stats into the running online-softmax state."""
    m_new = jnp.maximum(m, m_i)
    alpha = jnp.exp(m - m_new)
    beta = jnp.exp(m_i - m_new)
    return acc * alpha + pv_i * beta, m_new, l * alpha + l_i * beta


def ring_attention(
    q,
    k,
    v,
    *,
    axis_name: str = SEQUENCE,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    kv_repeat: int = 1,
):
    """Ring attention over a sequence-sharded mesh axis.

    Must be called inside ``shard_map`` (or any SPMD context where
    ``axis_name`` is bound). q: ``[b, h, s_local, d]``; k/v:
    ``[b, h // kv_repeat, s_local, d]``. Sequence is sharded
    contiguously, so shard i holds global positions
    ``[i*s_local, (i+1)*s_local)``.

    GQA: pass the *unrepeated* K/V plus ``kv_repeat`` — the ring rotates
    the small KV heads and repeats locally per chunk, so ICI traffic
    keeps GQA's 1/group_size savings.

    n devices → n chunk computes but only n-1 ppermutes: the local chunk
    is folded in during step 0 and the last received chunk is consumed
    outside the scan without a further rotation.
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    s_loc = q.shape[2]
    q32 = q.astype(jnp.float32)
    q_offset = my_idx * s_loc

    def rep(x):
        return jnp.repeat(x, kv_repeat, axis=1) if kv_repeat > 1 else x

    # chunk at device j moves to device j-1 each step, so after t steps
    # device i holds the chunk originally owned by (i + t) % n.
    perm = [(j, (j - 1) % n) for j in range(n)]

    @jax.checkpoint
    def merge_chunk(acc, m, l, kc, vc, t):
        """Fold one K/V chunk into the online-softmax state; checkpointed
        so backward recomputes the O(s_loc²) scores per chunk instead of
        storing them (blockwise remat)."""
        k_offset = ((my_idx + t) % n) * s_loc
        pv_i, m_i, l_i = _chunk_attention(
            q32, rep(kc), rep(vc), q_offset, k_offset, causal=causal, sm_scale=sm_scale
        )
        return _merge(acc, m, l, pv_i, m_i, l_i)

    def step(carry, t):
        acc, m, l, kc, vc = carry
        # Issue the rotation for the NEXT step before this step's compute:
        # no data dependence between them, so XLA can overlap the ppermute
        # with the chunk matmuls.
        kn = jax.lax.ppermute(kc, axis_name, perm)
        vn = jax.lax.ppermute(vc, axis_name, perm)
        acc, m, l = merge_chunk(acc, m, l, kc, vc, t)
        return (acc, m, l, kn, vn), None

    b, h, _, d = q.shape
    acc0 = jnp.zeros((b, h, s_loc, d), jnp.float32)
    m0 = jnp.full((b, h, s_loc, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s_loc, 1), jnp.float32)
    (acc, m, l, kc, vc), _ = jax.lax.scan(
        step, (acc0, m0, l0, k, v), jnp.arange(n - 1)
    )
    # Final chunk: consumed in place, no further rotation (n-1 permutes).
    acc, m, l = merge_chunk(acc, m, l, kc, vc, n - 1)
    return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)


def ring_attention_sharded(
    q,
    k,
    v,
    mesh,
    *,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    kv_repeat: int = 1,
    seq_axis: str = SEQUENCE,
    batch_axes: Tuple[str, ...] = (DATA, FSDP),
    head_axis: str = TENSOR,
):
    """Global-array entry point: shard_map the ring over ``mesh``.

    q: ``[batch, heads, seq, head_dim]``; k/v may carry fewer (KV) heads
    with ``kv_repeat`` set (GQA) — the small KV heads are what rotates.
    Batch rides the data/fsdp axes, heads the tensor axis, sequence the
    seq axis.
    """
    spec = P(batch_axes, head_axis, seq_axis, None)
    fn = shard_map_compat(
        functools.partial(
            ring_attention,
            axis_name=seq_axis,
            causal=causal,
            sm_scale=sm_scale,
            kv_repeat=kv_repeat,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)


def ulysses_attention(
    q,
    k,
    v,
    *,
    axis_name: str = SEQUENCE,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    impl: str = "auto",
):
    """Ulysses (DeepSpeed-style) sequence parallelism.

    Inside shard_map with q/k/v ``[b, h, s_local, d]``: all-to-all
    redistributes from seq-sharded to head-sharded, dense local (flash)
    attention runs on the full sequence for h/n heads, and the inverse
    all-to-all restores sequence sharding. Requires ``h % n == 0``.
    """
    from ray_tpu.ops.attention import flash_attention

    n = jax.lax.psum(1, axis_name)
    h = q.shape[1]
    h_kv = k.shape[1]
    if h % n != 0:
        raise ValueError(f"ulysses needs heads ({h}) divisible by seq-parallel degree ({n})")
    kv_repeat = h // h_kv

    # [b, h, s_loc, d] -> [b, h/n, s_loc*n, d]
    def seq_to_heads(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    def heads_to_seq(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    qh = seq_to_heads(q)
    if h_kv % n == 0:
        # GQA: all-to-all the small KV heads, repeat locally afterwards —
        # keeps the collective at 1/group_size the repeated volume.
        kh, vh = seq_to_heads(k), seq_to_heads(v)
        if kv_repeat > 1:
            kh = jnp.repeat(kh, kv_repeat, axis=1)
            vh = jnp.repeat(vh, kv_repeat, axis=1)
    else:
        # Too few KV heads to split n ways: repeat first (full volume).
        kh = seq_to_heads(jnp.repeat(k, kv_repeat, axis=1))
        vh = seq_to_heads(jnp.repeat(v, kv_repeat, axis=1))
    o = flash_attention(qh, kh, vh, causal=causal, sm_scale=sm_scale, impl=impl)
    return heads_to_seq(o)


def ulysses_attention_sharded(
    q,
    k,
    v,
    mesh,
    *,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    seq_axis: str = SEQUENCE,
    batch_axes: Tuple[str, ...] = (DATA, FSDP),
    head_axis: str = TENSOR,
    impl: str = "auto",
):
    spec = P(batch_axes, head_axis, seq_axis, None)
    fn = shard_map_compat(
        functools.partial(
            ulysses_attention,
            axis_name=seq_axis,
            causal=causal,
            sm_scale=sm_scale,
            impl=impl,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
