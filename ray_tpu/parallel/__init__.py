"""TPU parallelism layer: meshes, shardings, collectives.

This is the first-class replacement for the reference's torch.distributed
/ NCCL / ray.util.collective stack (SURVEY §2.4, §5.8): dense collectives
happen *inside* compiled XLA programs over ICI; the runtime's job is gang
placement and coordination. Cross-host/DCN data movement rides the object
store.
"""

from ray_tpu.parallel.pipeline import pipeline_apply, stack_stage_params
from ray_tpu.parallel.mesh import (
    MeshSpec,
    DATA,
    FSDP,
    TENSOR,
    SEQUENCE,
    EXPERT,
    STAGE,
    cpu_mesh_devices,
    make_mesh,
)
from ray_tpu.parallel.sharding import (
    ShardingRules,
    constrain,
    constrain_tree,
    ddp_rules,
    fsdp_rules,
    logical_to_sharding,
    match_partition_rules,
    named_tree_map,
    shard_params_fsdp,
    tp_rules,
    tree_path_names,
)
from ray_tpu.parallel.collectives import CollectiveGroup

__all__ = [
    "pipeline_apply",
    "stack_stage_params",
    "MeshSpec",
    "DATA",
    "FSDP",
    "TENSOR",
    "SEQUENCE",
    "EXPERT",
    "STAGE",
    "make_mesh",
    "cpu_mesh_devices",
    "ShardingRules",
    "constrain",
    "constrain_tree",
    "ddp_rules",
    "fsdp_rules",
    "logical_to_sharding",
    "match_partition_rules",
    "named_tree_map",
    "shard_params_fsdp",
    "tp_rules",
    "tree_path_names",
    "CollectiveGroup",
]
