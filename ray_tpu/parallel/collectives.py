"""Collective communication for actors.

Reference: ``ray.util.collective`` (``collective.py:120,258,423,472,531,
594``) with NCCL/GLOO backends. The TPU-native split (SURVEY §5.8):

  * **ICI (primary)** — dense collectives happen inside compiled XLA
    programs (``psum``/``all_gather``/``ppermute`` under pjit/shard_map);
    nothing to do at runtime level beyond gang placement. See
    ``ray_tpu.parallel.mesh``.
  * **Host-level / DCN** — ``ObjectStoreCollectives``: rendezvous through
    a coordinator actor + the distributed object store. This replaces the
    reference's GLOO group for control-plane-sized tensors and works
    between any actors anywhere (the GLOO-equivalent, not the NCCL path).

API parity: init/allreduce/allgather/reducescatter/broadcast/send/recv/
barrier.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu

_REDUCERS = {
    "sum": lambda arrs: np.sum(arrs, axis=0),
    "prod": lambda arrs: np.prod(arrs, axis=0),
    "min": lambda arrs: np.min(arrs, axis=0),
    "max": lambda arrs: np.max(arrs, axis=0),
    "mean": lambda arrs: np.mean(arrs, axis=0),
}


class _Coordinator:
    """Rendezvous actor: gathers per-rank contributions, serves results.

    Async actor so all ranks' calls overlap (max_concurrency is set by the
    creator to >= world_size).
    """

    def __init__(self, world_size: int):
        import asyncio

        self.world_size = world_size
        self._ops: Dict[Any, Dict] = {}
        self._mailbox: Dict[Any, Any] = {}
        self._mailbox_events: Dict[Any, "asyncio.Event"] = {}

    def _op(self, key):
        import asyncio

        op = self._ops.get(key)
        if op is None:
            op = self._ops[key] = {
                "parts": {},
                "event": asyncio.Event(),
                "result": None,
                "consumed": 0,
            }
        return op

    async def contribute(self, key, rank: int, data, kind: str, extra=None):
        """Submit rank's data; resolves once all ranks arrived."""
        op = self._op(key)
        op["parts"][rank] = data
        if len(op["parts"]) == self.world_size:
            parts = [op["parts"][r] for r in range(self.world_size)]
            if kind == "allreduce":
                op["result"] = _REDUCERS[extra](parts)
            elif kind == "allgather":
                op["result"] = parts
            elif kind == "reducescatter":
                reduced = _REDUCERS[extra](parts)
                op["result"] = np.array_split(reduced, self.world_size)
            elif kind == "broadcast":
                op["result"] = op["parts"][extra]  # extra = root rank
            elif kind == "barrier":
                op["result"] = True
            op["event"].set()
        await op["event"].wait()
        result = op["result"]
        op["consumed"] += 1
        if op["consumed"] == self.world_size:
            del self._ops[key]
        if kind == "reducescatter":
            return result[rank]
        return result

    async def post(self, key, value):
        import asyncio
        from collections import deque

        # Queue per (src, dst, tag) key: two sends before the receiver's
        # take must both be delivered, in order — never overwritten.
        self._mailbox.setdefault(key, deque()).append(value)
        ev = self._mailbox_events.get(key)
        if ev is None:
            ev = self._mailbox_events[key] = asyncio.Event()
        ev.set()

    async def take(self, key):
        import asyncio

        ev = self._mailbox_events.get(key)
        if ev is None:
            ev = self._mailbox_events[key] = asyncio.Event()
        while True:
            q = self._mailbox.get(key)
            if q:
                value = q.popleft()
                if not q:
                    del self._mailbox[key]
                    ev.clear()
                return value
            ev.clear()
            await ev.wait()


class CollectiveGroup:
    """Handle used by each participating actor/process.

    ``CollectiveGroup("g", world_size=4, rank=r)`` in every participant;
    the named coordinator is created once (get_if_exists).
    """

    def __init__(self, name: str, world_size: int, rank: int):
        if not 0 <= rank < world_size:
            raise ValueError(f"rank {rank} out of range for world size {world_size}")
        self.name = name
        self.world_size = world_size
        self.rank = rank
        coordinator_cls = ray_tpu.remote(_Coordinator)
        self._coord = coordinator_cls.options(
            name=f"ray_tpu:collective:{name}",
            get_if_exists=True,
            num_cpus=0,
            max_concurrency=max(2 * world_size, 8),
            lifetime="detached",
        ).remote(world_size)
        self._seq = 0
        self._lock = threading.Lock()

    def _next_key(self, kind: str):
        with self._lock:
            self._seq += 1
            return (kind, self._seq)

    # -- collectives -----------------------------------------------------
    def allreduce(self, array, op: str = "sum"):
        if op not in _REDUCERS:
            raise ValueError(f"op must be one of {list(_REDUCERS)}")
        key = self._next_key("ar")
        return ray_tpu.get(
            self._coord.contribute.remote(key, self.rank, np.asarray(array), "allreduce", op)
        )

    def allgather(self, array) -> List[np.ndarray]:
        key = self._next_key("ag")
        return ray_tpu.get(
            self._coord.contribute.remote(key, self.rank, np.asarray(array), "allgather")
        )

    def reducescatter(self, array, op: str = "sum"):
        key = self._next_key("rs")
        return ray_tpu.get(
            self._coord.contribute.remote(key, self.rank, np.asarray(array), "reducescatter", op)
        )

    def broadcast(self, array, root: int = 0):
        key = self._next_key("bc")
        data = np.asarray(array) if self.rank == root else None
        return ray_tpu.get(
            self._coord.contribute.remote(key, self.rank, data, "broadcast", root)
        )

    def barrier(self) -> None:
        key = self._next_key("ba")
        ray_tpu.get(self._coord.contribute.remote(key, self.rank, None, "barrier"))

    # -- p2p -------------------------------------------------------------
    def send(self, array, dst: int, tag: int = 0) -> None:
        ray_tpu.get(self._coord.post.remote((self.rank, dst, tag), np.asarray(array)))

    def recv(self, src: int, tag: int = 0):
        return ray_tpu.get(self._coord.take.remote((src, self.rank, tag)))


# Back-compat functional API (reference ``ray.util.collective``) ----------

_groups: Dict[str, CollectiveGroup] = {}


def init_collective_group(world_size: int, rank: int, backend: str = "objectstore", group_name: str = "default") -> CollectiveGroup:
    group = CollectiveGroup(group_name, world_size, rank)
    _groups[group_name] = group
    return group


def allreduce(tensor, group_name: str = "default", op: str = "sum"):
    return _groups[group_name].allreduce(tensor, op)


def allgather(tensor, group_name: str = "default"):
    return _groups[group_name].allgather(tensor)


def reducescatter(tensor, group_name: str = "default", op: str = "sum"):
    return _groups[group_name].reducescatter(tensor, op)


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    return _groups[group_name].broadcast(tensor, root=src_rank)


def barrier(group_name: str = "default") -> None:
    _groups[group_name].barrier()


def send(tensor, dst_rank: int, group_name: str = "default") -> None:
    _groups[group_name].send(tensor, dst_rank)


def recv(src_rank: int, group_name: str = "default"):
    return _groups[group_name].recv(src_rank)

