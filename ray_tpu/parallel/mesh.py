"""Device meshes with canonical parallelism axes.

The framework's standard mesh axes (every library component speaks these
names):

  * ``data``  — pure data parallelism (gradient psum over DCN or ICI)
  * ``fsdp``  — data parallelism with parameter sharding (ZeRO-3
                equivalent; GSPMD shards params over this axis)
  * ``tensor``— tensor/model parallelism (matmul-sharded, all-reduce on
                activations; keep within a pod slice so it rides ICI)
  * ``seq``   — sequence/context parallelism (ring attention, Ulysses)
  * ``expert``— MoE expert parallelism (all-to-all dispatch)
  * ``stage`` — pipeline stages

Replaces the reference's process-group bootstrap
(``train/torch/config.py:66-116``): instead of NCCL rendezvous, build a
``jax.sharding.Mesh`` and let pjit/XLA insert collectives. Axis order puts
the fastest-varying (most-communicating) axes last so they map to
adjacent ICI neighbors (cf. the scaling-book recipe).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

DATA = "data"
FSDP = "fsdp"
TENSOR = "tensor"
SEQUENCE = "seq"
EXPERT = "expert"
STAGE = "stage"

# canonical order: slower-varying first; tensor last → nearest neighbors
AXIS_ORDER = (STAGE, DATA, FSDP, EXPERT, SEQUENCE, TENSOR)


@dataclass(frozen=True)
class MeshSpec:
    """Logical mesh shape: axis name -> size (missing axes = 1).

    ``MeshSpec(fsdp=8, tensor=4)`` on 32 devices; ``auto`` axes (-1) are
    inferred from the device count.
    """

    data: int = 1
    fsdp: int = 1
    tensor: int = 1
    seq: int = 1
    expert: int = 1
    stage: int = 1

    def axis_sizes(self) -> Dict[str, int]:
        return {
            STAGE: self.stage,
            DATA: self.data,
            FSDP: self.fsdp,
            EXPERT: self.expert,
            SEQUENCE: self.seq,
            TENSOR: self.tensor,
        }

    @property
    def num_devices(self) -> int:
        n = 1
        for v in self.axis_sizes().values():
            n *= abs(v)
        return n

    def resolve(self, device_count: int) -> "MeshSpec":
        """Infer a single -1 axis from the device count."""
        sizes = self.axis_sizes()
        unknown = [k for k, v in sizes.items() if v == -1]
        if len(unknown) > 1:
            raise ValueError("at most one mesh axis may be -1")
        known = math.prod(v for v in sizes.values() if v != -1)
        if unknown:
            if device_count % known:
                raise ValueError(
                    f"cannot infer {unknown[0]}: {device_count} devices not "
                    f"divisible by {known}"
                )
            sizes[unknown[0]] = device_count // known
        total = math.prod(sizes.values())
        if total != device_count:
            raise ValueError(
                f"mesh {sizes} needs {total} devices, have {device_count}"
            )
        return MeshSpec(
            data=sizes[DATA],
            fsdp=sizes[FSDP],
            tensor=sizes[TENSOR],
            seq=sizes[SEQUENCE],
            expert=sizes[EXPERT],
            stage=sizes[STAGE],
        )

    def active_axes(self) -> Tuple[str, ...]:
        return tuple(k for k in AXIS_ORDER if self.axis_sizes()[k] > 1)


def make_mesh(spec: MeshSpec, devices: Optional[Sequence] = None):
    """Build a ``jax.sharding.Mesh`` with ALL canonical axes (size-1 axes
    included, so sharding rules can always name them)."""
    import jax
    import numpy as np

    if devices is None:
        devices = jax.devices()
    spec = spec.resolve(len(devices))
    sizes = spec.axis_sizes()
    shape = tuple(sizes[a] for a in AXIS_ORDER)
    arr = np.asarray(devices).reshape(shape)
    return jax.sharding.Mesh(arr, AXIS_ORDER)


def cpu_mesh_devices(n: int = 8):
    """CPU devices for the fake-ICI test path. Requires
    ``XLA_FLAGS=--xla_force_host_platform_device_count=N`` and
    ``JAX_PLATFORMS=cpu`` set before jax initializes (tests/conftest.py
    does this; mirrors the reference's mocked-NCCL conftest pattern,
    ``experimental/channel/conftest.py``)."""
    import jax

    devices = jax.devices("cpu")
    if len(devices) < n:
        raise RuntimeError(
            f"need {n} virtual CPU devices, have {len(devices)}; set "
            "XLA_FLAGS=--xla_force_host_platform_device_count before jax init"
        )
    return devices[:n]


def slice_topology_mesh(num_slices: int, per_slice_spec: MeshSpec, devices=None):
    """Multi-slice mesh: ``data`` axis spans slices over DCN, everything
    else stays inside a slice on ICI (reference's cross-NCCL-group
    training has no equivalent; this is the jax multi-slice recipe)."""
    import jax

    if devices is None:
        devices = jax.devices()
    per = len(devices) // num_slices
    spec = per_slice_spec.resolve(per)
    merged = MeshSpec(
        data=spec.data * num_slices,
        fsdp=spec.fsdp,
        tensor=spec.tensor,
        seq=spec.seq,
        expert=spec.expert,
        stage=spec.stage,
    )
    return make_mesh(merged, devices)


def shard_map_compat(f, *, mesh, in_specs, out_specs, check_vma=None):
    """Version-portable ``shard_map``: newer jax exposes ``jax.shard_map``
    with ``check_vma``; 0.4.x ships ``jax.experimental.shard_map`` where
    the same flag is named ``check_rep`` — and an intermediate window has
    the public name with the OLD flag, so the kwarg is chosen by what the
    resolved function accepts, not by which module exports it. Every
    shard_map in this repo goes through here so a jax upgrade/downgrade
    is a one-file event."""
    import inspect

    import jax as _jax

    sm = getattr(_jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm
    if check_vma is None:
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    try:
        params = inspect.signature(sm).parameters
    except (TypeError, ValueError):
        params = {}
    flag = "check_vma" if "check_vma" in params else "check_rep"
    return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **{flag: check_vma})
