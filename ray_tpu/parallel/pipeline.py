"""Pipeline parallelism: GPipe scheduling over the ``stage`` mesh axis.

Reference: no native impl — the reference simulates PP with compiled
actor DAGs (``dag/tests/experimental/test_accelerated_dag.py:1962``).
TPU-native build-new (SURVEY §2.4): a single SPMD program where stages
live on different devices of the ``stage`` axis, microbatch activations
hop stage→stage with ``lax.ppermute`` over ICI, and the whole schedule
is one ``lax.scan`` — XLA overlaps each step's compute with the
neighbor transfer (scaling-book "pipelining via collective permute").

Schedule: microbatch m is computed by stage s at step t = m + s; the
pipeline runs M + S - 1 steps (fill + drain). Stage 0 injects from the
input queue; the last stage's results are collected per step and
broadcast at the end (psum of a one-stage mask)."""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ray_tpu.parallel.mesh import shard_map_compat, STAGE


def stack_stage_params(per_stage_params: list):
    """[params_stage0, params_stage1, ...] → one pytree with a leading
    ``num_stages`` dim (the shard_map input over the stage axis)."""
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs, axis=0), *per_stage_params
    )


def pipeline_apply(
    mesh,
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    stacked_params: Any,
    microbatches: jnp.ndarray,
    *,
    stage_axis: str = STAGE,
):
    """Run ``microbatches [M, ...]`` through ``num_stages`` pipeline
    stages. ``stage_fn(stage_params, x) -> x`` is one stage's compute;
    ``stacked_params`` carries a leading ``num_stages`` dim (see
    ``stack_stage_params``). Returns outputs ``[M, ...]``.

    Differentiable: the scan + ppermute transpose cleanly, so this
    drops into a jitted train step."""
    num_stages = mesh.shape[stage_axis]
    M = microbatches.shape[0]

    def inner(params_local, xs):
        # params_local: [1, ...] (this stage's slice); xs: [M, ...] (replicated)
        p = jax.tree_util.tree_map(lambda a: a[0], params_local)
        s = jax.lax.axis_index(stage_axis)
        perm = [(i, (i + 1) % num_stages) for i in range(num_stages)]

        state0 = jnp.zeros_like(xs[0])
        out0 = jnp.zeros_like(xs)

        def step(carry, t):
            state, outputs = carry
            # stage 0 injects microbatch t (clamped index; masked later)
            inject = xs[jnp.minimum(t, M - 1)]
            state = jnp.where(s == 0, inject, state)
            state = stage_fn(p, state)
            # last stage emits microbatch t-(S-1) after its compute
            out_idx = t - (num_stages - 1)
            is_emit = (s == num_stages - 1) & (out_idx >= 0)
            updated = jax.lax.dynamic_update_index_in_dim(
                outputs, state, jnp.maximum(out_idx, 0), axis=0
            )
            outputs = jnp.where(is_emit, updated, outputs)
            state = jax.lax.ppermute(state, stage_axis, perm)
            return (state, outputs), None

        (_, outputs), _ = jax.lax.scan(
            step, (state0, out0), jnp.arange(M + num_stages - 1)
        )
        # results live on the last stage only — broadcast to every stage
        mask = (s == num_stages - 1).astype(outputs.dtype)
        return jax.lax.psum(outputs * mask, stage_axis)

    fn = shard_map_compat(
        inner,
        mesh=mesh,
        in_specs=(P(stage_axis), P()),
        out_specs=P(),
        check_vma=False,
    )
    return fn(stacked_params, microbatches)
