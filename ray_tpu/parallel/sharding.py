"""Logical-axis sharding rules → NamedSharding.

The GSPMD-first replacement for the reference's wrapper-based parallelism
(DDP wrapping ``train_loop_utils.py:162-190``, FSDP, DeepSpeed): models
annotate arrays with *logical* axis names ("batch", "embed", "mlp", ...)
and a ``ShardingRules`` table maps logical names to mesh axes. Swapping a
rules table re-parallelizes the whole model — no code changes.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence, Tuple, Union

from ray_tpu.parallel.mesh import DATA, EXPERT, FSDP, SEQUENCE, STAGE, TENSOR

MeshAxes = Union[None, str, Tuple[str, ...]]


@dataclass
class ShardingRules:
    """logical axis name -> mesh axis (or tuple of mesh axes, or None)."""

    rules: Dict[str, MeshAxes] = field(default_factory=dict)

    def __getitem__(self, logical: str) -> MeshAxes:
        return self.rules.get(logical)

    def spec(self, logical_axes: Sequence[Optional[str]]):
        """PartitionSpec for an array annotated with logical axis names."""
        from jax.sharding import PartitionSpec

        return PartitionSpec(*[self.rules.get(a) if a else None for a in logical_axes])

    def with_overrides(self, **updates: MeshAxes) -> "ShardingRules":
        merged = dict(self.rules)
        merged.update(updates)
        return ShardingRules(merged)


# Canonical rule tables ---------------------------------------------------

def ddp_rules() -> ShardingRules:
    """Pure data parallel: batch over (data, fsdp); params replicated."""
    return ShardingRules(
        {
            "batch": (DATA, FSDP),
            "seq": None,
            "embed": None,
            "mlp": None,
            "heads": None,
            "kv_heads": None,
            "head_dim": None,
            "vocab": None,
            "expert": None,
        }
    )


def fsdp_rules() -> ShardingRules:
    """ZeRO-3 equivalent via GSPMD: params sharded on fsdp over their
    embed dim; batch over (data, fsdp)."""
    return ShardingRules(
        {
            "batch": (DATA, FSDP),
            "seq": None,
            "embed": FSDP,
            "mlp": None,
            "heads": None,
            "kv_heads": None,
            "head_dim": None,
            "vocab": None,
            "expert": None,
        }
    )


def tp_rules() -> ShardingRules:
    """Megatron-style tensor parallel: mlp/heads/vocab over tensor;
    params' embed dim over fsdp; batch over (data, fsdp); sequence over
    seq (ring attention)."""
    return ShardingRules(
        {
            "batch": (DATA, FSDP),
            "seq": SEQUENCE,
            "embed": FSDP,
            "mlp": TENSOR,
            "heads": TENSOR,
            "kv_heads": TENSOR,
            "head_dim": None,
            "vocab": TENSOR,
            "expert": EXPERT,
        }
    )


def logical_to_sharding(mesh, rules: ShardingRules, logical_axes: Sequence[Optional[str]]):
    from jax.sharding import NamedSharding

    return NamedSharding(mesh, rules.spec(logical_axes))


def shard_params_fsdp(mesh, params, min_size: int = 2**14):
    """Heuristic parameter sharding when no logical annotations exist:
    shard each array's largest divisible dim over the fsdp axis
    (GSPMD makes this ZeRO-3-equivalent; cf. reference FSDP wrap policy
    ``train/torch/train_loop_utils.py:33-35``)."""
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec

    fsdp_size = dict(zip(mesh.axis_names, mesh.devices.shape)).get(FSDP, 1)

    def spec_for(x) -> PartitionSpec:
        if fsdp_size <= 1 or x.size < min_size:
            return PartitionSpec()
        dims = list(x.shape)
        order = sorted(range(len(dims)), key=lambda i: -dims[i])
        for i in order:
            if dims[i] % fsdp_size == 0:
                parts = [None] * len(dims)
                parts[i] = FSDP
                return PartitionSpec(*parts)
        return PartitionSpec()

    return jax.tree_util.tree_map(
        lambda x: NamedSharding(mesh, spec_for(x)), params
    )
