"""Logical-axis sharding rules → NamedSharding.

The GSPMD-first replacement for the reference's wrapper-based parallelism
(DDP wrapping ``train_loop_utils.py:162-190``, FSDP, DeepSpeed): models
annotate arrays with *logical* axis names ("batch", "embed", "mlp", ...)
and a ``ShardingRules`` table maps logical names to mesh axes. Swapping a
rules table re-parallelizes the whole model — no code changes.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

from ray_tpu.parallel.mesh import DATA, EXPERT, FSDP, SEQUENCE, STAGE, TENSOR

MeshAxes = Union[None, str, Tuple[str, ...]]


@dataclass
class ShardingRules:
    """logical axis name -> mesh axis (or tuple of mesh axes, or None)."""

    rules: Dict[str, MeshAxes] = field(default_factory=dict)

    def __getitem__(self, logical: str) -> MeshAxes:
        return self.rules.get(logical)

    def spec(self, logical_axes: Sequence[Optional[str]]):
        """PartitionSpec for an array annotated with logical axis names.

        Trailing ``None`` entries are stripped: ``P('fsdp', None)`` and
        ``P('fsdp')`` mean the same sharding but hash as DIFFERENT jit
        cache keys — a step fed table-built shardings would "recompile"
        once when its own outputs (XLA-normalized, trailing Nones
        dropped) came back as inputs."""
        from jax.sharding import PartitionSpec

        entries = [self.rules.get(a) if a else None for a in logical_axes]
        while entries and entries[-1] is None:
            entries.pop()
        return PartitionSpec(*entries)

    def with_overrides(self, **updates: MeshAxes) -> "ShardingRules":
        merged = dict(self.rules)
        merged.update(updates)
        return ShardingRules(merged)


# Canonical rule tables ---------------------------------------------------

def ddp_rules() -> ShardingRules:
    """Pure data parallel: batch over (data, fsdp); params replicated."""
    return ShardingRules(
        {
            "batch": (DATA, FSDP),
            "seq": None,
            "embed": None,
            "mlp": None,
            "heads": None,
            "kv_heads": None,
            "head_dim": None,
            "vocab": None,
            "expert": None,
            # activation axes (``act_*``): how INTERMEDIATE tensors shard,
            # distinct from the parameter axes above — e.g. under tp the
            # params' embed dim shards over fsdp (ZeRO-style storage) but
            # the residual stream's embed dim stays replicated across
            # tensor ranks. One table drives both so fwd, bwd, and the
            # optimizer see ONE source of truth (the involuntary-remat
            # fix: every with_sharding_constraint derives from here).
            "act_batch": (DATA, FSDP),
            "act_seq": None,
            "act_embed": None,
            "act_heads": None,
            "act_kv_heads": None,
            "act_mlp": None,
            "act_vocab": None,
        }
    )


def fsdp_rules() -> ShardingRules:
    """ZeRO-3 equivalent via GSPMD: params sharded on fsdp over their
    embed dim; batch over (data, fsdp); activations batch-sharded only."""
    return ShardingRules(
        {
            "batch": (DATA, FSDP),
            "seq": None,
            "embed": FSDP,
            "mlp": None,
            "heads": None,
            "kv_heads": None,
            "head_dim": None,
            "vocab": None,
            "expert": None,
            "act_batch": (DATA, FSDP),
            "act_seq": None,
            "act_embed": None,
            "act_heads": None,
            "act_kv_heads": None,
            "act_mlp": None,
            "act_vocab": None,
        }
    )


def tp_rules() -> ShardingRules:
    """Megatron-style tensor parallel: mlp/heads/vocab over tensor;
    params' embed dim over fsdp; batch over (data, fsdp); sequence over
    seq (ring attention). Activations: heads/mlp-hidden/vocab shard over
    tensor (the Megatron split), the residual stream stays replicated
    across tensor ranks, sequence rides the seq axis."""
    return ShardingRules(
        {
            "batch": (DATA, FSDP),
            "seq": SEQUENCE,
            "embed": FSDP,
            "mlp": TENSOR,
            "heads": TENSOR,
            "kv_heads": TENSOR,
            "head_dim": None,
            "vocab": TENSOR,
            "expert": EXPERT,
            "act_batch": (DATA, FSDP),
            "act_seq": SEQUENCE,
            "act_embed": None,
            "act_heads": TENSOR,
            "act_kv_heads": TENSOR,
            "act_mlp": TENSOR,
            "act_vocab": TENSOR,
        }
    )


def logical_to_sharding(mesh, rules: ShardingRules, logical_axes: Sequence[Optional[str]]):
    from jax.sharding import NamedSharding

    return NamedSharding(mesh, rules.spec(logical_axes))


# Regex partition rules ----------------------------------------------------
#
# The named-sharding source of truth for whole PYTREES: an ordered list of
# ``(regex, PartitionSpec)`` pairs matched against each leaf's '/'-joined
# tree path. One table covers params, grads (same tree), and optimizer
# state (optax mu/nu mirror the param tree, so ``wq$`` matches
# ``0/mu/layers/3/wq`` too; scalar leaves like adam's ``count`` are
# skipped). This is what lets fwd, bwd, and the optimizer update agree on
# every tensor's sharding — the multichip involuntary-remat fix.


def tree_path_names(tree: Any, sep: str = "/") -> List[str]:
    """'/'-joined key path for every leaf, in tree_leaves order."""
    import jax

    paths_and_leaves, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [
        sep.join(_path_entry_name(entry) for entry in path)
        for path, _leaf in paths_and_leaves
    ]


def _path_entry_name(entry) -> str:
    import jax

    if isinstance(entry, jax.tree_util.DictKey):
        return str(entry.key)
    if isinstance(entry, jax.tree_util.SequenceKey):
        return str(entry.idx)
    if isinstance(entry, jax.tree_util.GetAttrKey):
        return str(entry.name)
    if isinstance(entry, jax.tree_util.FlattenedIndexKey):
        return str(entry.key)
    return str(entry)


def named_tree_map(fn: Callable[[str, Any], Any], tree: Any, sep: str = "/") -> Any:
    """``tree_map`` where ``fn(path_name, leaf)`` also sees the leaf's
    '/'-joined key path (the SNIPPETS [1] pattern)."""
    import jax

    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: fn(
            sep.join(_path_entry_name(entry) for entry in path), leaf
        ),
        tree,
    )


def match_partition_rules(rules, tree: Any):
    """Pytree of ``PartitionSpec`` for ``tree`` from ordered regex rules.

    ``rules``: sequence of ``(pattern, PartitionSpec)``; the FIRST
    ``re.search`` hit wins, so overrides go in front. Scalar leaves
    (0-d or single-element) are never partitioned — they map to ``PS()``
    without consulting the rules (optax ``count``, loss scalars). A
    non-scalar leaf with no matching rule raises: silent replication is
    exactly how shardings drift apart across the step. A matched spec
    LONGER than the leaf's rank means the leaf is a rank-reduced mirror
    of the param the rule was written for (adafactor ``v_row``/``v_col``,
    SM3 diagonals) — the param's spec is structurally inapplicable, so
    those leaves replicate instead of raising. This length check is only
    a backstop: trailing-None stripping can leave a param spec the same
    length as a reduced stat's rank, so rules tables should ALSO pin
    known factored stats by name, in front (see
    ``models/llama.py::partition_rules``'s ``v_(row|col)`` rule).
    """
    import numpy as np
    from jax.sharding import PartitionSpec

    compiled = [(re.compile(pat), spec) for pat, spec in rules]

    def get_spec(name: str, leaf):
        shape = getattr(leaf, "shape", ())
        if len(shape) == 0 or int(np.prod(shape)) == 1:
            return PartitionSpec()
        for pat, spec in compiled:
            if pat.search(name) is not None:
                if len(spec) > len(shape):
                    return PartitionSpec()
                return spec
        raise ValueError(f"no partition rule matched leaf {name!r} {shape}")

    return named_tree_map(get_spec, tree)


def constrain(x: Any, mesh, rules: ShardingRules, logical_axes: Sequence[Optional[str]]):
    """``with_sharding_constraint`` by LOGICAL axis names: pins an
    intermediate's sharding to the rule table inside jit, so GSPMD never
    has to guess (and never disagrees with itself across fwd/bwd). A
    ``None`` mesh or rules is a no-op — single-device reference paths
    stay constraint-free and bit-identical to before."""
    if mesh is None or rules is None:
        return x
    import jax

    return jax.lax.with_sharding_constraint(
        x, logical_to_sharding(mesh, rules, logical_axes)
    )


def constrain_tree(tree: Any, mesh, rules) -> Any:
    """Pin a whole pytree (params/grads/opt-state) to its matched specs.

    ``rules``: ordered ``(regex, PartitionSpec)`` pairs (see
    ``match_partition_rules``). No-op when mesh or rules is None."""
    if mesh is None or rules is None:
        return tree
    import jax
    from jax.sharding import NamedSharding

    specs = match_partition_rules(rules, tree)
    return jax.tree_util.tree_map(
        lambda x, spec: jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, spec)
        ),
        tree,
        specs,
    )


def shard_params_fsdp(mesh, params, min_size: int = 2**14):
    """Heuristic parameter sharding when no logical annotations exist:
    shard each array's largest divisible dim over the fsdp axis
    (GSPMD makes this ZeRO-3-equivalent; cf. reference FSDP wrap policy
    ``train/torch/train_loop_utils.py:33-35``)."""
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec

    fsdp_size = dict(zip(mesh.axis_names, mesh.devices.shape)).get(FSDP, 1)

    def spec_for(x) -> PartitionSpec:
        if fsdp_size <= 1 or x.size < min_size:
            return PartitionSpec()
        dims = list(x.shape)
        order = sorted(range(len(dims)), key=lambda i: -dims[i])
        for i in order:
            if dims[i] % fsdp_size == 0:
                parts = [None] * len(dims)
                parts[i] = FSDP
                return PartitionSpec(*parts)
        return PartitionSpec()

    return jax.tree_util.tree_map(
        lambda x: NamedSharding(mesh, spec_for(x)), params
    )
