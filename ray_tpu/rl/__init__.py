"""ray_tpu.rl — reinforcement learning: EnvRunner actors + JAX learners.

Reference: ``rllib/`` new API stack (Algorithm / EnvRunnerGroup /
LearnerGroup). See ``ppo.py`` for the TPU-native design notes."""

from ray_tpu.rl.dqn import DQN, DQNConfig
from ray_tpu.rl.env_runner import EnvRunner
from ray_tpu.rl.impala import IMPALA, IMPALAConfig
from ray_tpu.rl.learner_group import LearnerGroup
from ray_tpu.rl.models import (
    apply_cnn_policy,
    apply_cnn_q,
    apply_mlp_policy,
    apply_mlp_q,
    init_cnn,
    init_mlp_policy,
    init_mlp_q,
)
from ray_tpu.rl.ppo import PPO, PPOConfig
from ray_tpu.rl.replay import ReplayBuffer

__all__ = [
    "DQN",
    "DQNConfig",
    "EnvRunner",
    "IMPALA",
    "IMPALAConfig",
    "LearnerGroup",
    "PPO",
    "PPOConfig",
    "ReplayBuffer",
    "apply_cnn_policy",
    "apply_cnn_q",
    "apply_mlp_policy",
    "apply_mlp_q",
    "init_cnn",
    "init_mlp_policy",
    "init_mlp_q",
]
