"""ray_tpu.rl — reinforcement learning: EnvRunner actors + JAX learners.

Reference: ``rllib/`` new API stack (Algorithm / EnvRunnerGroup /
LearnerGroup). See ``ppo.py`` for the TPU-native design notes."""

from ray_tpu.rl.env_runner import EnvRunner
from ray_tpu.rl.impala import IMPALA, IMPALAConfig
from ray_tpu.rl.learner_group import LearnerGroup
from ray_tpu.rl.models import apply_mlp_policy, init_mlp_policy
from ray_tpu.rl.ppo import PPO, PPOConfig

__all__ = [
    "EnvRunner",
    "IMPALA",
    "IMPALAConfig",
    "LearnerGroup",
    "PPO",
    "PPOConfig",
    "apply_mlp_policy",
    "init_mlp_policy",
]
