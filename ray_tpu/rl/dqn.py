"""DQN: value-based learning with a replay buffer.

Reference: ``rllib/algorithms/dqn/`` (DQNConfig/DQN, double-Q +
target network + replay). TPU-native redesign mirroring ppo.py: the
learner is ONE jitted update (double-DQN Huber TD loss) over replay
minibatches; EnvRunner actors collect epsilon-greedy transitions on
CPU; the target network refreshes by pytree copy every
``target_update_freq`` gradient steps. ``model="cnn_q"`` runs the conv
torso for image observations (models.py) — the Atari path.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rl.replay import ReplayBuffer


@dataclass
class DQNConfig:
    """Reference ``DQNConfig`` as a dataclass."""

    env: str = "CartPole-v1"
    env_config: Optional[Dict[str, Any]] = None
    num_env_runners: int = 1
    num_envs_per_runner: int = 4
    rollout_fragment_length: int = 32  # steps per env per iteration
    model: str = "mlp_q"  # "mlp_q" | "cnn_q"
    hidden: tuple = (128, 128)
    gamma: float = 0.99
    lr: float = 1e-3
    buffer_capacity: int = 50_000
    learning_starts: int = 500  # transitions before updates begin
    train_batch_size: int = 64
    updates_per_iteration: int = 32
    target_update_freq: int = 200  # gradient steps between target syncs
    epsilon_initial: float = 1.0
    epsilon_final: float = 0.05
    epsilon_decay_steps: int = 5_000  # env steps to anneal over
    double_q: bool = True
    seed: int = 0
    runner_resources: Dict[str, float] = field(default_factory=lambda: {"CPU": 0.5})

    def build(self) -> "DQN":
        return DQN(self)


class DQN:
    """EnvRunner gang + jitted double-DQN learner (reference Algorithm)."""

    def __init__(self, config: DQNConfig):
        import jax
        import optax

        from ray_tpu.rl.models import init_cnn, init_mlp_q
        from ray_tpu.rl.utils import make_runners, probe_env_space

        self.config = config
        obs_shape, num_actions = probe_env_space(config.env, config.env_config)
        self._num_actions = num_actions

        rng = jax.random.PRNGKey(config.seed)
        if config.model == "cnn_q":
            self.params = init_cnn(rng, obs_shape, num_actions, heads=("q",))
        else:
            obs_dim = int(np.prod(obs_shape))
            self.params = init_mlp_q(rng, obs_dim, num_actions, config.hidden)
        self.target_params = jax.tree_util.tree_map(lambda x: x, self.params)
        self.optimizer = optax.adam(config.lr)
        self.opt_state = self.optimizer.init(self.params)
        self.iteration = 0
        self.env_steps = 0
        self.gradient_steps = 0
        self._update = jax.jit(self._make_update())

        self.buffer = ReplayBuffer(config.buffer_capacity, seed=config.seed)
        self.runners = make_runners(config)
        self._recent_returns: List[float] = []

    def _apply(self):
        from ray_tpu.rl.models import apply_cnn_q, apply_mlp_q

        return apply_cnn_q if self.config.model == "cnn_q" else apply_mlp_q

    # -- learner ---------------------------------------------------------
    def _make_update(self):
        import jax
        import jax.numpy as jnp
        import optax

        cfg = self.config
        apply_q = self._apply()

        def loss_fn(params, target_params, batch):
            q = apply_q(params, batch["obs"])
            q_taken = jnp.take_along_axis(q, batch["actions"][:, None], axis=1)[:, 0]
            q_next_target = apply_q(target_params, batch["next_obs"])
            if cfg.double_q:
                # double-DQN: online net picks, target net evaluates
                q_next_online = apply_q(params, batch["next_obs"])
                next_a = jnp.argmax(q_next_online, axis=-1)
                next_q = jnp.take_along_axis(
                    q_next_target, next_a[:, None], axis=1
                )[:, 0]
            else:
                next_q = q_next_target.max(axis=-1)
            not_done = 1.0 - batch["dones"].astype(jnp.float32)
            target = jax.lax.stop_gradient(
                batch["rewards"] + cfg.gamma * not_done * next_q
            )
            td = q_taken - target
            loss = optax.huber_loss(td).mean()
            return loss, (jnp.abs(td).mean(), q_taken.mean())

        def update(params, target_params, opt_state, batch):
            (loss, (td_abs, q_mean)), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(params, target_params, batch)
            updates, opt_state = self.optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, {
                "loss": loss,
                "td_error_abs": td_abs,
                "q_mean": q_mean,
            }

        return update

    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self.env_steps / max(1, cfg.epsilon_decay_steps))
        return cfg.epsilon_initial + frac * (cfg.epsilon_final - cfg.epsilon_initial)

    # -- Tune trainable surface -----------------------------------------
    def train(self) -> Dict[str, Any]:
        import jax
        import jax.numpy as jnp

        cfg = self.config
        t0 = time.perf_counter()
        eps = self._epsilon()
        rollouts = ray_tpu.get(
            [
                r.sample_transitions.remote(
                    self.params, cfg.rollout_fragment_length, eps, cfg.model
                )
                for r in self.runners
            ],
            timeout=600,
        )
        sample_time = time.perf_counter() - t0
        for ro in rollouts:
            self._recent_returns.extend(ro.pop("episode_returns"))
            self.env_steps += len(ro["obs"])
            self.buffer.add_batch(ro)

        stats: Dict[str, Any] = {}
        if len(self.buffer) >= max(cfg.learning_starts, cfg.train_batch_size):
            for _ in range(cfg.updates_per_iteration):
                batch_np = self.buffer.sample(cfg.train_batch_size)
                batch = {k: jnp.asarray(v) for k, v in batch_np.items()}
                self.params, self.opt_state, stats = self._update(
                    self.params, self.target_params, self.opt_state, batch
                )
                self.gradient_steps += 1
                if self.gradient_steps % cfg.target_update_freq == 0:
                    self.target_params = jax.tree_util.tree_map(
                        lambda x: x, self.params
                    )
        self.iteration += 1
        self._recent_returns = self._recent_returns[-100:]
        mean_return = (
            float(np.mean(self._recent_returns)) if self._recent_returns else 0.0
        )
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": mean_return,
            "num_env_steps_sampled": self.env_steps,
            "num_gradient_steps": self.gradient_steps,
            "epsilon": round(eps, 4),
            "buffer_size": len(self.buffer),
            "sample_time_s": round(sample_time, 3),
            **{k: float(v) for k, v in stats.items()},
        }

    # -- state / eval ----------------------------------------------------
    def get_state(self) -> Dict[str, Any]:
        import jax

        to_np = lambda t: jax.tree_util.tree_map(np.asarray, t)
        return {
            "params": to_np(self.params),
            "target_params": to_np(self.target_params),
            "opt_state": to_np(self.opt_state),
            "iteration": self.iteration,
            "env_steps": self.env_steps,
            "gradient_steps": self.gradient_steps,
        }

    def set_state(self, state: Dict[str, Any]) -> None:
        self.params = state["params"]
        self.target_params = state["target_params"]
        self.opt_state = state["opt_state"]
        self.iteration = state["iteration"]
        self.env_steps = state["env_steps"]
        self.gradient_steps = state["gradient_steps"]

    def compute_single_action(self, obs) -> int:
        import jax.numpy as jnp

        q = self._apply()(self.params, jnp.asarray(obs)[None])
        return int(np.argmax(np.asarray(q)[0]))

    def stop(self) -> None:
        for r in self.runners:
            try:
                ray_tpu.get(r.close.remote(), timeout=10)
                ray_tpu.kill(r)
            except Exception:
                pass
