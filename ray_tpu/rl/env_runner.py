"""EnvRunner: rollout-collection actors.

Reference: ``rllib/env/env_runner_group.py:70`` +
``SingleAgentEnvRunner`` — CPU actors step gymnasium vector envs with
the current policy and return episode batches; learning happens
elsewhere (the reference's Learner gang; here a JAX learner)."""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

import ray_tpu


class _EnvRunner:
    """One rollout actor: a gymnasium vector env + jitted policy apply.

    Defined undecorated so cloudpickle exports by module reference."""

    def __init__(self, env_name: str, num_envs: int, seed: int, env_config=None):
        import gymnasium as gym

        self._envs = gym.make_vec(env_name, num_envs=num_envs, **(env_config or {}))
        self._num_envs = num_envs
        self._obs, _ = self._envs.reset(seed=seed)
        self._rng = np.random.default_rng(seed)
        self._apply = None
        self._episode_returns = np.zeros(num_envs)
        self._finished_returns: List[float] = []

    def _policy(self):
        if self._apply is None:
            import jax

            # Rollout actors are CPU workers (reference: EnvRunners are
            # CPU-only; learners own the accelerator) — never let a tiny
            # policy apply claim the TPU from a pool worker.
            try:
                jax.config.update("jax_platforms", "cpu")
            except Exception:
                pass  # backend already initialized in this process

            from ray_tpu.rl.models import apply_mlp_policy

            self._apply = jax.jit(apply_mlp_policy)
        return self._apply

    def sample(self, params, num_steps: int) -> Dict[str, Any]:
        """Collect ``num_steps`` vector steps with the given policy params.

        Returns time-major arrays [T, N, ...] plus bootstrap values and
        episode-return stats (the learner computes GAE)."""
        import jax.numpy as jnp

        apply = self._policy()
        obs_buf, act_buf, rew_buf, done_buf, logp_buf, val_buf = [], [], [], [], [], []
        for _ in range(num_steps):
            logits, value = apply(params, jnp.asarray(self._obs, jnp.float32))
            logits = np.asarray(logits)
            value = np.asarray(value)
            # sample actions from the categorical (gumbel trick, numpy rng)
            z = self._rng.gumbel(size=logits.shape)
            actions = np.argmax(logits + z, axis=-1)
            logp = logits - _logsumexp(logits)
            act_logp = np.take_along_axis(logp, actions[:, None], axis=1)[:, 0]

            next_obs, rewards, terminated, truncated, _ = self._envs.step(actions)
            dones = np.logical_or(terminated, truncated)

            obs_buf.append(self._obs)
            act_buf.append(actions)
            rew_buf.append(rewards)
            done_buf.append(dones)
            logp_buf.append(act_logp)
            val_buf.append(value)

            self._episode_returns += rewards
            for i, d in enumerate(dones):
                if d:
                    self._finished_returns.append(float(self._episode_returns[i]))
                    self._episode_returns[i] = 0.0
            self._obs = next_obs

        _, last_value = apply(params, jnp.asarray(self._obs, jnp.float32))
        finished, self._finished_returns = self._finished_returns, []
        return {
            "obs": np.asarray(obs_buf, np.float32),
            "actions": np.asarray(act_buf, np.int64),
            "rewards": np.asarray(rew_buf, np.float32),
            "dones": np.asarray(done_buf, np.bool_),
            "logp": np.asarray(logp_buf, np.float32),
            "values": np.asarray(val_buf, np.float32),
            "last_values": np.asarray(last_value, np.float32),
            "episode_returns": finished,
        }

    def close(self) -> bool:
        self._envs.close()
        return True


def _logsumexp(x: np.ndarray) -> np.ndarray:
    m = x.max(axis=-1, keepdims=True)
    return m + np.log(np.exp(x - m).sum(axis=-1, keepdims=True))


EnvRunner = ray_tpu.remote(_EnvRunner)
