"""EnvRunner: rollout-collection actors.

Reference: ``rllib/env/env_runner_group.py:70`` +
``SingleAgentEnvRunner`` — CPU actors step gymnasium vector envs with
the current policy and return episode batches; learning happens
elsewhere (the reference's Learner gang; here a JAX learner)."""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

import ray_tpu


def resolve_env_class(env_name: str):
    """``"module.path:EnvClass"`` → the class (importable on any worker
    by module path — the fake-env CI strategy)."""
    import importlib

    mod_name, attr = env_name.split(":", 1)
    return getattr(importlib.import_module(mod_name), attr)


def make_vector_env(env_name: str, num_envs: int, env_config=None):
    """Vector env in SAME_STEP autoreset mode: a done step returns the
    NEW episode's reset obs and the next step is a real transition.
    gymnasium>=1.0 defaults to NEXT_STEP, whose reset step IGNORES the
    action and pays reward 0 — recorded naively (as the rollout loops
    here do), that trains Q/V toward cross-episode garbage."""
    import gymnasium as gym
    from gymnasium.vector import AutoresetMode

    if ":" in env_name:
        cls = resolve_env_class(env_name)
        cfg = env_config or {}
        return gym.vector.SyncVectorEnv(
            [(lambda: cls(**cfg)) for _ in range(num_envs)],
            autoreset_mode=AutoresetMode.SAME_STEP,
        )
    # vectorization_mode="sync" forces the generic SyncVectorEnv — the
    # per-env custom vector classes (e.g. CartPoleVectorEnv) reject
    # vector_kwargs and pin their own autoreset behavior
    return gym.make_vec(
        env_name,
        num_envs=num_envs,
        vectorization_mode="sync",
        vector_kwargs={"autoreset_mode": AutoresetMode.SAME_STEP},
        **(env_config or {}),
    )


class _EnvRunner:
    """One rollout actor: a gymnasium vector env + jitted policy apply.

    Defined undecorated so cloudpickle exports by module reference."""

    def __init__(self, env_name: str, num_envs: int, seed: int, env_config=None):
        self._envs = make_vector_env(env_name, num_envs, env_config)
        self._num_envs = num_envs
        self._obs, _ = self._envs.reset(seed=seed)
        self._rng = np.random.default_rng(seed)
        self._apply = None
        self._apply_q = None
        self._episode_returns = np.zeros(num_envs)
        self._finished_returns: List[float] = []

    def _policy(self):
        if self._apply is None:
            import jax

            # Rollout actors are CPU workers (reference: EnvRunners are
            # CPU-only; learners own the accelerator) — never let a tiny
            # policy apply claim the TPU from a pool worker.
            try:
                jax.config.update("jax_platforms", "cpu")
            except Exception:
                pass  # backend already initialized in this process

            from ray_tpu.rl.models import apply_mlp_policy

            self._apply = jax.jit(apply_mlp_policy)
        return self._apply

    def sample(self, params, num_steps: int) -> Dict[str, Any]:
        """Collect ``num_steps`` vector steps with the given policy params.

        Returns time-major arrays [T, N, ...] plus bootstrap values and
        episode-return stats (the learner computes GAE)."""
        import jax.numpy as jnp

        apply = self._policy()
        obs_buf, act_buf, rew_buf, done_buf, logp_buf, val_buf = [], [], [], [], [], []
        for _ in range(num_steps):
            logits, value = apply(params, jnp.asarray(self._obs, jnp.float32))
            logits = np.asarray(logits)
            value = np.asarray(value)
            # sample actions from the categorical (gumbel trick, numpy rng)
            z = self._rng.gumbel(size=logits.shape)
            actions = np.argmax(logits + z, axis=-1)
            logp = logits - _logsumexp(logits)
            act_logp = np.take_along_axis(logp, actions[:, None], axis=1)[:, 0]

            next_obs, rewards, terminated, truncated, _ = self._envs.step(actions)
            dones = np.logical_or(terminated, truncated)

            obs_buf.append(self._obs)
            act_buf.append(actions)
            rew_buf.append(rewards)
            done_buf.append(dones)
            logp_buf.append(act_logp)
            val_buf.append(value)

            self._episode_returns += rewards
            for i, d in enumerate(dones):
                if d:
                    self._finished_returns.append(float(self._episode_returns[i]))
                    self._episode_returns[i] = 0.0
            self._obs = next_obs

        _, last_value = apply(params, jnp.asarray(self._obs, jnp.float32))
        finished, self._finished_returns = self._finished_returns, []
        return {
            "obs": np.asarray(obs_buf, np.float32),
            "actions": np.asarray(act_buf, np.int64),
            "rewards": np.asarray(rew_buf, np.float32),
            "dones": np.asarray(done_buf, np.bool_),
            "logp": np.asarray(logp_buf, np.float32),
            "values": np.asarray(val_buf, np.float32),
            "last_values": np.asarray(last_value, np.float32),
            "episode_returns": finished,
        }

    def sample_transitions(
        self, params, num_steps: int, epsilon: float, model: str = "mlp_q"
    ) -> Dict[str, Any]:
        """Off-policy collection (DQN): epsilon-greedy over Q-values,
        returns flat (s, a, r, s', done) transition arrays plus episode
        stats. ``model``: "mlp_q" | "cnn_q"."""
        import jax
        import jax.numpy as jnp

        if self._apply_q is None:
            try:
                jax.config.update("jax_platforms", "cpu")
            except Exception:
                pass
            from ray_tpu.rl.models import apply_cnn_q, apply_mlp_q

            self._apply_q = jax.jit(
                apply_cnn_q if model == "cnn_q" else apply_mlp_q
            )
        obs_buf, act_buf, rew_buf, next_buf, done_buf = [], [], [], [], []
        for _ in range(num_steps):
            q = np.asarray(self._apply_q(params, jnp.asarray(self._obs)))
            greedy = np.argmax(q, axis=-1)
            explore = self._rng.random(self._num_envs) < epsilon
            random_a = self._rng.integers(0, q.shape[-1], self._num_envs)
            actions = np.where(explore, random_a, greedy)

            next_obs, rewards, terminated, truncated, infos = self._envs.step(actions)
            dones = np.logical_or(terminated, truncated)
            # SAME_STEP autoreset returns the NEW episode's reset obs on
            # done steps; the stored transition must end at the true
            # final obs (a truncated episode bootstraps from it)
            stored_next = next_obs
            final = infos.get("final_obs", infos.get("final_observation"))
            if final is not None and dones.any():
                stored_next = np.array(next_obs)
                for i in np.nonzero(dones)[0]:
                    if final[i] is not None:
                        stored_next[i] = final[i]
            obs_buf.append(self._obs)
            act_buf.append(actions)
            rew_buf.append(rewards)
            # bootstrap cuts only on TERMINATION — a truncated episode's
            # final state still has value (standard DQN detail)
            done_buf.append(terminated)
            next_buf.append(stored_next)

            self._episode_returns += rewards
            for i, d in enumerate(dones):
                if d:
                    self._finished_returns.append(float(self._episode_returns[i]))
                    self._episode_returns[i] = 0.0
            self._obs = next_obs

        finished, self._finished_returns = self._finished_returns, []
        flat = lambda a: np.asarray(a).reshape(-1, *np.asarray(a).shape[2:])
        return {
            "obs": flat(obs_buf),
            "actions": flat(act_buf).astype(np.int64),
            "rewards": flat(rew_buf).astype(np.float32),
            "next_obs": flat(next_buf),
            "dones": flat(done_buf).astype(np.bool_),
            "episode_returns": finished,
        }

    def close(self) -> bool:
        self._envs.close()
        return True


def _logsumexp(x: np.ndarray) -> np.ndarray:
    m = x.max(axis=-1, keepdims=True)
    return m + np.log(np.exp(x - m).sum(axis=-1, keepdims=True))


EnvRunner = ray_tpu.remote(_EnvRunner)
