"""IMPALA: asynchronous sampling with V-trace off-policy correction.

Reference: ``rllib/algorithms/impala/impala.py:143`` — EnvRunners sample
CONTINUOUSLY with slightly-stale weights; rollouts stream to the learner
as they arrive (no synchronous barrier like PPO); V-trace corrects for
the policy lag. The reference's aggregator actors batch rollouts ahead
of GPU learners; here the aggregation is the ready-set drain each
``train()`` pass and the learner is a jitted V-trace update — run
locally, or across a ``LearnerGroup`` gang (one pjit program, batch
sharded over learners) when ``num_learners > 1``.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rl.models import apply_mlp_policy, init_mlp_policy


@dataclass
class IMPALAConfig:
    env: str = "CartPole-v1"
    env_config: Optional[Dict[str, Any]] = None
    num_env_runners: int = 2
    num_envs_per_runner: int = 4
    rollout_fragment_length: int = 64
    gamma: float = 0.99
    vtrace_clip_rho: float = 1.0
    vtrace_clip_c: float = 1.0
    entropy_coeff: float = 0.01
    vf_coeff: float = 0.5
    lr: float = 5e-4
    hidden: tuple = (64, 64)
    seed: int = 0
    #: rollout fragments consumed (= learner updates) per train() call
    rollouts_per_iteration: int = 8
    #: learner gang size; >1 runs the update as one pjit program over a
    #: LearnerGroup (CPU gang in tests, chips in production)
    num_learners: int = 1
    learner_platform: Optional[str] = "cpu"
    runner_resources: Dict[str, float] = field(default_factory=lambda: {"CPU": 0.5})

    def build(self) -> "IMPALA":
        return IMPALA(self)


def _make_vtrace_update(cfg: IMPALAConfig, obs_dim: int, num_actions: int):
    """Builds ``update(state, batch) -> (state, stats)`` — pure jax, so
    it can be jitted locally or shipped to a LearnerGroup. Batch layout
    is [B, T, ...] (batch-major so a gang shards envs, not time)."""
    import jax
    import jax.numpy as jnp
    import optax

    optimizer = optax.adam(cfg.lr)

    def vtrace(values, rewards, dones, rhos, bootstrap):
        """V-trace targets (IMPALA paper eq. 1) via a reverse scan over
        time. Shapes [B, T]; bootstrap [B]."""
        rho = jnp.minimum(rhos, cfg.vtrace_clip_rho)
        c = jnp.minimum(rhos, cfg.vtrace_clip_c)
        nonterminal = 1.0 - dones
        next_values = jnp.concatenate(
            [values[:, 1:], bootstrap[:, None]], axis=1
        )
        deltas = rho * (rewards + cfg.gamma * next_values * nonterminal - values)

        def step(carry, xs):
            delta_t, c_t, nt_t = xs
            carry = delta_t + cfg.gamma * nt_t * c_t * carry
            return carry, carry

        # scan over time reversed (time axis moved to front for the scan)
        xs = (
            jnp.moveaxis(deltas, 1, 0)[::-1],
            jnp.moveaxis(c, 1, 0)[::-1],
            jnp.moveaxis(nonterminal, 1, 0)[::-1],
        )
        _, acc = jax.lax.scan(step, jnp.zeros_like(bootstrap), xs)
        vs_minus_v = jnp.moveaxis(acc[::-1], 0, 1)
        vs = values + vs_minus_v
        next_vs = jnp.concatenate([vs[:, 1:], bootstrap[:, None]], axis=1)
        pg_adv = rho * (rewards + cfg.gamma * next_vs * nonterminal - values)
        return jax.lax.stop_gradient(vs), jax.lax.stop_gradient(pg_adv)

    def loss_fn(params, batch):
        B, T = batch["actions"].shape
        flat_obs = batch["obs"].reshape(B * T, -1)
        logits, values = apply_mlp_policy(params, flat_obs)
        logits = logits.reshape(B, T, -1)
        values = values.reshape(B, T)
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(
            logp_all, batch["actions"][..., None], axis=-1
        )[..., 0]
        rhos = jnp.exp(logp - batch["behavior_logp"])
        vs, pg_adv = vtrace(
            values,
            batch["rewards"],
            batch["dones"].astype(jnp.float32),
            rhos,
            batch["bootstrap"],
        )
        pi_loss = -(logp * pg_adv).mean()
        vf_loss = ((values - vs) ** 2).mean()
        entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
        total = pi_loss + cfg.vf_coeff * vf_loss - cfg.entropy_coeff * entropy
        return total, (pi_loss, vf_loss, entropy)

    def update(state, batch):
        params, opt_state = state
        (total, (pi_loss, vf_loss, entropy)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return (params, opt_state), {
            "loss": total,
            "pi_loss": pi_loss,
            "vf_loss": vf_loss,
            "entropy": entropy,
        }

    def init_state():
        import jax as _jax

        params = init_mlp_policy(
            _jax.random.PRNGKey(cfg.seed), obs_dim, num_actions, cfg.hidden
        )
        return (params, optimizer.init(params))

    return init_state, update


class IMPALA:
    """Async sample → V-trace learn loop (Tune-trainable surface)."""

    def __init__(self, config: IMPALAConfig):
        import jax

        from ray_tpu.rl.utils import make_runners, probe_env_spec

        self.config = config
        obs_dim, num_actions = probe_env_spec(config.env, config.env_config)

        init_state, update = _make_vtrace_update(config, obs_dim, num_actions)
        self._group = None
        if config.num_learners > 1:
            from ray_tpu.rl.learner_group import LearnerGroup

            self._group = LearnerGroup(
                num_learners=config.num_learners,
                init_fn=init_state,
                update_builder=lambda: update,
                platform=config.learner_platform,
            )
            self._state = None
        else:
            self._state = init_state()
            self._update = jax.jit(update)
        self.iteration = 0
        self._recent_returns: List[float] = []

        self.runners = make_runners(config)
        #: runner index -> in-flight sample ref (the ASYNC loop: runners
        #: never wait for the learner)
        self._inflight: Dict[int, Any] = {}

    # -- weights ---------------------------------------------------------
    def _params(self):
        if self._group is not None:
            return self._group.get_params()
        return self._state[0]

    def _dispatch(self, i: int, params) -> None:
        self._inflight[i] = self.runners[i].sample.remote(
            params, self.config.rollout_fragment_length
        )

    # -- one training iteration -----------------------------------------
    def train(self) -> Dict[str, Any]:
        cfg = self.config
        t0 = time.perf_counter()
        params = self._params()
        for i in range(len(self.runners)):
            if i not in self._inflight:
                self._dispatch(i, params)

        stats: Dict[str, float] = {}
        steps = 0
        consumed = 0
        # consume a budget of fragments, one learner update each; the
        # runners stay busy throughout (async: a fragment is re-dispatched
        # the moment it's consumed, with the freshest weights)
        while consumed < cfg.rollouts_per_iteration:
            refs = list(self._inflight.values())
            ready, _ = ray_tpu.wait(refs, num_returns=1, timeout=300)
            if not ready:
                # a hung EnvRunner must surface, not spin this loop forever
                raise ray_tpu.GetTimeoutError(
                    "no rollout completed within 300s (hung env runner?)"
                )
            ready_set = {r.binary() for r in ready}
            more, _ = ray_tpu.wait(refs, num_returns=len(refs), timeout=0)
            ready_set |= {r.binary() for r in more}
            for i, ref in list(self._inflight.items()):
                if ref.binary() not in ready_set:
                    continue
                rollout = ray_tpu.get(ref, timeout=300)
                del self._inflight[i]
                batch = self._to_batch(rollout)
                steps += batch["actions"].size
                if self._group is not None:
                    stats = self._group.update(batch)
                    params = self._group.get_params()
                else:
                    self._state, jstats = self._update(self._state, batch)
                    stats = {k: float(v) for k, v in jstats.items()}
                    params = self._state[0]
                self._recent_returns.extend(rollout["episode_returns"])
                consumed += 1
                # IMPALA weight broadcast: staleness = one fragment
                self._dispatch(i, params)

        self.iteration += 1
        self._recent_returns = self._recent_returns[-100:]
        mean_return = (
            float(np.mean(self._recent_returns)) if self._recent_returns else 0.0
        )
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": mean_return,
            "num_env_steps_trained": steps,
            "iter_time_s": round(time.perf_counter() - t0, 3),
            **stats,
        }

    @staticmethod
    def _to_batch(rollout) -> Dict[str, np.ndarray]:
        """[T, N, ...] rollout → batch-major [N, T, ...] arrays (a gang
        shards on envs, never mid-trajectory)."""
        return {
            "obs": np.moveaxis(rollout["obs"], 0, 1).astype(np.float32),
            "actions": np.moveaxis(rollout["actions"], 0, 1).astype(np.int32),
            "behavior_logp": np.moveaxis(rollout["logp"], 0, 1).astype(np.float32),
            "rewards": np.moveaxis(rollout["rewards"], 0, 1).astype(np.float32),
            "dones": np.moveaxis(rollout["dones"], 0, 1).astype(np.float32),
            "bootstrap": rollout["last_values"].astype(np.float32),
        }

    # -- Tune/state surface ---------------------------------------------
    def get_state(self) -> Dict[str, Any]:
        import jax

        state = self._group.get_state() if self._group is not None else self._state
        return {
            "state": jax.tree_util.tree_map(np.asarray, state),
            "iteration": self.iteration,
        }

    def set_state(self, state: Dict[str, Any]) -> None:
        if self._group is not None:
            self._group.set_state(state["state"])
        else:
            self._state = state["state"]
        self.iteration = state["iteration"]

    def compute_single_action(self, obs) -> int:
        from ray_tpu.rl.utils import greedy_action

        return greedy_action(self._params(), obs)

    def stop(self) -> None:
        for r in self.runners:
            try:
                ray_tpu.get(r.close.remote(), timeout=10)
                ray_tpu.kill(r)
            except Exception:
                pass
        if self._group is not None:
            self._group.shutdown()
