"""LearnerGroup: a gang of learner processes running ONE pjit program.

Reference: ``rllib/core/learner/learner_group.py:81`` — remote Learner
actors whose module updates are synchronized with DDP all-reduce
(``torch_learner.py:576-590``). TPU-native redesign: the learners join a
``jax.distributed`` gang; the update is a single jitted SPMD program
over a global device mesh with the batch sharded on its leading axis —
XLA inserts the gradient psum, so an N-learner update is numerically
IDENTICAL to a 1-learner update on the concatenated batch (no
DDP wrapper, no gradient bucketing).

Learners are dedicated actors (one fresh process each); CPU gangs (tests)
force ``JAX_PLATFORMS=cpu`` before the first jax import.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, List, Optional

import cloudpickle
import numpy as np

import ray_tpu

logger = logging.getLogger(__name__)


class _LearnerWorker:
    """One gang member. Defined undecorated for by-reference pickling."""

    def __init__(self, rank: int, world: int, platform: Optional[str]):
        import os

        self._rank = rank
        self._world = world
        self._platform = platform
        # belt: effective if jax is not yet imported in this process
        if platform:
            os.environ["JAX_PLATFORMS"] = platform
        self._state = None
        self._update = None
        self._mesh = None

    def get_coordinator(self) -> str:
        import socket

        # a routable host address — loopback would strand ranks on other
        # nodes waiting for a coordinator that isn't there
        try:
            host = socket.gethostbyname(socket.gethostname())
        except OSError:
            host = "127.0.0.1"
        s = socket.socket()
        s.bind((host if host != "127.0.0.1" else "", 0))
        port = s.getsockname()[1]
        s.close()
        return f"{host}:{port}"

    def initialize(self, coordinator: Optional[str]) -> int:
        import jax

        # suspenders: unpickling this class already imported jax (the rl
        # package pulls in models.py), so the __init__ env var came too
        # late — config.update works post-import and keeps a "cpu" gang
        # off the chip
        if self._platform:
            try:
                jax.config.update("jax_platforms", self._platform)
            except Exception:
                pass  # backend already initialized
        if self._world > 1:
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=self._world,
                process_id=self._rank,
            )
        return len(jax.devices())

    def get_params(self):
        """Weight-broadcast payload: params only (opt state stays put)."""
        import jax

        return jax.tree_util.tree_map(
            lambda x: np.asarray(jax.device_get(x)), self._state[0]
        )

    def build(self, init_fn_b: bytes, update_builder_b: bytes) -> bool:
        """``init_fn() -> state`` must be deterministic (same seed on
        every learner → replicated state); ``update_builder() ->
        fn(state, batch) -> (state, stats)`` is pure jax and gets jitted
        over the global mesh."""
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        init_fn = cloudpickle.loads(init_fn_b)
        update = cloudpickle.loads(update_builder_b)()
        # The mesh's outer axis is sized by the GANG (one row per learner
        # process), not by len(jax.devices()): a host-device mesh of
        # world*8 CPU devices must not demand a batch divisible by 16
        # when there are 2 learners feeding 2-row shards. Each learner's
        # local devices form an inner axis that ALSO data-parallelizes
        # when the batch divides (P(("dp","repl"))), falling back to
        # per-process replication for small batches.
        devices = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
        per_proc = len(devices) // self._world
        mesh_devices = np.array(devices).reshape(self._world, per_proc)
        self._mesh = Mesh(mesh_devices, ("dp", "repl"))
        self._full_sharding = NamedSharding(self._mesh, P(("dp", "repl")))
        self._proc_sharding = NamedSharding(self._mesh, P("dp"))
        self._n_devices = len(devices)
        self._state = init_fn()  # plain host arrays, identical per rank
        self._update = jax.jit(update)
        return True

    def _global_batch(self, local_batch: Dict[str, np.ndarray]):
        import jax

        rows = len(next(iter(local_batch.values()))) * self._world
        sharding = (
            self._full_sharding if rows % self._n_devices == 0
            else self._proc_sharding
        )

        def to_global(x):
            x = np.asarray(x)
            return jax.make_array_from_process_local_data(sharding, x)

        return {k: to_global(v) for k, v in local_batch.items()}

    def update(self, local_batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        """One SPMD update step: every learner must call this with its
        shard of the same global batch (the compiled collectives
        synchronize the gang)."""
        batch = self._global_batch(local_batch)
        self._state, stats = self._update(self._state, batch)
        import jax

        return {k: float(jax.device_get(v)) for k, v in stats.items()}

    def get_state(self):
        import jax

        return jax.tree_util.tree_map(
            lambda x: np.asarray(jax.device_get(x)), self._state
        )

    def set_state(self, state) -> None:
        self._state = state


LearnerWorker = ray_tpu.remote(_LearnerWorker)


class LearnerGroup:
    """Driver-side handle on the learner gang."""

    def __init__(
        self,
        *,
        num_learners: int,
        init_fn: Callable[[], Any],
        update_builder: Callable[[], Callable],
        platform: Optional[str] = "cpu",
        resources_per_learner: Optional[Dict[str, float]] = None,
    ):
        self.num_learners = max(1, num_learners)
        res = dict(resources_per_learner or {})
        num_cpus = res.pop("CPU", 1.0)
        self._learners = [
            LearnerWorker.options(num_cpus=num_cpus, resources=res or None).remote(
                rank, self.num_learners, platform
            )
            for rank in range(self.num_learners)
        ]
        coordinator = None
        if self.num_learners > 1:
            coordinator = ray_tpu.get(
                self._learners[0].get_coordinator.remote(), timeout=120
            )
        # initialize CONCURRENTLY: jax.distributed blocks until the whole
        # gang arrives
        ray_tpu.get(
            [l.initialize.remote(coordinator) for l in self._learners],
            timeout=300,
        )
        init_b = cloudpickle.dumps(init_fn)
        upd_b = cloudpickle.dumps(update_builder)
        ray_tpu.get(
            [l.build.remote(init_b, upd_b) for l in self._learners], timeout=300
        )

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        """Shard ``batch`` on its leading axis and run one gang update.
        The leading dimension is trimmed to a multiple of the gang size
        (global batch shape must be identical across learners)."""
        n = self.num_learners
        first = next(iter(batch.values()))
        usable = (len(first) // n) * n
        if usable == 0:
            raise ValueError(
                f"batch of {len(first)} rows cannot feed {n} learners"
            )
        refs = []
        for i in range(n):
            shard = {k: v[i * usable // n : (i + 1) * usable // n] for k, v in batch.items()}
            refs.append(self._learners[i].update.remote(shard))
        stats = ray_tpu.get(refs, timeout=600)
        return stats[0]

    def get_state(self):
        return ray_tpu.get(self._learners[0].get_state.remote(), timeout=120)

    def get_params(self):
        """Params only — the per-fragment weight broadcast must not drag
        optimizer moments (~3x the bytes) across the wire."""
        return ray_tpu.get(self._learners[0].get_params.remote(), timeout=120)

    def set_state(self, state) -> None:
        ray_tpu.get(
            [l.set_state.remote(state) for l in self._learners], timeout=120
        )

    def shutdown(self) -> None:
        for l in self._learners:
            try:
                ray_tpu.kill(l)
            except Exception:
                pass
        self._learners = []
