"""RL models: pure-JAX MLP policy/value networks.

Reference: ``rllib/core/rl_module/`` (RLModule abstraction; torch).
TPU-native: params are plain pytrees, ``apply`` is jit/pjit-able, and
the same function serves actors (CPU rollout) and learners (TPU)."""

from __future__ import annotations

import math
from typing import Any, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp


def init_mlp_policy(
    rng: jax.Array,
    obs_dim: int,
    num_actions: int,
    hidden: Sequence[int] = (64, 64),
) -> Dict[str, Any]:
    """Shared torso + policy logits head + value head."""
    params: Dict[str, Any] = {"layers": [], "pi": None, "vf": None}
    sizes = [obs_dim, *hidden]
    keys = jax.random.split(rng, len(hidden) + 2)
    for i, (fan_in, fan_out) in enumerate(zip(sizes[:-1], sizes[1:])):
        scale = math.sqrt(2.0 / fan_in)
        params["layers"].append(
            {
                "w": jax.random.normal(keys[i], (fan_in, fan_out)) * scale,
                "b": jnp.zeros((fan_out,)),
            }
        )
    last = sizes[-1]
    params["pi"] = {
        "w": jax.random.normal(keys[-2], (last, num_actions)) * 0.01,
        "b": jnp.zeros((num_actions,)),
    }
    params["vf"] = {
        "w": jax.random.normal(keys[-1], (last, 1)) * 1.0,
        "b": jnp.zeros((1,)),
    }
    return params


def apply_mlp_policy(params: Dict[str, Any], obs: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """obs [B, obs_dim] → (logits [B, A], value [B])."""
    x = obs
    for layer in params["layers"]:
        x = jnp.tanh(x @ layer["w"] + layer["b"])
    logits = x @ params["pi"]["w"] + params["pi"]["b"]
    value = (x @ params["vf"]["w"] + params["vf"]["b"])[..., 0]
    return logits, value
