"""RL models: pure-JAX MLP policy/value networks.

Reference: ``rllib/core/rl_module/`` (RLModule abstraction; torch).
TPU-native: params are plain pytrees, ``apply`` is jit/pjit-able, and
the same function serves actors (CPU rollout) and learners (TPU)."""

from __future__ import annotations

import math
from typing import Any, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp


def init_mlp_policy(
    rng: jax.Array,
    obs_dim: int,
    num_actions: int,
    hidden: Sequence[int] = (64, 64),
) -> Dict[str, Any]:
    """Shared torso + policy logits head + value head."""
    params: Dict[str, Any] = {"layers": [], "pi": None, "vf": None}
    sizes = [obs_dim, *hidden]
    keys = jax.random.split(rng, len(hidden) + 2)
    for i, (fan_in, fan_out) in enumerate(zip(sizes[:-1], sizes[1:])):
        scale = math.sqrt(2.0 / fan_in)
        params["layers"].append(
            {
                "w": jax.random.normal(keys[i], (fan_in, fan_out)) * scale,
                "b": jnp.zeros((fan_out,)),
            }
        )
    last = sizes[-1]
    params["pi"] = {
        "w": jax.random.normal(keys[-2], (last, num_actions)) * 0.01,
        "b": jnp.zeros((num_actions,)),
    }
    params["vf"] = {
        "w": jax.random.normal(keys[-1], (last, 1)) * 1.0,
        "b": jnp.zeros((1,)),
    }
    return params


def apply_mlp_policy(params: Dict[str, Any], obs: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """obs [B, obs_dim] → (logits [B, A], value [B])."""
    x = obs
    for layer in params["layers"]:
        x = jnp.tanh(x @ layer["w"] + layer["b"])
    logits = x @ params["pi"]["w"] + params["pi"]["b"]
    value = (x @ params["vf"]["w"] + params["vf"]["b"])[..., 0]
    return logits, value


# ---------------------------------------------------------------------------
# Q-networks (DQN) — MLP for vector obs, CNN for image obs


def init_mlp_q(
    rng: jax.Array,
    obs_dim: int,
    num_actions: int,
    hidden: Sequence[int] = (128, 128),
) -> Dict[str, Any]:
    """MLP Q-network: obs -> Q(s, a) per action (reference
    rllib/algorithms/dqn catalog, torch; pure-JAX here)."""
    params: Dict[str, Any] = {"layers": [], "q": None}
    sizes = [obs_dim, *hidden]
    keys = jax.random.split(rng, len(hidden) + 1)
    for i, (fan_in, fan_out) in enumerate(zip(sizes[:-1], sizes[1:])):
        scale = math.sqrt(2.0 / fan_in)
        params["layers"].append(
            {
                "w": jax.random.normal(keys[i], (fan_in, fan_out)) * scale,
                "b": jnp.zeros((fan_out,)),
            }
        )
    params["q"] = {
        "w": jax.random.normal(keys[-1], (sizes[-1], num_actions)) * 0.01,
        "b": jnp.zeros((num_actions,)),
    }
    return params


def apply_mlp_q(params: Dict[str, Any], obs: jnp.ndarray) -> jnp.ndarray:
    """obs [B, obs_dim] -> q-values [B, A]."""
    x = obs.reshape(obs.shape[0], -1)
    for layer in params["layers"]:
        x = jax.nn.relu(x @ layer["w"] + layer["b"])
    return x @ params["q"]["w"] + params["q"]["b"]


#: Atari-style conv stack (reference rllib CNN defaults): NHWC input.
DEFAULT_CONV = ((16, 8, 4), (32, 4, 2), (32, 3, 1))  # (channels, kernel, stride)


def init_cnn(
    rng: jax.Array,
    obs_shape: Sequence[int],  # (H, W, C)
    num_actions: int,
    *,
    conv: Sequence[Tuple[int, int, int]] = DEFAULT_CONV,
    dense: int = 256,
    heads: Sequence[str] = ("q",),
) -> Dict[str, Any]:
    """Conv torso + dense + one linear head per name in ``heads``
    ("q" for DQN, "pi"+"vf" for actor-critic on images). Convs run as
    ``lax.conv_general_dilated`` in NHWC — XLA lays them onto the MXU."""
    if tuple(conv) != DEFAULT_CONV:
        raise ValueError(
            "custom conv stacks need their own apply fn: the stride "
            "schedule is STATIC (a pytree-carried int would be traced "
            "under jit) and the module-level apply_cnn_* assume "
            "DEFAULT_CONV"
        )
    h, w, c = obs_shape
    keys = jax.random.split(rng, len(conv) + 1 + len(heads))
    params: Dict[str, Any] = {"conv": [], "dense": None}
    in_ch = c
    for i, (out_ch, k, s) in enumerate(conv):
        scale = math.sqrt(2.0 / (k * k * in_ch))
        params["conv"].append(
            {
                "w": jax.random.normal(keys[i], (k, k, in_ch, out_ch)) * scale,
                "b": jnp.zeros((out_ch,)),
            }
        )
        h = -(-h // s)  # ceil division (SAME padding)
        w = -(-w // s)
        in_ch = out_ch
    flat = h * w * in_ch
    params["dense"] = {
        "w": jax.random.normal(keys[len(conv)], (flat, dense)) * math.sqrt(2.0 / flat),
        "b": jnp.zeros((dense,)),
    }
    for j, head in enumerate(heads):
        out = 1 if head == "vf" else num_actions
        params[head] = {
            "w": jax.random.normal(keys[len(conv) + 1 + j], (dense, out)) * 0.01,
            "b": jnp.zeros((out,)),
        }
    return params


def _cnn_torso(params: Dict[str, Any], obs: jnp.ndarray) -> jnp.ndarray:
    x = obs  # already float32, normalized by the apply_* wrappers
    for layer, (_ch, _k, stride) in zip(params["conv"], DEFAULT_CONV):
        x = jax.lax.conv_general_dilated(
            x,
            layer["w"],
            window_strides=(stride, stride),  # static (not pytree-carried)
            padding="SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        x = jax.nn.relu(x + layer["b"])
    x = x.reshape(x.shape[0], -1)
    return jax.nn.relu(x @ params["dense"]["w"] + params["dense"]["b"])


def apply_cnn_q(params: Dict[str, Any], obs: jnp.ndarray) -> jnp.ndarray:
    """obs [B, H, W, C] (float or uint8) -> q-values [B, A]."""
    x = _cnn_torso(params, obs.astype(jnp.float32) / 255.0 if obs.dtype == jnp.uint8 else obs)
    return x @ params["q"]["w"] + params["q"]["b"]


def apply_cnn_policy(params: Dict[str, Any], obs: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """obs [B, H, W, C] -> (logits [B, A], value [B]) — the image-obs
    actor-critic head pair (PPO/IMPALA on pixels)."""
    x = _cnn_torso(params, obs.astype(jnp.float32) / 255.0 if obs.dtype == jnp.uint8 else obs)
    logits = x @ params["pi"]["w"] + params["pi"]["b"]
    value = (x @ params["vf"]["w"] + params["vf"]["b"])[:, 0]
    return logits, value
