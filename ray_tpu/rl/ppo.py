"""PPO: the flagship RL algorithm.

Reference: ``rllib/algorithms/ppo/ppo.py:374,400`` — training_step =
parallel sampling on EnvRunner actors → GAE → clipped-surrogate SGD on
a Learner — and ``core/learner/learner_group.py`` (the learner gang).

TPU-native redesign: the learner is one jitted (pjit-able) update over
the whole rollout batch; EnvRunner actors sample on CPU while the
compiled update runs on the accelerator. The Algorithm implements the
Tune trainable surface (``train()`` returns a metrics dict,
``save``/``restore`` via state dicts), so ``tune.Tuner(ppo_factory)``
sweeps hyperparameters exactly like the reference."""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rl.models import apply_mlp_policy, init_mlp_policy


@dataclass
class PPOConfig:
    """Reference ``PPOConfig`` (algorithm_config builder) as a dataclass."""

    env: str = "CartPole-v1"
    env_config: Optional[Dict[str, Any]] = None
    num_env_runners: int = 2
    num_envs_per_runner: int = 4
    rollout_fragment_length: int = 64  # steps per env per iteration
    gamma: float = 0.99
    gae_lambda: float = 0.95
    clip_eps: float = 0.2
    entropy_coeff: float = 0.01
    vf_coeff: float = 0.5
    lr: float = 3e-4
    num_epochs: int = 4
    minibatch_size: int = 256
    hidden: tuple = (64, 64)
    seed: int = 0
    runner_resources: Dict[str, float] = field(default_factory=lambda: {"CPU": 0.5})

    def build(self) -> "PPO":
        return PPO(self)


class PPO:
    """EnvRunner gang + jitted JAX learner (reference Algorithm)."""

    def __init__(self, config: PPOConfig):
        import jax
        import optax

        from ray_tpu.rl.utils import make_runners, probe_env_spec

        self.config = config
        obs_dim, num_actions = probe_env_spec(config.env, config.env_config)

        rng = jax.random.PRNGKey(config.seed)
        self.params = init_mlp_policy(rng, obs_dim, num_actions, config.hidden)
        self.optimizer = optax.adam(config.lr)
        self.opt_state = self.optimizer.init(self.params)
        self.iteration = 0
        self._update = jax.jit(self._make_update())

        self.runners = make_runners(config)
        self._recent_returns: List[float] = []

    # -- learner ---------------------------------------------------------
    def _make_update(self):
        import jax
        import jax.numpy as jnp
        import optax

        cfg = self.config

        def loss_fn(params, batch):
            logits, values = apply_mlp_policy(params, batch["obs"])
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, batch["actions"][:, None], axis=1
            )[:, 0]
            ratio = jnp.exp(logp - batch["logp_old"])
            adv = batch["advantages"]
            surr = jnp.minimum(
                ratio * adv,
                jnp.clip(ratio, 1 - cfg.clip_eps, 1 + cfg.clip_eps) * adv,
            )
            pi_loss = -surr.mean()
            vf_loss = ((values - batch["returns"]) ** 2).mean()
            entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
            total = pi_loss + cfg.vf_coeff * vf_loss - cfg.entropy_coeff * entropy
            return total, (pi_loss, vf_loss, entropy)

        def update(params, opt_state, batch):
            (total, (pi_loss, vf_loss, entropy)), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(params, batch)
            updates, opt_state = self.optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, {
                "loss": total,
                "pi_loss": pi_loss,
                "vf_loss": vf_loss,
                "entropy": entropy,
            }

        return update

    @staticmethod
    def _gae(rollout, gamma: float, lam: float):
        """Generalized advantage estimation over [T, N] arrays."""
        rewards = rollout["rewards"]
        values = rollout["values"]
        dones = rollout["dones"].astype(np.float32)
        T = rewards.shape[0]
        adv = np.zeros_like(rewards)
        last = np.zeros_like(rollout["last_values"])
        next_value = rollout["last_values"]
        for t in reversed(range(T)):
            nonterminal = 1.0 - dones[t]
            delta = rewards[t] + gamma * next_value * nonterminal - values[t]
            last = delta + gamma * lam * nonterminal * last
            adv[t] = last
            next_value = values[t]
        returns = adv + values
        return adv, returns

    # -- Tune trainable surface -----------------------------------------
    def train(self) -> Dict[str, Any]:
        """One training iteration (reference ``Algorithm.train``)."""
        import jax.numpy as jnp

        cfg = self.config
        t0 = time.perf_counter()
        rollouts = ray_tpu.get(
            [
                r.sample.remote(self.params, cfg.rollout_fragment_length)
                for r in self.runners
            ],
            timeout=600,
        )
        sample_time = time.perf_counter() - t0

        obs, actions, logp_old, advs, rets = [], [], [], [], []
        for ro in rollouts:
            adv, ret = self._gae(ro, cfg.gamma, cfg.gae_lambda)
            obs.append(ro["obs"].reshape(-1, ro["obs"].shape[-1]))
            actions.append(ro["actions"].reshape(-1))
            logp_old.append(ro["logp"].reshape(-1))
            advs.append(adv.reshape(-1))
            rets.append(ret.reshape(-1))
            self._recent_returns.extend(ro["episode_returns"])
        obs = np.concatenate(obs)
        actions = np.concatenate(actions)
        logp_old = np.concatenate(logp_old)
        advs = np.concatenate(advs)
        rets = np.concatenate(rets)
        advs = (advs - advs.mean()) / (advs.std() + 1e-8)

        n = len(obs)
        rng = np.random.default_rng(cfg.seed + self.iteration)
        stats: Dict[str, Any] = {}
        for _ in range(cfg.num_epochs):
            perm = rng.permutation(n)
            for start in range(0, n, cfg.minibatch_size):
                idx = perm[start : start + cfg.minibatch_size]
                batch = {
                    "obs": jnp.asarray(obs[idx]),
                    "actions": jnp.asarray(actions[idx]),
                    "logp_old": jnp.asarray(logp_old[idx]),
                    "advantages": jnp.asarray(advs[idx]),
                    "returns": jnp.asarray(rets[idx]),
                }
                self.params, self.opt_state, stats = self._update(
                    self.params, self.opt_state, batch
                )
        self.iteration += 1
        self._recent_returns = self._recent_returns[-100:]
        mean_return = (
            float(np.mean(self._recent_returns)) if self._recent_returns else 0.0
        )
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": mean_return,
            "num_env_steps_sampled": n,
            "sample_time_s": round(sample_time, 3),
            **{k: float(v) for k, v in stats.items()},
        }

    def get_state(self) -> Dict[str, Any]:
        import jax

        return {
            "params": jax.tree_util.tree_map(np.asarray, self.params),
            "opt_state": jax.tree_util.tree_map(np.asarray, self.opt_state),
            "iteration": self.iteration,
        }

    def set_state(self, state: Dict[str, Any]) -> None:
        self.params = state["params"]
        self.opt_state = state["opt_state"]
        self.iteration = state["iteration"]

    def compute_single_action(self, obs) -> int:
        """Greedy action for evaluation."""
        from ray_tpu.rl.utils import greedy_action

        return greedy_action(self.params, obs)

    def stop(self) -> None:
        for r in self.runners:
            try:
                ray_tpu.get(r.close.remote(), timeout=10)
                ray_tpu.kill(r)
            except Exception:
                pass

