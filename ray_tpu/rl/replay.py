"""Replay buffer for off-policy learning.

Reference: ``rllib/utils/replay_buffers/`` (EpisodeReplayBuffer used by
DQN/SAC). A flat circular numpy transition store — uniform sampling;
arrays preallocate on first add so image observations don't pay a
per-transition object overhead.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np


class ReplayBuffer:
    def __init__(self, capacity: int, seed: Optional[int] = None):
        self.capacity = int(capacity)
        self._rng = np.random.default_rng(seed)
        self._storage: Dict[str, np.ndarray] = {}
        self._idx = 0
        self._size = 0

    def __len__(self) -> int:
        return self._size

    def add_batch(self, batch: Dict[str, np.ndarray]) -> None:
        """Append a batch of transitions (leading dim = batch)."""
        n = len(next(iter(batch.values())))
        if not self._storage:
            for k, v in batch.items():
                v = np.asarray(v)
                self._storage[k] = np.zeros(
                    (self.capacity, *v.shape[1:]), dtype=v.dtype
                )
        for start in range(0, n, self.capacity):
            chunk = {k: np.asarray(v)[start : start + self.capacity] for k, v in batch.items()}
            m = len(next(iter(chunk.values())))
            end = self._idx + m
            for k, v in chunk.items():
                if end <= self.capacity:
                    self._storage[k][self._idx : end] = v
                else:
                    split = self.capacity - self._idx
                    self._storage[k][self._idx :] = v[:split]
                    self._storage[k][: end - self.capacity] = v[split:]
            self._idx = end % self.capacity
            self._size = min(self.capacity, self._size + m)

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        idx = self._rng.integers(0, self._size, size=batch_size)
        return {k: v[idx] for k, v in self._storage.items()}
