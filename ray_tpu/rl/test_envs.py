"""Tiny deterministic test environments (CI stand-ins for Atari).

Reference test model: rllib's fake/random envs under ``rllib/env/tests``
— learning tests need an env whose optimal policy is discoverable in
seconds on CPU, with the same observation modality as the real target.
Use as ``env="ray_tpu.rl.test_envs:TinyImageEnv"`` (the module:class
form resolves on any worker by import path).
"""

from __future__ import annotations

import numpy as np

try:
    import gymnasium as gym
    from gymnasium import spaces
except ImportError:  # pragma: no cover
    gym = None


class TinyImageEnv(gym.Env if gym else object):
    """An 8x8x3 uint8 image shows a target pixel in the left or right
    half; the agent must press 0 (left) or 1 (right). Reward +1 for the
    correct side, episode length 16 — optimal return 16, random ~8.
    The smallest env that genuinely requires READING the image."""

    metadata = {"render_modes": []}

    def __init__(self, size: int = 8, episode_len: int = 16, seed: int = 0):
        self.size = size
        self.episode_len = episode_len
        self.observation_space = spaces.Box(
            low=0, high=255, shape=(size, size, 3), dtype=np.uint8
        )
        self.action_space = spaces.Discrete(2)
        self._rng = np.random.default_rng(seed)
        self._t = 0
        self._side = 0

    def _obs(self) -> np.ndarray:
        img = np.zeros((self.size, self.size, 3), np.uint8)
        row = int(self._rng.integers(0, self.size))
        half = self.size // 2
        col = int(self._rng.integers(0, half))
        if self._side == 1:
            col += half
        img[row, col] = 255
        return img

    def reset(self, *, seed=None, options=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._t = 0
        self._side = int(self._rng.integers(0, 2))
        return self._obs(), {}

    def step(self, action):
        reward = 1.0 if int(action) == self._side else 0.0
        self._t += 1
        self._side = int(self._rng.integers(0, 2))
        terminated = False
        truncated = self._t >= self.episode_len
        return self._obs(), reward, terminated, truncated, {}
