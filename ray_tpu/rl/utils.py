"""Shared algorithm plumbing (PPO + IMPALA).

Reference: the pieces ``rllib/algorithms/algorithm.py`` provides to every
algorithm — env-space probing, the EnvRunnerGroup construction, greedy
evaluation actions."""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.rl.env_runner import EnvRunner
from ray_tpu.rl.models import apply_mlp_policy


def _make_probe_env(env: str, env_config: Optional[Dict[str, Any]]):
    if ":" in env:
        from ray_tpu.rl.env_runner import resolve_env_class

        return resolve_env_class(env)(**(env_config or {}))
    import gymnasium as gym

    return gym.make(env, **(env_config or {}))


def probe_env_spec(env: str, env_config: Optional[Dict[str, Any]]) -> Tuple[int, int]:
    """(obs_dim, num_actions) from one throwaway env instance."""
    shape, num_actions = probe_env_space(env, env_config)
    return int(np.prod(shape)), num_actions


def probe_env_space(env: str, env_config: Optional[Dict[str, Any]]) -> Tuple[tuple, int]:
    """(obs_shape, num_actions) — shape preserved for image obs (CNN)."""
    probe = _make_probe_env(env, env_config)
    shape = tuple(probe.observation_space.shape)
    num_actions = int(probe.action_space.n)
    probe.close()
    return shape, num_actions


def make_runners(config) -> List[Any]:
    """The EnvRunner gang from any config carrying env/num_env_runners/
    num_envs_per_runner/seed/env_config/runner_resources."""
    return [
        EnvRunner.options(
            num_cpus=config.runner_resources.get("CPU", 0.5),
            resources={
                k: v for k, v in config.runner_resources.items() if k != "CPU"
            }
            or None,
        ).remote(
            config.env,
            config.num_envs_per_runner,
            config.seed + 1000 * i,
            config.env_config,
        )
        for i in range(config.num_env_runners)
    ]


def greedy_action(params, obs) -> int:
    """Deterministic evaluation action."""
    import jax.numpy as jnp

    logits, _ = apply_mlp_policy(params, jnp.asarray(obs, jnp.float32)[None])
    return int(np.argmax(np.asarray(logits)[0]))
