"""Runtime environments: per-task/actor env shipping behind a plugin ABC.

Reference: ``python/ray/_private/runtime_env/plugin.py:24``
(``RuntimeEnvPlugin``) + the pip/conda/working_dir/py_modules plugins
and the per-node agent. TPU-native compression: no separate agent
process — the driver PACKAGES (zip → content-addressed controller-KV
upload) at submission, the executing worker APPLIES (download → per-hash
cache extract → sys.path/cwd) before running the task, both through the
plugin registry here.

    @ray_tpu.remote(runtime_env={"working_dir": "./my_project",
                                 "py_modules": ["./libs/helper"],
                                 "env_vars": {"TOKENIZERS_PARALLELISM": "false"}})
    def train(): ...
"""

from __future__ import annotations

import os
import sys
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.runtime_env.packaging import (
    URI_PREFIX,
    ensure_local,
    upload_package,
    zip_directory,
)


class RuntimeEnvPlugin:
    """One runtime_env key (reference ``plugin.py:24``). Driver-side
    ``package`` rewrites the value for the wire (uploading code);
    worker-side ``apply`` realizes it and returns a restore callable
    (or None when nothing needs undoing)."""

    name: str = ""
    priority: int = 50  # lower applies first

    def validate(self, value: Any) -> None:
        pass

    def package(self, value: Any, kv_put: Callable, kv_get: Callable) -> Any:
        return value

    def apply(
        self, value: Any, kv_get: Callable, *, permanent: bool
    ) -> Optional[Callable[[], None]]:
        return None


class EnvVarsPlugin(RuntimeEnvPlugin):
    name = "env_vars"
    priority = 10

    def validate(self, value: Any) -> None:
        if not isinstance(value, dict):
            raise ValueError(f"env_vars must be a dict, got {type(value).__name__}")

    def apply(self, value, kv_get, *, permanent: bool):
        saved = {k: os.environ.get(k) for k in value}
        os.environ.update({k: str(v) for k, v in value.items()})
        if permanent:
            return None

        def restore():
            for k, old in saved.items():
                if old is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = old

        return restore


class WorkingDirPlugin(RuntimeEnvPlugin):
    """Ship the driver's project directory (reference working_dir
    plugin): zipped at submit, extracted per-hash on the worker, put at
    the FRONT of sys.path; dedicated (actor) workers also chdir into it."""

    name = "working_dir"
    priority = 20

    def validate(self, value: Any) -> None:
        if not isinstance(value, str):
            raise ValueError("working_dir must be a path or kvpkg:// uri")
        if not value.startswith(URI_PREFIX) and not os.path.isdir(value):
            raise ValueError(f"working_dir {value!r} is not a directory")

    def package(self, value: str, kv_put, kv_get) -> str:
        if value.startswith(URI_PREFIX):
            return value
        return upload_package(kv_put, kv_get, zip_directory(value))

    def apply(self, value: str, kv_get, *, permanent: bool):
        target = ensure_local(kv_get, value)
        sys.path.insert(0, target)
        if permanent:
            os.chdir(target)  # dedicated worker: cwd for its lifetime
            return None

        def restore():
            try:
                sys.path.remove(target)
            except ValueError:
                pass

        return restore


class PyModulesPlugin(RuntimeEnvPlugin):
    """Ship importable modules (reference py_modules plugin): each entry
    is a package directory or single .py file; the worker extracts each
    and adds a directory CONTAINING the module to sys.path."""

    name = "py_modules"
    priority = 30

    def validate(self, value: Any) -> None:
        if not isinstance(value, (list, tuple)):
            raise ValueError("py_modules must be a list of paths/uris")
        for v in value:
            if not isinstance(v, str):
                raise ValueError("py_modules entries must be strings")
            if not v.startswith(URI_PREFIX) and not os.path.exists(v):
                raise ValueError(f"py_modules entry {v!r} does not exist")

    def package(self, value, kv_put, kv_get):
        out = []
        for v in value:
            if v.startswith(URI_PREFIX):
                out.append(v)
                continue
            # a directory keeps its top-level name in the zip so that
            # `import <name>` works from the extraction root
            data = zip_directory(v, include_root=os.path.isdir(v))
            out.append(upload_package(kv_put, kv_get, data))
        return out

    def apply(self, value, kv_get, *, permanent: bool):
        added = []
        for uri in value:
            target = ensure_local(kv_get, uri)
            sys.path.insert(0, target)
            added.append(target)
        if permanent:
            return None

        def restore():
            for t in added:
                try:
                    sys.path.remove(t)
                except ValueError:
                    pass

        return restore


_PLUGINS: Dict[str, RuntimeEnvPlugin] = {}


def register_plugin(plugin: RuntimeEnvPlugin) -> None:
    _PLUGINS[plugin.name] = plugin


for _p in (EnvVarsPlugin(), WorkingDirPlugin(), PyModulesPlugin()):
    register_plugin(_p)


def validate_runtime_env(env: Dict[str, Any]) -> None:
    for key, value in env.items():
        plugin = _PLUGINS.get(key)
        if plugin is None:
            raise ValueError(
                f"unknown runtime_env key {key!r} "
                f"(known: {sorted(_PLUGINS)})"
            )
        plugin.validate(value)


def package_runtime_env(
    env: Dict[str, Any], kv_put: Callable, kv_get: Callable
) -> Dict[str, Any]:
    """Driver side: validate + upload local code, returning the
    wire-form env (local paths replaced by kvpkg:// uris)."""
    validate_runtime_env(env)
    return {
        key: _PLUGINS[key].package(value, kv_put, kv_get)
        for key, value in env.items()
    }


def apply_runtime_env(
    env: Dict[str, Any], kv_get: Callable, *, permanent: bool
) -> List[Callable[[], None]]:
    """Worker side: realize every key (priority order); returns restore
    callables (reverse-apply order)."""
    restores: List[Callable[[], None]] = []
    try:
        for key in sorted(env, key=lambda k: _PLUGINS[k].priority if k in _PLUGINS else 99):
            plugin = _PLUGINS.get(key)
            if plugin is None:
                raise ValueError(f"unknown runtime_env key {key!r}")
            r = plugin.apply(env[key], kv_get, permanent=permanent)
            if r is not None:
                restores.append(r)
    except BaseException:
        # a later plugin failing must not leak earlier plugins' effects
        # (env vars / sys.path entries) into the shared pooled worker
        for r in reversed(restores):
            try:
                r()
            except Exception:
                pass
        raise
    restores.reverse()
    return restores
