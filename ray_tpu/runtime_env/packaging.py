"""Runtime-env packaging: zip local code, ship via controller KV, cache
per-hash on workers.

Reference: ``python/ray/_private/runtime_env/packaging.py`` — local
``working_dir``/``py_modules`` paths zip deterministically, upload once
(content-addressed ``kvpkg://{sha1}``), and extract into a per-hash
cache directory on each node; concurrent extractions are made atomic by
extract-to-temp + rename.
"""

from __future__ import annotations

import hashlib
import io
import os
import shutil
import tempfile
import zipfile
from typing import Callable, List

URI_PREFIX = "kvpkg://"
_KV_PREFIX = b"runtime_env_pkg:"
#: reference cap (GCS_STORAGE_MAX_SIZE); the KV lives in controller memory
MAX_PACKAGE_BYTES = 100 * 1024 * 1024
_EXCLUDE_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}

_CACHE_ROOT = "/tmp/ray_tpu/runtime_env"


def zip_directory(path: str, *, include_root: bool = False) -> bytes:
    """Deterministic zip of a directory tree (or a single file).
    ``include_root=True`` keeps the directory's own name as the zip's
    top level (py_modules: ``import <name>`` works from the extraction
    root); otherwise the zip is rooted at the directory's contents."""
    path = os.path.abspath(path)
    arc_base = os.path.dirname(path) if include_root else path
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        if os.path.isfile(path):
            zf.write(path, os.path.basename(path))
        else:
            entries: List[str] = []
            for root, dirs, files in os.walk(path):
                dirs[:] = sorted(d for d in dirs if d not in _EXCLUDE_DIRS)
                for f in sorted(files):
                    entries.append(os.path.join(root, f))
            for f in entries:
                zf.write(f, os.path.relpath(f, arc_base))
    data = buf.getvalue()
    if len(data) > MAX_PACKAGE_BYTES:
        raise ValueError(
            f"packaged {path!r} is {len(data)} bytes "
            f"(cap {MAX_PACKAGE_BYTES}); exclude large data from "
            "working_dir/py_modules"
        )
    return data


def package_uri(data: bytes) -> str:
    return URI_PREFIX + hashlib.sha1(data).hexdigest()


def upload_package(kv_put: Callable, kv_get: Callable, data: bytes) -> str:
    """Content-addressed upload: skip if the hash is already there."""
    uri = package_uri(data)
    key = _KV_PREFIX + uri[len(URI_PREFIX):].encode()
    if kv_get(key) is None:
        kv_put(key, data)
    return uri


def ensure_local(kv_get: Callable, uri: str) -> str:
    """Worker side: download + extract once per hash; returns the
    extracted directory. Atomic against concurrent workers via
    extract-to-temp + rename."""
    if not uri.startswith(URI_PREFIX):
        raise ValueError(f"not a package uri: {uri!r}")
    digest = uri[len(URI_PREFIX):]
    target = os.path.join(_CACHE_ROOT, digest)
    if os.path.isdir(target):
        return target
    data = kv_get(_KV_PREFIX + digest.encode())
    if data is None:
        raise FileNotFoundError(f"runtime-env package {uri} not in cluster KV")
    os.makedirs(_CACHE_ROOT, exist_ok=True)
    tmp = tempfile.mkdtemp(dir=_CACHE_ROOT, prefix=f".{digest}-")
    try:
        with zipfile.ZipFile(io.BytesIO(data)) as zf:
            zf.extractall(tmp)
        try:
            os.rename(tmp, target)
        except OSError:
            # concurrent extractor won the rename — use theirs
            shutil.rmtree(tmp, ignore_errors=True)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    return target
