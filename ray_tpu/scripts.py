"""Operator CLI: ``python -m ray_tpu.scripts <command>`` (also installed
as the ``ray_tpu`` console entry point when packaged).

Reference: ``python/ray/scripts/scripts.py`` (``ray start/stop/status/
list/timeline/memory``). Commands:

  start --head [--num-cpus N] [--resources JSON] [--port P]
      Start a head (controller + daemon) in the background; prints the
      address workers and drivers connect to.
  start --address HOST:PORT [--num-cpus N]
      Start a worker-node daemon joined to an existing head.
  stop
      Stop every ray_tpu daemon this user started on this machine.
  status --address HOST:PORT
      Cluster resources + node table.
  list (nodes|actors|tasks|objects|pgs) --address HOST:PORT
      State API listings (``ray list ...``).
"""

from __future__ import annotations

import argparse
import json
import os
import select
import signal
import subprocess
import sys
import time

_PID_DIR = "/tmp/ray_tpu"


def _pid_file(kind: str, pid: int) -> str:
    return os.path.join(_PID_DIR, f"{kind}-{pid}.pid")


def _record_pid(kind: str, pid: int) -> None:
    os.makedirs(_PID_DIR, exist_ok=True)
    with open(_pid_file(kind, pid), "w") as f:
        f.write(str(pid))


def _read_ready_line(proc, what: str, log_path: str, timeout: float = 30.0) -> dict:
    """Read a daemon's one-line JSON readiness handshake with a timeout.
    stdout carries exactly that one line; stderr goes to ``log_path``
    (a pipe would eventually fill and block a chatty daemon), which is
    tail-quoted when the daemon dies before becoming ready."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        if proc.poll() is not None:
            tail = ""
            try:
                with open(log_path) as f:
                    lines = f.read().strip().splitlines()
                    tail = lines[-1] if lines else ""
            except OSError:
                pass
            raise SystemExit(
                f"{what} exited (code {proc.returncode}) before becoming "
                f"ready{': ' + tail if tail else ''} (log: {log_path})"
            )
        r, _, _ = select.select([proc.stdout], [], [], 0.2)
        if r:
            line = proc.stdout.readline().strip()
            if line:
                try:
                    return json.loads(line)
                except json.JSONDecodeError:
                    continue  # stray log line on stdout
    proc.terminate()
    raise SystemExit(f"{what} did not become ready within {timeout:.0f}s")


def _daemon_log(kind: str) -> str:
    os.makedirs(_PID_DIR, exist_ok=True)
    return os.path.join(_PID_DIR, f"{kind}-{os.getpid()}-{int(time.time())}.log")


def _connect(address: str):
    """Driver-less controller client for status/list commands."""
    import ray_tpu

    ray_tpu.init(address=address, namespace="cli")
    from ray_tpu.core.api import _global_worker

    return _global_worker().backend


def cmd_start(args) -> int:
    if args.head:
        cmd = [
            sys.executable, "-m", "ray_tpu.core.head_main",
            "--session-dir", args.session_dir
            or f"/tmp/ray_tpu/session_cli_{os.getpid()}",
        ]
        if args.num_cpus is not None:
            cmd += ["--num-cpus", str(args.num_cpus)]
        if args.resources:
            cmd += ["--resources", args.resources]
        log_path = _daemon_log("head")
        with open(log_path, "ab") as logf:
            proc = subprocess.Popen(
                cmd, stdout=subprocess.PIPE, stderr=logf, text=True
            )
        info = _read_ready_line(proc, "head", log_path)
        _record_pid("head", proc.pid)
        # full driver address is host:controller_port:daemon_port
        addr = f"127.0.0.1:{info['controller_port']}:{info['daemon_port']}"
        print(f"ray_tpu head started (pid {proc.pid})")
        print(f"  address: {addr}")
        print(f"  connect: ray_tpu.init(address={addr!r})")
        print(f"  add a node: ray_tpu start --address {addr}")
        return 0
    if not args.address:
        print("start needs --head or --address HOST:PORT", file=sys.stderr)
        return 2
    parts = args.address.split(":")
    controller = ":".join(parts[:2])  # node daemons join the controller
    cmd = [
        sys.executable, "-m", "ray_tpu.core.node_main",
        "--controller", controller,
    ]
    if args.num_cpus is not None:
        cmd += ["--num-cpus", str(args.num_cpus)]
    if args.resources:
        cmd += ["--resources", args.resources]
    log_path = _daemon_log("node")
    with open(log_path, "ab") as logf:
        proc = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=logf, text=True
        )
    info = _read_ready_line(proc, "node daemon", log_path)
    _record_pid("node", proc.pid)
    print(
        f"ray_tpu node daemon started (pid {proc.pid}, "
        f"node {info.get('node_id', '?')[:12]}) -> {args.address}"
    )
    return 0


def cmd_stop(args) -> int:
    stopped = 0
    if os.path.isdir(_PID_DIR):
        for name in os.listdir(_PID_DIR):
            if not name.endswith(".pid"):
                continue
            path = os.path.join(_PID_DIR, name)
            try:
                with open(path) as f:
                    pid = int(f.read().strip())
                # never kill a reused PID: verify it is still a ray_tpu
                # daemon (reference CLI checks cmdline the same way)
                with open(f"/proc/{pid}/cmdline", "rb") as c:
                    cmdline = c.read().replace(b"\0", b" ")
                if b"ray_tpu" in cmdline:
                    os.kill(pid, signal.SIGTERM)
                    stopped += 1
            except (OSError, ValueError):
                pass
            try:
                os.remove(path)
            except OSError:
                pass
    print(f"stopped {stopped} daemon(s)")
    return 0


def cmd_status(args) -> int:
    core = _connect(args.address)
    total = core.cluster_resources()
    avail = core.available_resources()
    nodes = core.nodes()
    print(f"cluster: {len(nodes)} node(s)")
    for n in nodes:
        state = "ALIVE" if n["Alive"] else "DEAD"
        print(f"  {n['NodeID'][:12]} {state} {n['host']}:{n['port']} {n['Resources']}")
    print("resources:")
    for k in sorted(total):
        print(f"  {k}: {avail.get(k, 0):g}/{total[k]:g} available")
    return 0


def cmd_list(args) -> int:
    _connect(args.address)
    from ray_tpu.util import state

    fetch = {
        "nodes": state.list_nodes,
        "actors": state.list_actors,
        "tasks": state.list_tasks,
        "objects": state.list_objects,
        "pgs": state.list_placement_groups,
    }[args.what]
    rows = fetch()
    print(json.dumps(rows, indent=1, default=repr))
    print(f"({len(rows)} {args.what})", file=sys.stderr)
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="ray_tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("start", help="start a head or worker-node daemon")
    sp.add_argument("--head", action="store_true")
    sp.add_argument("--address", help="head address for worker nodes")
    sp.add_argument("--num-cpus", type=float, default=None)
    sp.add_argument("--resources", help="JSON resource dict")
    sp.add_argument("--session-dir")
    sp.set_defaults(fn=cmd_start)

    sp = sub.add_parser("stop", help="stop daemons started by this CLI")
    sp.set_defaults(fn=cmd_stop)

    sp = sub.add_parser("status", help="cluster nodes + resources")
    sp.add_argument("--address", required=True)
    sp.set_defaults(fn=cmd_status)

    sp = sub.add_parser("list", help="state API listings")
    sp.add_argument("what", choices=["nodes", "actors", "tasks", "objects", "pgs"])
    sp.add_argument("--address", required=True)
    sp.set_defaults(fn=cmd_list)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
