"""ray_tpu.serve — model serving: deployments, replicas, routing, HTTP.

Reference: ``python/ray/serve/`` — the controller/replica/router/proxy
architecture (``_private/controller.py:84``, ``replica.py``,
``pow_2_scheduler.py:52``, ``proxy.py``) on ray_tpu actors.

    import ray_tpu
    from ray_tpu import serve

    @serve.deployment(num_replicas=2)
    class Model:
        def __init__(self, scale):
            self.scale = scale
        def __call__(self, x):
            return self.scale * x

    handle = serve.run(Model.bind(3))
    assert ray_tpu.get(handle.remote(2), timeout=30) == 6

TPU-first: a deployment's ``ray_actor_options={"resources": {"TPU": n}}``
puts each replica on chips; ``max_concurrent_queries`` maps to actor
``max_concurrency`` so batched inference saturates a replica's chip."""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional

import ray_tpu
from ray_tpu.serve.batching import batch
from ray_tpu.serve.config import AutoscalingConfig, DeploymentConfig
from ray_tpu.serve.controller import (
    CONTROLLER_NAME,
    get_or_create_controller,
)
from ray_tpu.serve.ingress import (
    HttpIngress,
    IngressConfig,
    TenantPolicy,
    ingress_addresses,
    ingress_deployment,
    pick_ingress,
)
from ray_tpu.serve.multiplex import get_multiplexed_model_id, multiplexed
from ray_tpu.serve.proxy import start_http, stop_http
from ray_tpu.serve.router import Router


class Application:
    """A deployment bound to its init args (reference ``.bind()``)."""

    def __init__(self, deployment: "Deployment", args, kwargs):
        self.deployment = deployment
        self.args = args
        self.kwargs = kwargs


class DisaggApplication(Application):
    """A disaggregated two-pool application (decode deployment + its
    paired prefill deployment, ``llm_deployment(disaggregated=True)``).
    ``serve.run`` deploys ``prefill_app`` first, then this (decode)
    application, and returns the decode handle — the router discovers
    the pairing through the deployment's ``disagg_prefill`` meta, so
    any handle to the decode deployment (including one built later by
    an ingress replica) gets the two-stage dispatch."""

    def __init__(self, deployment: "Deployment", args, kwargs):
        super().__init__(deployment, args, kwargs)
        self.prefill_app: Optional[Application] = None


class Deployment:
    def __init__(self, cls_or_fn, name: str, config: DeploymentConfig):
        self._cls_or_fn = cls_or_fn
        self.name = name
        self.config = config

    def bind(self, *args, **kwargs) -> Application:
        return Application(self, args, kwargs)

    def options(self, **updates) -> "Deployment":
        import dataclasses

        cfg_fields = {f.name for f in dataclasses.fields(DeploymentConfig)}
        cfg = dataclasses.replace(
            self.config, **{k: v for k, v in updates.items() if k in cfg_fields}
        )
        name = updates.get("name", self.name)
        return Deployment(self._cls_or_fn, name, cfg)


def deployment(
    _cls=None,
    *,
    name: Optional[str] = None,
    num_replicas: int = 1,
    max_concurrent_queries: int = 8,
    ray_actor_options: Optional[Dict[str, Any]] = None,
    autoscaling_config: Optional[AutoscalingConfig] = None,
    route_prefix: Optional[str] = None,
    version: Optional[str] = None,
):
    """Class/function decorator → Deployment (reference ``@serve.deployment``)."""

    def wrap(cls_or_fn):
        cfg = DeploymentConfig(
            num_replicas=num_replicas,
            max_concurrent_queries=max_concurrent_queries,
            ray_actor_options=dict(ray_actor_options or {}),
            autoscaling=autoscaling_config,
            route_prefix=route_prefix,
            version=version,
        )
        return Deployment(cls_or_fn, name or cls_or_fn.__name__, cfg)

    if _cls is not None:
        return wrap(_cls)
    return wrap


class DeploymentHandle:
    """Client-side handle: pow-2 routed calls returning ObjectRefs
    (reference ``DeploymentHandle``/``Router``).

    ``remote()`` is at-most-once and returns an ObjectRef;
    ``call()`` is retry-until-executed (reference router semantics);
    ``stream()`` iterates a streaming (generator) deployment's values;
    ``options(multiplexed_model_id=...)`` routes model-local
    (reference ``handle.options``)."""

    def __init__(self, deployment_name: str, controller=None, *, _shared_router=None, _model_id: str = ""):
        self._name = deployment_name
        self._controller = controller or get_or_create_controller()
        self._router = _shared_router or Router(self._controller, deployment_name)
        self._model_id = _model_id

    def options(self, *, multiplexed_model_id: str = "") -> "DeploymentHandle":
        # shares the router (and its long-poll thread + stats cache)
        return DeploymentHandle(
            self._name,
            self._controller,
            _shared_router=self._router,
            _model_id=multiplexed_model_id or self._model_id,
        )

    def remote(self, *args, **kwargs):
        return self._router.dispatch("__call__", args, kwargs, self._model_id)

    def call(self, *args, _timeout: Optional[float] = 60.0, _idempotent: bool = True, **kwargs):
        """Blocking retry-until-executed call (survives replica death
        mid-rolling-update). Exactly-once-effective while the replica is
        reachable (request-id dedup at the RPC layer absorbs lost
        replies and connection resets); AT-LEAST-ONCE across replica
        DEATH by default — see ``Router.execute`` for the full contract.
        Pass ``_idempotent=False`` for non-idempotent requests so a
        post-dispatch replica death propagates instead of re-executing
        on a survivor."""
        return self._router.execute(
            "__call__", args, kwargs, model_id=self._model_id,
            timeout=_timeout, idempotent=_idempotent,
        )

    def stream(self, *args, _method: str = "__call__", _timeout: Optional[float] = 60.0, **kwargs):
        """Iterate a generator deployment's yielded values (token
        streaming; reference streaming DeploymentResponseGenerator)."""
        return self._router.execute_stream(
            _method, args, kwargs, model_id=self._model_id, timeout=_timeout
        )

    def method(self, method_name: str):
        def call(*args, **kwargs):
            return self._router.dispatch(method_name, args, kwargs, self._model_id)

        return call

    def __reduce__(self):
        # Carry the controller's ACTOR HANDLE, not just the name: a
        # handle deserialized inside a worker (namespace "") cannot find
        # the named controller registered under the driver's namespace —
        # name-only reconstruction silently created a SECOND, empty
        # serve controller and every call failed with "no replicas".
        # The router (and its long-poll thread) is rebuilt lazily; the
        # multiplexed model id survives via the state dict.
        return (
            DeploymentHandle,
            (self._name, self._controller),
            {"_model_id": self._model_id},
        )


def run(app: Application, *, name: Optional[str] = None, _blocking_ready: bool = True) -> DeploymentHandle:
    """Deploy an application; returns its handle (reference ``serve.run``).
    A :class:`DisaggApplication` deploys its prefill pool first, then
    the decode pool, and returns the decode handle."""
    if isinstance(app, Deployment):
        app = app.bind()
    controller = get_or_create_controller()
    prefill = getattr(app, "prefill_app", None)
    if prefill is not None:
        pdep = prefill.deployment
        ray_tpu.get(
            controller.deploy.remote(
                pdep.name, pdep._cls_or_fn, list(prefill.args),
                dict(prefill.kwargs), pdep.config,
            ),
            timeout=120,
        )
    dep = app.deployment
    ray_tpu.get(
        controller.deploy.remote(
            dep.name, dep._cls_or_fn, list(app.args), dict(app.kwargs), dep.config
        ),
        timeout=120,
    )
    handle = DeploymentHandle(dep.name, controller)
    if _blocking_ready:
        if prefill is not None:
            # the prefill pool must be routable too, or the first
            # requests burn their whole handoff budget waiting on a
            # replica that is still warming up
            DeploymentHandle(pdep.name, controller)._router.choose_replica()
        handle._router.choose_replica()  # wait for ≥1 replica
    return handle


def get_deployment_handle(name: str) -> DeploymentHandle:
    return DeploymentHandle(name)


def delete(name: str) -> None:
    controller = get_or_create_controller()
    # disaggregated deployments pair with a prefill pool serve.run
    # deployed alongside them — deleting only the decode pool would
    # orphan full engine replicas until serve.shutdown()
    try:
        meta = ray_tpu.get(controller.deployment_meta.remote(name), timeout=30)
        prefill = (meta or {}).get("disagg_prefill")
    except Exception:
        prefill = None
    ray_tpu.get(controller.delete_deployment.remote(name), timeout=60)
    if prefill:
        ray_tpu.get(controller.delete_deployment.remote(prefill), timeout=60)


def status() -> Dict[str, Dict[str, Any]]:
    controller = get_or_create_controller()
    return ray_tpu.get(controller.status.remote(), timeout=30)


def slo_report(*, flight_limit: int = 100, timeout: float = 60.0) -> Dict[str, Any]:
    """Cluster-wide SLO report (observability/slo.py): one call answers
    "what were TTFT/ITL/e2e p50/p99/p99.9 per deployment (and tenant
    class), how much of the token work was goodput vs fault cost, do the
    intake books balance, and which stage made the slow requests slow".

    The serve controller fans out to every replica for its ledger
    snapshot (aggregatable log-bucket histogram counts + flight-recorder
    ring + books); THIS process's own snapshot merges in too — the
    driver-side router is a tier of the serving path (its ledger holds
    the failover stage of resumed streams consumed here).

    Report shape: ``{"deployments": {name: {"ttft_s"/"itl_s"/"e2e_s":
    {p50, p99, p999, count}, "by_class": {...}, "goodput_tokens",
    "fault_tokens": {reason: n}, "goodput_fraction", "deadline_expired",
    "books": [...], "books_balanced", "restarts", "shed_total"}},
    "flight_recorder": [joined per-request records, slowest first, each
    with a per-tier stage breakdown, flags, resume counts, and the
    trace id when sampled], "counters": raw merged counter values}``.

    Degrades instead of erroring: with no serve controller (idle
    cluster, or serve never used — we look the actor up rather than
    CREATE one just to ask it for nothing), or with the fan-out timing
    out mid-restart, the report is built from the driver-local snapshot
    alone — well-formed and empty, under the caller's deadline."""
    from ray_tpu.observability import slo as _slo

    collected: Dict[str, Any] = {}
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
    except Exception:  # noqa: BLE001 — no controller / no cluster
        controller = None
    if controller is not None:
        try:
            # the controller-side fan-out budget rides INSIDE the
            # driver-side get timeout, so a wedged replica sweep
            # returns the survivors' snapshots instead of timing the
            # whole call out
            collected = ray_tpu.get(
                controller.slo_snapshots.remote(
                    max(1.0, float(timeout) * 0.8)
                ),
                timeout=timeout,
            ) or {}
        except Exception:  # noqa: BLE001 — controller dead/slow: degrade
            collected = {}
    snapshots = list(collected.get("snapshots") or ())
    local = _slo.snapshot()
    local["tier"] = "driver"
    snapshots.append(local)
    return _slo.build_report(
        snapshots, collected.get("status"), flight_limit=flight_limit
    )


def shutdown() -> None:
    stop_http()
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
    except Exception:
        return
    try:
        ray_tpu.get(controller.shutdown.remote(), timeout=60)
        ray_tpu.kill(controller)
    except Exception:
        pass


def __getattr__(name: str):
    # lazy: the LLM deployment pulls in jax via the inference engine —
    # plain serve users (and control-plane processes) must not pay that
    if name == "llm_deployment":
        from ray_tpu.inference.serve_llm import llm_deployment

        return llm_deployment
    if name == "LLMServer":
        from ray_tpu.inference.serve_llm import LLMServer

        return LLMServer
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "Application",
    "AutoscalingConfig",
    "batch",
    "Deployment",
    "DeploymentConfig",
    "DeploymentHandle",
    "DisaggApplication",
    "delete",
    "deployment",
    "get_deployment_handle",
    "get_multiplexed_model_id",
    "HttpIngress",
    "IngressConfig",
    "TenantPolicy",
    "ingress_addresses",
    "ingress_deployment",
    "pick_ingress",
    # llm_deployment/LLMServer stay OUT of __all__: star-imports resolve
    # every listed name, which would trigger the lazy __getattr__ above
    # and drag jax into plain serve users. Reach them by attribute.
    "multiplexed",
    "run",
    "shutdown",
    "slo_report",
    "start_http",
    "status",
    "stop_http",
]
