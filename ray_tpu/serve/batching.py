"""Dynamic request batching — the highest-leverage TPU serving feature.

Reference: ``python/ray/serve/batching.py`` (``@serve.batch``) — N
concurrent single requests coalesce into ONE call of the wrapped method
with a list argument, so a replica's chip sees large batches (MXU
utilization) instead of singletons. The method must be async, take a
list, and return a list of equal length::

    @serve.deployment
    class Model:
        @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.01)
        async def handle(self, inputs: List[np.ndarray]) -> List[np.ndarray]:
            return list(model(np.stack(inputs)))

        async def __call__(self, x):
            return await self.handle(x)

Each caller awaits its own element; the flusher waits up to
``batch_wait_timeout_s`` for the batch to fill after the first arrival.
"""

from __future__ import annotations

import asyncio
import functools
import inspect
from typing import Any, List, Optional


class _BatchState:
    __slots__ = ("queue", "task")

    def __init__(self):
        self.queue: "asyncio.Queue" = asyncio.Queue()
        self.task: Optional[asyncio.Task] = None


class _BatchedMethod:
    """Descriptor: per-instance batching state, shared flusher task."""

    def __init__(self, fn, max_batch_size: int, batch_wait_timeout_s: float):
        if not inspect.iscoroutinefunction(fn):
            raise TypeError("@serve.batch requires an async def method")
        self._fn = fn
        self._max = max(1, max_batch_size)
        self._wait = batch_wait_timeout_s
        self._attr = f"__serve_batch_{fn.__name__}"
        functools.update_wrapper(self, fn)

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self

        async def call(item):
            state: _BatchState = obj.__dict__.get(self._attr)
            if state is None:
                state = _BatchState()
                obj.__dict__[self._attr] = state
            if state.task is None or state.task.done():
                state.task = asyncio.ensure_future(self._flush_loop(obj, state))
            fut = asyncio.get_event_loop().create_future()
            state.queue.put_nowait((item, fut))
            return await fut

        call.__name__ = self._fn.__name__
        return call

    async def _flush_loop(self, obj, state: _BatchState) -> None:
        loop = asyncio.get_event_loop()
        while True:
            item, fut = await state.queue.get()
            batch = [(item, fut)]
            deadline = loop.time() + self._wait
            while len(batch) < self._max:
                remaining = deadline - loop.time()
                if remaining <= 0:
                    break
                try:
                    batch.append(
                        await asyncio.wait_for(state.queue.get(), remaining)
                    )
                except (asyncio.TimeoutError, TimeoutError):
                    break
            items = [b[0] for b in batch]
            try:
                results = await self._fn(obj, items)
                if results is None or len(results) != len(items):
                    raise ValueError(
                        f"@serve.batch method {self._fn.__name__} returned "
                        f"{0 if results is None else len(results)} results "
                        f"for a batch of {len(items)}"
                    )
                for (_, f), r in zip(batch, results):
                    if not f.done():
                        f.set_result(r)
            except Exception as e:  # noqa: BLE001 — fan the error out
                for _, f in batch:
                    if not f.done():
                        f.set_exception(e)


def batch(
    _fn=None, *, max_batch_size: int = 10, batch_wait_timeout_s: float = 0.01
):
    """``@serve.batch`` decorator (reference ``serve/batching.py``)."""

    def wrap(fn):
        return _BatchedMethod(fn, max_batch_size, batch_wait_timeout_s)

    if _fn is not None:
        return wrap(_fn)
    return wrap
