"""Serve configuration dataclasses.

Reference: ``serve/config.py`` + ``serve/schema.py`` (DeploymentConfig,
autoscaling config). TPU note: replicas may reserve ``{"TPU": n}`` so a
deployment maps onto chips exactly like any other actor."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class AutoscalingConfig:
    """Queue-length autoscaling (reference ``autoscaling_state.py:262``,
    ``serve/autoscaling_policy.py:100``): scale toward
    total_ongoing / target_ongoing_requests replicas.

    SLO autopilot mode: when ``target_ttft_p99_s`` is set the controller
    scales on TTFT-p99 BUDGET BURN (worst fresh replica's windowed p99
    divided by the target) instead of raw queue depth — burn at or above
    ``ttft_burn_high`` forces a scale-out, burn at or below
    ``ttft_burn_low`` releases capacity down to the queue-derived floor,
    and the band between them HOLDS the current target so a chaos blip
    (one replica kill inflating p99 for a window) doesn't thrash
    replicas. See ``serve/controller.py::autoscale_decision``."""

    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 0.5
    downscale_delay_s: float = 2.0
    #: TTFT p99 budget (seconds); None = legacy queue-depth autoscaling
    target_ttft_p99_s: Optional[float] = None
    #: burn ratio (measured p99 / target) at/above which to scale OUT
    ttft_burn_high: float = 1.0
    #: burn ratio at/below which scale-IN is allowed; the gap between
    #: low and high is the hysteresis dead band (hold the target)
    ttft_burn_low: float = 0.5


@dataclass
class DeploymentConfig:
    num_replicas: int = 1
    max_concurrent_queries: int = 8
    ray_actor_options: Dict[str, Any] = field(default_factory=dict)
    autoscaling: Optional[AutoscalingConfig] = None
    route_prefix: Optional[str] = None
    #: rolling updates key on this (reference deployment_state.py:2331):
    #: redeploying the SAME version is an in-place config update;
    #: a different (or absent) version rolls replicas start-before-kill
    version: Optional[str] = None
    #: disaggregated prefill/decode serving: name of the PREFILL-pool
    #: deployment paired with this (decode) deployment. Routers read it
    #: through ``deployment_meta`` and run the two-stage dispatch —
    #: prefill_export on the prefill pool, then the stream on this pool
    #: with the KV descriptor attached (inference/serve_llm.py).
    disagg_prefill: Optional[str] = None
