"""Serve configuration dataclasses.

Reference: ``serve/config.py`` + ``serve/schema.py`` (DeploymentConfig,
autoscaling config). TPU note: replicas may reserve ``{"TPU": n}`` so a
deployment maps onto chips exactly like any other actor."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class AutoscalingConfig:
    """Queue-length autoscaling (reference ``autoscaling_state.py:262``,
    ``serve/autoscaling_policy.py:100``): scale toward
    total_ongoing / target_ongoing_requests replicas."""

    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 0.5
    downscale_delay_s: float = 2.0


@dataclass
class DeploymentConfig:
    num_replicas: int = 1
    max_concurrent_queries: int = 8
    ray_actor_options: Dict[str, Any] = field(default_factory=dict)
    autoscaling: Optional[AutoscalingConfig] = None
    route_prefix: Optional[str] = None
    #: rolling updates key on this (reference deployment_state.py:2331):
    #: redeploying the SAME version is an in-place config update;
    #: a different (or absent) version rolls replicas start-before-kill
    version: Optional[str] = None
    #: disaggregated prefill/decode serving: name of the PREFILL-pool
    #: deployment paired with this (decode) deployment. Routers read it
    #: through ``deployment_meta`` and run the two-stage dispatch —
    #: prefill_export on the prefill pool, then the stream on this pool
    #: with the KV descriptor attached (inference/serve_llm.py).
    disagg_prefill: Optional[str] = None
