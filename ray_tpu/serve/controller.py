"""ServeController: reconciles deployments to their target state.

Reference: ``serve/_private/controller.py:84`` (deploy_application
``:719``), ``deployment_state.py:2331`` (replica FSM reconcile) and
``autoscaling_state.py:262`` (queue-length autoscaling). One named
controller actor owns the replica sets; handles/proxies query it for
routing tables and it runs a control loop: start missing replicas,
reap dead ones, and scale on the replicas' reported ongoing-request
counts."""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.serve.config import AutoscalingConfig, DeploymentConfig
from ray_tpu.serve.replica import Replica

CONTROLLER_NAME = "__serve_controller__"


class _DeploymentState:
    def __init__(self, name, cls_or_fn, init_args, init_kwargs, config: DeploymentConfig):
        self.name = name
        self.cls_or_fn = cls_or_fn
        self.init_args = init_args
        self.init_kwargs = init_kwargs
        self.config = config
        self.target = (
            config.autoscaling.min_replicas if config.autoscaling else config.num_replicas
        )
        self.replicas: List[Any] = []
        self.last_scale_ts = 0.0
        self.ongoing_history: List[float] = []


class _ServeController:
    """Runs inside an actor; a background thread reconciles."""

    def __init__(self):
        self._deployments: Dict[str, _DeploymentState] = {}
        self._lock = threading.Lock()
        # serializes whole reconcile passes: deploy() (RPC thread) and the
        # control loop both reconcile, and unsynchronized passes would
        # double-start replicas then drop one set from tracking (leak)
        self._reconcile_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._control_loop, daemon=True, name="serve-control"
        )
        self._thread.start()

    # -- API -------------------------------------------------------------
    def deploy(self, name, cls_or_fn, init_args, init_kwargs, config: DeploymentConfig) -> bool:
        with self._lock:
            old = self._deployments.get(name)
            state = _DeploymentState(name, cls_or_fn, init_args, init_kwargs, config)
            self._deployments[name] = state
            if old is not None:
                # rolling-update-lite: drop old replicas; reconcile starts new
                for r in old.replicas:
                    try:
                        ray_tpu.kill(r)
                    except Exception:
                        pass
        self._reconcile_once()
        return True

    def delete_deployment(self, name: str) -> bool:
        with self._lock:
            state = self._deployments.pop(name, None)
        if state is None:
            return False
        for r in state.replicas:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
        return True

    def get_replicas(self, name: str) -> List[Any]:
        with self._lock:
            state = self._deployments.get(name)
            return list(state.replicas) if state else []

    def routes(self) -> Dict[str, str]:
        """route_prefix -> deployment name (proxy routing table)."""
        with self._lock:
            out = {}
            for name, st in self._deployments.items():
                prefix = st.config.route_prefix or f"/{name}"
                out[prefix] = name
            return out

    def status(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {
                name: {
                    "target": st.target,
                    "replicas": len(st.replicas),
                    "autoscaling": st.config.autoscaling is not None,
                }
                for name, st in self._deployments.items()
            }

    def ping(self) -> bool:
        return True

    def shutdown(self) -> bool:
        self._stop.set()
        with self._lock:
            deployments = list(self._deployments.values())
            self._deployments.clear()
        for st in deployments:
            for r in st.replicas:
                try:
                    ray_tpu.kill(r)
                except Exception:
                    pass
        return True

    # -- control loop ----------------------------------------------------
    def _control_loop(self) -> None:
        while not self._stop.wait(0.25):
            try:
                self._reconcile_once()
                self._autoscale_once()
            except Exception:  # noqa: BLE001 — keep the loop alive
                import logging

                logging.getLogger(__name__).exception("serve control loop error")

    def _reconcile_once(self) -> None:
        with self._reconcile_lock:
            with self._lock:
                states = list(self._deployments.values())
            for st in states:
                # reap dead replicas. A stats TIMEOUT is overload, not
                # death — keep the replica (dropping it would churn
                # healthy-but-slow replicas); real death (actor error /
                # connection loss) drops it, with a defensive kill so a
                # half-dead replica can't leak its reservation.
                alive = []
                for r in st.replicas:
                    try:
                        ray_tpu.get(r.stats.remote(), timeout=5)
                        alive.append(r)
                    except ray_tpu.GetTimeoutError:
                        alive.append(r)  # slow ≠ dead
                    except Exception:
                        try:
                            ray_tpu.kill(r)
                        except Exception:
                            pass
                st.replicas = alive
                started: List[Any] = []
                while len(st.replicas) + len(started) < st.target:
                    opts = dict(st.config.ray_actor_options)
                    opts.setdefault(
                        "max_concurrency", st.config.max_concurrent_queries
                    )
                    started.append(
                        Replica.options(**opts).remote(
                            st.cls_or_fn, st.init_args, st.init_kwargs
                        )
                    )
                with self._lock:
                    if self._deployments.get(st.name) is st:
                        st.replicas.extend(started)
                        started = []
                # state swapped mid-reconcile (redeploy/delete): kill the
                # replicas we just started for the stale state
                for r in started:
                    try:
                        ray_tpu.kill(r)
                    except Exception:
                        pass
                while len(st.replicas) > st.target:
                    victim = st.replicas.pop()
                    try:
                        ray_tpu.kill(victim)
                    except Exception:
                        pass

    def _autoscale_once(self) -> None:
        now = time.monotonic()
        with self._lock:
            states = [s for s in self._deployments.values() if s.config.autoscaling]
        for st in states:
            cfg: AutoscalingConfig = st.config.autoscaling
            total = 0.0
            n = 0
            for r in st.replicas:
                try:
                    total += ray_tpu.get(r.stats.remote(), timeout=5)["ongoing"]
                    n += 1
                except Exception:
                    pass
            if n == 0:
                continue
            desired = max(
                cfg.min_replicas,
                min(cfg.max_replicas, round(total / cfg.target_ongoing_requests)),
            )
            delay = (
                cfg.upscale_delay_s if desired > st.target else cfg.downscale_delay_s
            )
            if desired != st.target and now - st.last_scale_ts >= delay:
                st.target = desired
                st.last_scale_ts = now


ServeController = ray_tpu.remote(_ServeController)


def get_or_create_controller():
    # get_if_exists handles the named-actor creation race internally
    # (actor.py) and real creation failures surface as themselves.
    return ServeController.options(
        name=CONTROLLER_NAME, num_cpus=0, max_concurrency=16, get_if_exists=True
    ).remote()
