"""ServeController: reconciles deployments to their target state.

Reference: ``serve/_private/controller.py:84`` (deploy_application
``:719``), ``deployment_state.py:2331`` (replica FSM + ROLLING updates
keyed on deployment version) and ``autoscaling_state.py:262``
(queue-length autoscaling). One named controller actor owns the replica
sets and runs a control loop: start missing replicas, promote them once
READY, reap dead ones, roll old-version replicas out start-before-kill,
and scale on the replicas' reported ongoing-request counts. Routing
tables are PUSHED to routers via long-poll (``long_poll.py`` in the
reference): ``poll_replicas`` parks until the replica set version
changes."""

from __future__ import annotations

import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

import ray_tpu
from ray_tpu.core.config import GLOBAL_CONFIG
from ray_tpu.serve.config import AutoscalingConfig, DeploymentConfig
from ray_tpu.serve.replica import Replica

CONTROLLER_NAME = "__serve_controller__"


def autoscale_decision(
    *,
    target: int,
    cfg: AutoscalingConfig,
    total_load: float,
    ttft_p99_s: float = 0.0,
) -> Tuple[int, str]:
    """Pure replica-count decision (cluster-free testable): what the
    deployment's target should be, and why.

    Legacy mode (no ``target_ttft_p99_s``, or no TTFT signal gossiped
    yet): scale toward ``total_load / target_ongoing_requests`` —
    unchanged queue-depth behavior.

    SLO autopilot mode: burn = measured windowed TTFT-p99 / budget.
      * burn >= ttft_burn_high — the budget is gone: scale OUT (at
        least one step; straight to the queue-derived count when a
        burst demands more).
      * burn <= ttft_burn_low AND the queue signal agrees we're
        over-provisioned: release ONE replica (conservative scale-in).
      * in between — the hysteresis dead band: HOLD, so a chaos blip
        (a replica kill inflating p99 for one window) doesn't thrash.
    """
    queue_desired = max(
        cfg.min_replicas,
        min(cfg.max_replicas, round(total_load / cfg.target_ongoing_requests)),
    )
    budget = cfg.target_ttft_p99_s
    if not budget or ttft_p99_s <= 0.0:
        return queue_desired, "queue_depth"
    burn = ttft_p99_s / float(budget)
    if burn >= cfg.ttft_burn_high:
        return min(cfg.max_replicas, max(target + 1, queue_desired)), "ttft_burn"
    if burn <= cfg.ttft_burn_low and queue_desired < target:
        return max(cfg.min_replicas, target - 1), "ttft_relax"
    return target, "hold"


def pool_ratio_decision(
    *,
    prefill_target: int,
    n_decode: int,
    prefill_tokens_per_s: float,
    decode_tokens_per_s: float,
    min_replicas: int,
    max_replicas: int,
) -> Tuple[int, str]:
    """Pure disagg prefill-pool sizing decision: with homogeneous
    replicas, the prefill:decode split should track the observed
    prefill:decode TOKEN mix (desired_prefill ≈ n_decode * P/D, both
    rates from engine gossip). No signal on either side (idle pool,
    gossip not landed) holds the current target — never resize blind."""
    if prefill_tokens_per_s <= 0.0 or decode_tokens_per_s <= 0.0 or n_decode <= 0:
        return prefill_target, "no_signal"
    desired = int(round(n_decode * prefill_tokens_per_s / decode_tokens_per_s))
    desired = max(min_replicas, min(max_replicas, max(1, desired)))
    return desired, "token_mix"


def _count_autoscale_decision(deployment: str, reason: str) -> None:
    try:
        from ray_tpu.observability.rpc_metrics import SERVE_AUTOSCALE_DECISIONS

        SERVE_AUTOSCALE_DECISIONS.inc(
            labels={"deployment": deployment, "reason": reason}
        )
    except Exception:
        pass


def _count_replica_restart(state: "_DeploymentState", reason: str) -> None:
    """A ready replica was killed for replacement: observed death or an
    unhealthy self-report. Counted on the controller's /metrics registry
    AND on the deployment state (surfaced via status())."""
    state.restarts[reason] = state.restarts.get(reason, 0) + 1
    try:
        from ray_tpu.observability.rpc_metrics import SERVE_REPLICA_RESTARTS

        SERVE_REPLICA_RESTARTS.inc(labels={"reason": reason})
    except Exception:
        pass


class _DeploymentState:
    def __init__(self, name, cls_or_fn, init_args, init_kwargs, config: DeploymentConfig):
        self.name = name
        self.cls_or_fn = cls_or_fn
        self.init_args = init_args
        self.init_kwargs = init_kwargs
        self.config = config
        # every deploy without an explicit version is a new code version
        # (the reference hashes config+code; we can't diff code, so a
        # fresh uuid forces the same rolling replacement)
        self.version: str = config.version or uuid.uuid4().hex[:8]
        self.target = (
            config.autoscaling.min_replicas if config.autoscaling else config.num_replicas
        )
        #: READY replicas: (version, handle) — the routing set
        self.replicas: List[Tuple[str, Any]] = []
        #: started but not yet proven ready: (version, handle, started_at)
        self.starting: List[Tuple[str, Any, float]] = []
        #: unrouted, waiting for in-flight requests to finish before the
        #: kill (graceful drain — zero-downtime rolls/scale-downs)
        self.draining: List[Tuple[str, Any, float]] = []
        self.last_scale_ts = 0.0
        self.last_stuck_evict_ts = 0.0
        #: last time a starter died as runtime-unplaceable
        self.unplaceable_ts = 0.0
        #: replica uid -> multiplexed model ids loaded there (pushed by
        #: replicas; propagated to routers through the long-poll)
        self.replica_models: Dict[str, List[str]] = {}
        #: replica uid -> (routing stats dict, receipt monotonic) —
        #: load + prefix-digest gossip from gossip-capable replicas
        #: (serve/replica.py), shipped to routers with the routing set
        self.replica_stats: Dict[str, Tuple[Dict[str, Any], float]] = {}
        #: last replica.health() poll sweep (proactive wedged-replica
        #: restart rides its own cadence, not every reconcile pass)
        self.last_health_ts = 0.0
        #: ready replicas killed for replacement, by reason — mirrored
        #: into status() so tests/operators see it without scraping the
        #: controller process's /metrics
        self.restarts: Dict[str, int] = {"death": 0, "unhealthy": 0}
        #: last APPLIED autoscale decision ({"ts", "from", "to",
        #: "reason"}) — surfaced via status() so the load harness can
        #: measure autoscaler lag (burst start -> first target change)
        #: without scraping metrics
        self.last_scale_info: Dict[str, Any] = {}


class _ServeController:
    """Runs inside an actor; a background thread reconciles."""

    def __init__(self, registered_namespace=None):
        # the namespace this controller's NAME lives in (the creating
        # driver's) — the controller process's own namespace differs, and
        # replicas need the registered one to get_actor() us for reports
        self._registered_namespace = registered_namespace
        self._deployments: Dict[str, _DeploymentState] = {}
        self._lock = threading.Lock()
        # Preemption-aware drain handoff: node ids currently DRAINING
        # (controller-pushed). Replicas there are unrouted (moved to the
        # draining list) so routers drop them, in-flight requests finish,
        # and replacements start — all before the kill lands.
        self._draining_nodes: set = set()
        #: ingress-door key -> {tenant: bucket state}: the timer-pushed
        #: token-bucket persistence table (survives ingress replica
        #: restarts; this controller outlives its replicas)
        self._ingress_buckets: Dict[str, Dict[str, Dict[str, float]]] = {}
        #: replica actor_id -> node_id cache (stable: replicas don't move)
        self._replica_nodes: Dict[bytes, bytes] = {}
        try:
            from ray_tpu.core.api import _global_worker

            self._node_listener_backend = _global_worker().backend
            self._node_listener_backend.add_node_event_listener(self._on_node_event)
        except Exception:
            self._node_listener_backend = None  # local mode: no node events
        # serializes whole reconcile passes: deploy() (RPC thread) and the
        # control loop both reconcile, and unsynchronized passes would
        # double-start replicas then drop one set from tracking (leak)
        self._reconcile_lock = threading.Lock()
        # long-poll state: bumped whenever any routing set changes
        self._versions: Dict[str, int] = {}
        self._change = threading.Condition()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._control_loop, daemon=True, name="serve-control"
        )
        self._thread.start()

    def _bump(self, name: str) -> None:
        with self._change:
            self._versions[name] = self._versions.get(name, 0) + 1
            self._change.notify_all()

    def _on_node_event(self, msg) -> None:
        """Controller node-state push (io-loop thread: keep non-blocking).
        DRAINING enters the set; DEAD/removed leaves it."""
        node_id = msg.get("node_id")
        if node_id is None:
            return
        if msg.get("state") == "DRAINING":
            self._draining_nodes.add(node_id)
        elif not msg.get("alive", True):
            self._draining_nodes.discard(node_id)

    # -- API -------------------------------------------------------------
    def deploy(self, name, cls_or_fn, init_args, init_kwargs, config: DeploymentConfig) -> bool:
        with self._lock:
            old = self._deployments.get(name)
            state = _DeploymentState(name, cls_or_fn, init_args, init_kwargs, config)
            if old is not None:
                if config.version is not None and config.version == old.version:
                    # same code version: in-place config update (scale);
                    # existing replicas keep serving untouched — and an
                    # autoscaled target must survive the redeploy, or the
                    # pass after a config tweak drains replicas under load
                    state.version = old.version
                    if config.autoscaling and old.config.autoscaling:
                        state.target = old.target
                        state.last_scale_ts = old.last_scale_ts
                state.replicas = old.replicas
                state.starting = old.starting
                state.draining = old.draining
                state.restarts = old.restarts
            self._deployments[name] = state
        self._reconcile_once()
        return True

    def delete_deployment(self, name: str) -> bool:
        with self._lock:
            state = self._deployments.pop(name, None)
        if state is None:
            return False
        all_handles = (
            state.replicas
            + [(v, h) for v, h, _t in state.starting]
            + [(v, h) for v, h, _t in state.draining]
        )
        for _v, r in all_handles:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
        self._bump(name)
        return True

    def get_replicas(self, name: str) -> List[Any]:
        with self._lock:
            state = self._deployments.get(name)
            return [r for _v, r in state.replicas] if state else []

    def _routing_set(self, name: str):
        """(handle, loaded_model_ids, stats_entry) triples — what
        routers consume. ``stats_entry`` is None for replicas that never
        gossiped (plain deployments), else ``{"stats": ..., "age_s": ...}``
        with the age measured on THIS controller's clock at poll time
        (routers age it locally from receipt — monotonic clocks don't
        compare across processes)."""
        now = time.monotonic()
        with self._lock:
            state = self._deployments.get(name)
            if state is None:
                return []
            out = []
            for _v, r in state.replicas:
                uid = r.actor_id.hex()
                ent = state.replica_stats.get(uid)
                stats_entry = (
                    {
                        "stats": ent[0],
                        "age_s": max(0.0, now - ent[1]),
                        # opaque identity of THIS report (controller
                        # receipt time): routers must reset their
                        # optimistic load bumps only when a genuinely
                        # NEW report arrives — re-deriving freshness
                        # from now-age_s wobbles with delivery latency
                        # and would wipe bumps on every relay
                        "stamp": ent[1],
                    }
                    if ent is not None
                    else None
                )
                out.append((r, state.replica_models.get(uid, []), stats_entry))
            return out

    @staticmethod
    def _live_uids(state: _DeploymentState) -> set:
        """Actor uids the deployment still tracks in ANY lifecycle list
        — the pruning horizon for replica-pushed side tables (models,
        routing stats). One definition, used by every prune site, so a
        future lifecycle list can't silently leak one of the dicts."""
        return {
            r.actor_id.hex()
            for group in (
                state.replicas,
                [(v, h) for v, h, _t in state.starting],
                [(v, h) for v, h, _t in state.draining],
            )
            for _v, r in group
        }

    def report_models(self, name: str, replica_uid: str, models: List[str]) -> bool:
        """Replica-pushed multiplexed-model set (reference: model ids
        flow replica -> controller -> routers via long-poll broadcast,
        ``multiplex.py`` + ``long_poll.py``)."""
        with self._lock:
            state = self._deployments.get(name)
            if state is None:
                return False
            state.replica_models[replica_uid] = list(models)
            # prune entries for replicas no longer tracked — without this
            # the dict grows one entry per replica generation forever
            live = self._live_uids(state)
            live.add(replica_uid)
            for uid in [u for u in state.replica_models if u not in live]:
                del state.replica_models[uid]
        self._bump(name)
        return True

    def report_replica_stats(self, name: str, replica_uid: str, stats: Dict[str, Any]) -> bool:
        """Replica-pushed routing gossip (load + prefix digest): stored
        with a receipt timestamp and broadcast to routers through the
        same long-poll channel as the routing set. Bounded: entries are
        pruned to live replicas, mirroring ``report_models``. Bump cost:
        one long-poll wake per report per parked router — the gossip
        cadence IS the `serve_replica_stats_period_s` knob (raise it to
        trade routing-signal freshness for controller fan-out)."""
        with self._lock:
            state = self._deployments.get(name)
            if state is None:
                return False
            state.replica_stats[replica_uid] = (dict(stats), time.monotonic())
            live = self._live_uids(state)
            live.add(replica_uid)
            for uid in [u for u in state.replica_stats if u not in live]:
                del state.replica_stats[uid]
        self._bump(name)
        return True

    @ray_tpu.method(concurrency_group="longpoll")
    def poll_replicas(self, name: str, known_version: int, timeout_s: float = 30.0):
        """Long-poll (reference ``LongPollClient``): returns
        ``(version, routing_set)`` as soon as the routing set differs
        from ``known_version`` (or on timeout, with the current state).
        The routing set pairs each replica handle with its loaded
        multiplexed-model ids."""
        deadline = time.monotonic() + timeout_s
        with self._change:
            while self._versions.get(name, 0) == known_version:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._stop.is_set():
                    break
                self._change.wait(min(remaining, 1.0))
            version = self._versions.get(name, 0)
        return version, self._routing_set(name)

    @ray_tpu.method(concurrency_group="longpoll")
    def wait_status(
        self,
        name: str,
        *,
        min_replicas: Optional[int] = None,
        max_replicas: Optional[int] = None,
        quiescent: bool = False,
        version: Optional[str] = None,
        timeout_s: float = 30.0,
    ):
        """Condition-based status wait (deflakes what used to be client
        sleep-polling): parks on the controller's change condition until
        the deployment's routed-replica count enters
        [min_replicas, max_replicas] (with ``quiescent``, nothing is
        starting or draining; with ``version``, every routed replica is
        on that version — a completed roll), or the timeout expires.
        Returns the final status dict either way — callers assert on it."""
        deadline = time.monotonic() + timeout_s

        def _ok(st: Dict[str, Any]) -> bool:
            if st is None:
                return False
            if min_replicas is not None and st["replicas"] < min_replicas:
                return False
            if max_replicas is not None and st["replicas"] > max_replicas:
                return False
            if quiescent and (st["starting"] or st["draining"]):
                return False
            if version is not None and (
                st["version"] != version
                or st["replicas_current_version"] != st["replicas"]
            ):
                return False
            return True

        while True:
            st = self.status().get(name)
            if _ok(st):
                return st
            remaining = deadline - time.monotonic()
            if remaining <= 0 or self._stop.is_set():
                return st
            with self._change:
                # woken by any routing-set change; the 0.25s cap also
                # re-samples target/autoscale changes that don't bump
                self._change.wait(min(remaining, 0.25))

    def resumable_stream_methods(self, name: str) -> List[str]:
        """Streaming methods the deployment's CALLABLE declares
        replay-safe (``resumable_streams`` class attribute) — read off
        the deployed class object, no replica round-trip. Routers fetch
        this once and upgrade ``execute_stream`` to exactly-once token
        delivery for these methods (serve/router.py tier 3)."""
        with self._lock:
            st = self._deployments.get(name)
            if st is None:
                return []
            return [
                str(m)
                for m in (getattr(st.cls_or_fn, "resumable_streams", ()) or ())
            ]

    def deployment_meta(self, name: str) -> Dict[str, Any]:
        """Code/config properties a router needs once per deployment
        (cached router-side with a TTL): the resumable-streams
        declaration plus the paired prefill-pool name for disaggregated
        serving. One RPC instead of one per property."""
        with self._lock:
            st = self._deployments.get(name)
            if st is None:
                return {"resumable_streams": [], "disagg_prefill": None}
            return {
                "resumable_streams": [
                    str(m)
                    for m in (
                        getattr(st.cls_or_fn, "resumable_streams", ()) or ()
                    )
                ],
                "disagg_prefill": st.config.disagg_prefill,
            }

    # -- ingress bucket persistence (serve/ingress.py satellite) ---------
    #: per-door cap on remembered tenants — newest-stamp entries win
    _MAX_BUCKET_TENANTS = 4096

    def save_ingress_buckets(
        self, key: str, buckets: Dict[str, Dict[str, float]]
    ) -> bool:
        """Timer-pushed per-tenant token-bucket fill levels from an
        ingress replica (``{"level": ..., "wall": time.time()}`` per
        tenant). Merged per tenant by NEWEST wall stamp — tenants
        rendezvous onto one door, so cross-replica conflicts are rare
        and recency is the right tiebreak. A replacement replica
        restores from here instead of refilling every tenant's burst."""
        with self._lock:
            table = self._ingress_buckets.setdefault(key, {})
            for tenant, state in buckets.items():
                cur = table.get(tenant)
                if cur is None or float(state.get("wall", 0.0)) >= float(
                    cur.get("wall", 0.0)
                ):
                    table[tenant] = dict(state)
            if len(table) > self._MAX_BUCKET_TENANTS:
                for victim in sorted(
                    table, key=lambda t: float(table[t].get("wall", 0.0))
                )[: len(table) - self._MAX_BUCKET_TENANTS]:
                    del table[victim]
        return True

    def load_ingress_buckets(self, key: str) -> Dict[str, Dict[str, float]]:
        """Snapshot for a (re)starting ingress replica."""
        with self._lock:
            return {
                t: dict(s) for t, s in self._ingress_buckets.get(key, {}).items()
            }

    # -- SLO ledger (observability/slo.py) -------------------------------
    def slo_snapshots(self, timeout_s: float = 10.0) -> Dict[str, Any]:
        """Cluster-wide SLO-ledger collection: every replica of every
        deployment is asked for its ``slo_snapshot`` (latency histogram
        bucket counts, goodput/fault counters, flight-recorder ring,
        intake books). Replicas whose callable has no ledger (plain
        deployments) and dead/slow replicas are skipped — the report is
        built from whoever answers, which is exactly the survivors'
        view an operator wants mid-incident. Returns raw snapshots plus
        ``status()``; ``serve.slo_report()`` merges and quantiles them
        driver-side (where the driver's own router ledger joins in)."""
        with self._lock:
            targets = [
                (name, r)
                for name, st in self._deployments.items()
                for _v, r in st.replicas
            ]
        pending = []
        for name, r in targets:
            try:
                pending.append(
                    (name, r.handle_request.remote("slo_snapshot", [], {}, ""))
                )
            except Exception:  # noqa: BLE001 — dead replica: skip
                pass
        snaps: List[Dict[str, Any]] = []
        # ONE shared deadline across the whole fan-in: N wedged replicas
        # must cost ~timeout_s total, not N*timeout_s of serialized
        # stalls on the controller actor (every other controller RPC —
        # status, scaling, wait_status — queues behind this loop)
        deadline = time.monotonic() + float(timeout_s)
        for name, ref in pending:
            try:
                snap = ray_tpu.get(
                    ref, timeout=max(0.1, deadline - time.monotonic())
                )
            except Exception:  # noqa: BLE001 — no ledger / dead / slow
                continue
            if isinstance(snap, dict):
                snap.setdefault("deployment", name)
                snaps.append(snap)
        return {"snapshots": snaps, "status": self.status()}

    def routes(self) -> Dict[str, str]:
        """route_prefix -> deployment name (proxy routing table)."""
        with self._lock:
            out = {}
            for name, st in self._deployments.items():
                prefix = st.config.route_prefix or f"/{name}"
                out[prefix] = name
            return out

    @staticmethod
    def _pressure_of(st: _DeploymentState) -> Dict[str, Any]:
        """Shed/queue pressure rollup from FRESH replica gossip — what
        lets an operator see shedding and engine backlog straight from
        ``serve.status()`` without scraping /metrics. ``queue_depth`` /
        ``outstanding_tokens`` come from engine replicas
        (``InferenceEngine.routing_stats``); ``shed_total`` from ingress
        replicas (``serve/ingress.py`` gossips its shed counter the same
        way). Stale reports (older than ``serve_routing_stats_ttl_s``)
        are excluded: a wedged replica's last gossip must not pin
        phantom pressure into the status view."""
        now = time.monotonic()
        ttl = GLOBAL_CONFIG.serve_routing_stats_ttl_s
        queue_depth = 0
        outstanding = 0.0
        shed = 0
        ttft = 0.0
        itl = 0.0
        for stats, received in st.replica_stats.values():
            if now - received > ttl:
                continue
            queue_depth += int(stats.get("queue_depth") or 0)
            outstanding += float(stats.get("outstanding_tokens") or 0.0)
            shed += int(stats.get("shed_total") or 0)
            # worst fresh replica's windowed tail latencies — the same
            # signals the autopilot steers on, surfaced for operators
            # and the load harness
            ttft = max(ttft, float(stats.get("ttft_p99_s", 0.0) or 0.0))
            itl = max(itl, float(stats.get("itl_p99_s", 0.0) or 0.0))
        return {
            "queue_depth": queue_depth,
            "outstanding_tokens": round(outstanding, 1),
            "shed_total": shed,
            "ttft_p99_s": round(ttft, 6),
            "itl_p99_s": round(itl, 6),
        }

    def status(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {
                name: {
                    "target": st.target,
                    "replicas": len(st.replicas),
                    "starting": len(st.starting),
                    "draining": len(st.draining),
                    "version": st.version,
                    # rolling-update progress: the roll is done when every
                    # routed replica is on the current version
                    "replicas_current_version": sum(
                        1 for v, _r in st.replicas if v == st.version
                    ),
                    "autoscaling": st.config.autoscaling is not None,
                    "restarts": dict(st.restarts),
                    "last_scale": dict(st.last_scale_info),
                    **self._pressure_of(st),
                }
                for name, st in self._deployments.items()
            }

    def ping(self) -> bool:
        return True

    def shutdown(self) -> bool:
        self._stop.set()
        if self._node_listener_backend is not None:
            try:
                self._node_listener_backend.remove_node_event_listener(
                    self._on_node_event
                )
            except Exception:
                pass
        with self._lock:
            deployments = list(self._deployments.values())
            self._deployments.clear()
        for st in deployments:
            handles = (
                st.replicas
                + [(v, h) for v, h, _t in st.starting]
                + [(v, h) for v, h, _t in st.draining]
            )
            for _v, r in handles:
                try:
                    ray_tpu.kill(r)
                except Exception:
                    pass
        with self._change:
            self._change.notify_all()
        return True

    # -- control loop ----------------------------------------------------
    def _control_loop(self) -> None:
        while not self._stop.wait(0.25):
            try:
                self._reconcile_once()
                self._autoscale_once()
            except Exception:  # noqa: BLE001 — keep the loop alive
                import logging

                logging.getLogger(__name__).exception("serve control loop error")

    def _spawn_replica(self, st: _DeploymentState):
        opts = dict(st.config.ray_actor_options)
        opts.setdefault("max_concurrency", st.config.max_concurrent_queries)
        return Replica.options(**opts).remote(
            st.cls_or_fn, st.init_args, st.init_kwargs, st.name,
            self._registered_namespace,
        )

    def _core_actor_info(self, handle) -> Optional[Dict[str, Any]]:
        """The runtime's actor FSM view for a replica (PENDING or a
        death reason of "no node can host" both mean the cluster can't
        place it — the real resource-stuck signals)."""
        try:
            from ray_tpu.core.api import _global_worker

            be = _global_worker().backend
            return be.io.run(
                be.controller.call(
                    "get_actor_info", {"actor_id": handle.actor_id}
                ),
                timeout=5,
            )
        except Exception:
            return None

    def _replica_node(self, handle) -> Optional[bytes]:
        """Node hosting a replica (cached: replicas never migrate)."""
        key = handle.actor_id
        nid = self._replica_nodes.get(key)
        if nid is not None:
            return nid
        info = self._core_actor_info(handle)
        addr = (info or {}).get("address")
        nid = getattr(addr, "node_id", None)
        if nid is not None:
            if len(self._replica_nodes) > 4096:  # replica-generation churn
                self._replica_nodes.clear()
            self._replica_nodes[key] = nid
        return nid

    def _alive(self, replica) -> Optional[bool]:
        """True=alive, False=dead, None=slow (indeterminate)."""
        try:
            ray_tpu.get(replica.stats.remote(), timeout=5)
            return True
        except ray_tpu.GetTimeoutError:
            return None  # slow ≠ dead
        except Exception:
            return False

    def _reconcile_once(self) -> None:
        with self._reconcile_lock:
            with self._lock:
                states = list(self._deployments.values())
            for st in states:
                changed = False
                # 1. promote starters that became ready; reap only DEAD
                # ones — slow init (large model loads) is normal for TPU
                # replicas and must never trigger a kill/respawn loop
                still_starting: List[Tuple[str, Any, float]] = []
                for v, r, t0 in st.starting:
                    ok = self._alive(r)
                    if ok is True:
                        st.replicas.append((v, r))
                        changed = True
                    elif ok is False:
                        # a starter the RUNTIME failed as unplaceable is
                        # the resource-stuck signal (the core fails such
                        # actors at its lease timeout, typically before
                        # our PENDING-age gate can observe them)
                        info = self._core_actor_info(r)
                        if info and str(info.get("reason", "")).startswith(
                            "no node can host"
                        ):
                            st.unplaceable_ts = time.monotonic()
                        try:
                            ray_tpu.kill(r)
                        except Exception:
                            pass
                    else:
                        still_starting.append((v, r, t0))
                st.starting = still_starting
                # 2. reap dead ready replicas (timeout = overload, keep)
                alive: List[Tuple[str, Any]] = []
                for v, r in st.replicas:
                    ok = self._alive(r)
                    if ok is False:
                        changed = True
                        _count_replica_restart(st, "death")
                        try:
                            ray_tpu.kill(r)
                        except Exception:
                            pass
                    else:
                        alive.append((v, r))
                st.replicas = alive
                # 2a. proactive health: replicas that ANSWER but report
                # unhealthy (replica.health -> the callable's
                # check_health, e.g. the LLM engine's wedged-step-loop
                # detector) are restarted — liveness alone never catches
                # a stalled engine whose actor loop still replies. Own
                # cadence: the 0.25s reconcile pass must not double
                # every replica's RPC load.
                period = GLOBAL_CONFIG.serve_replica_health_period_s
                now_h = time.monotonic()
                if (
                    period > 0
                    and st.replicas
                    and now_h - st.last_health_ts >= period
                ):
                    st.last_health_ts = now_h
                    healthy: List[Tuple[str, Any]] = []
                    for v, r in st.replicas:
                        wedged = False
                        try:
                            wedged = (
                                ray_tpu.get(r.health.remote(), timeout=5)
                                is False
                            )
                        except Exception:
                            # dead/slow/raising: liveness reaping (above,
                            # next pass) owns those — restarting on a
                            # saturated replica's timeout would turn
                            # overload into an outage
                            wedged = False
                        if wedged:
                            changed = True
                            _count_replica_restart(st, "unhealthy")
                            try:
                                ray_tpu.kill(r)
                            except Exception:
                                pass
                        else:
                            healthy.append((v, r))
                    st.replicas = healthy
                # 2b. preemption handoff: replicas on DRAINING nodes are
                # unrouted NOW (routers drop them on the next long-poll
                # push, in-flight requests finish, the drain-kill waits
                # for idle) and replacements start below — all inside the
                # node's drain grace, so clients see zero errors.
                if self._draining_nodes:
                    still_routed: List[Tuple[str, Any]] = []
                    for v, r in st.replicas:
                        nid = self._replica_node(r)
                        if nid is not None and nid in self._draining_nodes:
                            st.draining.append((v, r, time.monotonic()))
                            changed = True
                        else:
                            still_routed.append((v, r))
                    st.replicas = still_routed
                cur = st.version
                ready_cur = [(v, r) for v, r in st.replicas if v == cur]
                ready_old = [(v, r) for v, r in st.replicas if v != cur]
                starting_cur = [s for s in st.starting if s[0] == cur]
                # 3. start replicas: scale-up AND rolling replacement are
                # the same move — keep (ready_cur + starting_cur) headed
                # toward target, start-before-kill. While OLD replicas
                # exist the surge is capped at 1: TPU replicas hold chips,
                # and a full-surge roll could never schedule.
                start_cap = 1 if ready_old else st.target
                while (
                    len(ready_cur) + len(starting_cur) < st.target
                    and len(starting_cur) < start_cap
                ):
                    h = self._spawn_replica(st)
                    entry = (cur, h, time.monotonic())
                    st.starting.append(entry)
                    starting_cur.append(entry)
                # resource-stuck roll: if the new replica can't come up
                # (cluster can't fit target+1 — e.g. all chips held by
                # old replicas), free one old after a grace period; the
                # availability dip is then unavoidable, not a deadlock
                now = time.monotonic()
                # resource-stuck: either a live starter is still PENDING
                # past the grace (cluster can't fit target+1), or the
                # runtime already failed a starter as unplaceable. A
                # placed-but-slow init (big model load) matches neither.
                starter_pending = bool(starting_cur) and (
                    now - min(t for _v, _h, t in starting_cur) > 30
                    and (self._core_actor_info(starting_cur[0][1]) or {}).get(
                        "state"
                    )
                    == "PENDING"
                )
                recently_unplaceable = now - st.unplaceable_ts < 60 and (
                    st.unplaceable_ts > 0
                )
                if (
                    ready_old
                    and (starter_pending or recently_unplaceable)
                    # one eviction per grace period — or every 0.25s pass
                    # would drain another old replica and a slow roll
                    # would cause a full outage
                    and now - st.last_stuck_evict_ts > 30
                ):
                    st.last_stuck_evict_ts = now
                    victim = ready_old.pop(0)
                    st.replicas.remove(victim)
                    st.draining.append((victim[0], victim[1], now))
                    changed = True
                # 4. rolling: once a current-version replica is ready,
                # retire old-version replicas one-for-one (total ready
                # never dips below target while old ones remain). Retire
                # = UNROUTE now, kill only after in-flight requests drain
                # (zero-downtime: a hard kill would fail them).
                while ready_old and len(st.replicas) > st.target:
                    victim = ready_old.pop(0)
                    st.replicas.remove(victim)
                    st.draining.append((victim[0], victim[1], time.monotonic()))
                    changed = True
                # 5. scale down current-version surplus (same drain)
                while not ready_old and len(st.replicas) > st.target:
                    v, r = st.replicas.pop()
                    st.draining.append((v, r, time.monotonic()))
                    changed = True
                # 6. reap drained replicas: kill once idle (or after the
                # 30s drain grace for stuck requests)
                still_draining: List[Tuple[str, Any, float]] = []
                for v, r, t0 in st.draining:
                    idle = False
                    try:
                        idle = (
                            ray_tpu.get(r.stats.remote(), timeout=5)["ongoing"] == 0
                        )
                    except ray_tpu.GetTimeoutError:
                        idle = False  # saturated ≠ idle: wait out the grace
                    except Exception:
                        idle = True  # dead/unreachable: nothing to drain
                    # ≥0.5s in drain before an idle-kill: routers need a
                    # long-poll push cycle to drop the replica from their
                    # cached set, or a just-dispatched request dies
                    if (idle and time.monotonic() - t0 > 0.5) or (
                        time.monotonic() - t0 > 30
                    ):
                        try:
                            ray_tpu.kill(r)
                        except Exception:
                            pass
                    else:
                        still_draining.append((v, r, t0))
                st.draining = still_draining
                with self._lock:
                    if self._deployments.get(st.name) is not st:
                        # state swapped mid-reconcile (redeploy/delete):
                        # hand our replicas to the new state object so
                        # the roll continues from them
                        newer = self._deployments.get(st.name)
                        if newer is not None:
                            newer.replicas = st.replicas
                            newer.starting = st.starting
                            newer.draining = st.draining
                        else:
                            # deleted mid-pass: kill EVERYTHING this pass
                            # touched, incl. starters spawned after the
                            # delete snapshotted its handles
                            handles = (
                                st.replicas
                                + [(v, h) for v, h, _t in st.starting]
                                + [(v, h) for v, h, _t in st.draining]
                            )
                            for _v, r in handles:
                                try:
                                    ray_tpu.kill(r)
                                except Exception:
                                    pass
                if changed:
                    self._bump(st.name)

    def _autoscale_once(self) -> None:
        now = time.monotonic()
        with self._lock:
            all_states = dict(self._deployments)
        states = [s for s in all_states.values() if s.config.autoscaling]
        # disagg prefill pools whose size the decode pool's token mix
        # owns: the ratio decision replaces the queue/SLO decision there
        # (both deployments must exist and the prefill one must opt in
        # by carrying an autoscaling config)
        paired_prefill = {
            st.config.disagg_prefill: st.name
            for st in all_states.values()
            if st.config.disagg_prefill and st.config.disagg_prefill in all_states
        }
        for st in states:
            cfg: AutoscalingConfig = st.config.autoscaling
            if st.name in paired_prefill:
                self._adapt_prefill_pool(
                    st, all_states[paired_prefill[st.name]], cfg, now
                )
                continue
            total = 0.0
            n = 0
            ttft = 0.0
            for _v, r in st.replicas:
                try:
                    total += ray_tpu.get(r.stats.remote(), timeout=5)["ongoing"]
                    n += 1
                except Exception:
                    pass
                # gossip-capable replicas (LLM engines) also report their
                # ADMISSION-QUEUE depth: requests the engine had to park
                # for KV blocks are real unmet demand that the serve-level
                # ongoing count (streams in flight) underplays — fold it
                # into the autoscale signal so a saturated engine scales
                # out before callers hit the queue bound. FRESH reports
                # only: a wedged reporter's last gossip must not pin
                # phantom demand into every future autoscale pass.
                ent = st.replica_stats.get(r.actor_id.hex())
                if ent is not None and (
                    now - ent[1] < GLOBAL_CONFIG.serve_routing_stats_ttl_s
                ):
                    total += float(ent[0].get("queue_depth", 0) or 0)
                    # SLO autopilot signal: the WORST fresh replica's
                    # windowed TTFT p99 — a tail SLO is only as good as
                    # the slowest replica serving it
                    ttft = max(ttft, float(ent[0].get("ttft_p99_s", 0.0) or 0.0))
            # the front door's client-observed first-byte p99 for THIS
            # deployment (ingress replicas gossip target + ttfb_p99_s):
            # the door's clock includes router-side waits — a replica
            # death, dispatch queues — that the engines' own TTFT
            # windows never contain, so a kill that stalls clients
            # burns the budget even while every surviving engine's
            # p99 looks healthy
            for other in all_states.values():
                for stats, received in other.replica_stats.values():
                    if (
                        stats.get("ingress")
                        and stats.get("target") == st.name
                        and now - received
                        < GLOBAL_CONFIG.serve_routing_stats_ttl_s
                    ):
                        ttft = max(
                            ttft, float(stats.get("ttfb_p99_s", 0.0) or 0.0)
                        )
            # no replica answered AND no door is watching: nothing to
            # steer on. But every-replica-dead WITH a fresh ingress
            # signal is exactly when budget burn must still scale out —
            # the replacement logic restores count, the burn decision
            # raises it
            if n == 0 and ttft <= 0.0:
                continue
            desired, reason = autoscale_decision(
                target=st.target, cfg=cfg, total_load=total, ttft_p99_s=ttft
            )
            self._apply_scale(st, cfg, desired, reason, now)

    def _apply_scale(
        self,
        st: _DeploymentState,
        cfg: AutoscalingConfig,
        desired: int,
        reason: str,
        now: float,
    ) -> None:
        """Delay-gated target write shared by every autoscale path —
        records the applied decision for status()/harness lag scoring."""
        delay = (
            cfg.upscale_delay_s if desired > st.target else cfg.downscale_delay_s
        )
        if desired != st.target and now - st.last_scale_ts >= delay:
            prev = st.target
            st.target = desired
            st.last_scale_ts = now
            st.last_scale_info = {
                "ts": time.time(),
                "from": prev,
                "to": desired,
                "reason": reason,
            }
            _count_autoscale_decision(st.name, reason)

    def _adapt_prefill_pool(
        self,
        st: _DeploymentState,
        decode_st: _DeploymentState,
        cfg: AutoscalingConfig,
        now: float,
    ) -> None:
        """Adapt a disagg prefill pool's size to the observed
        prefill:decode token mix (both rates from fresh engine gossip —
        prefill throughput reported by the prefill pool, decode
        throughput by the decode pool)."""
        ttl = GLOBAL_CONFIG.serve_routing_stats_ttl_s

        def _rate(state: _DeploymentState, key: str) -> float:
            return sum(
                float(stats.get(key, 0.0) or 0.0)
                for stats, received in state.replica_stats.values()
                if now - received <= ttl
            )

        desired, reason = pool_ratio_decision(
            prefill_target=st.target,
            n_decode=len(decode_st.replicas),
            prefill_tokens_per_s=_rate(st, "prefill_tokens_per_s"),
            decode_tokens_per_s=_rate(decode_st, "decode_tokens_per_s"),
            min_replicas=cfg.min_replicas,
            max_replicas=cfg.max_replicas,
        )
        self._apply_scale(st, cfg, desired, reason, now)


ServeController = ray_tpu.remote(_ServeController)


def get_or_create_controller():
    # get_if_exists handles the named-actor creation race internally
    # (actor.py) and real creation failures surface as themselves.
    # long-polls park a thread each for up to 30s; a dedicated
    # concurrency group keeps any number of routers from starving
    # deploy/status/get_replicas lanes
    try:
        ns = ray_tpu.get_runtime_context().namespace
    except Exception:
        ns = None
    return ServeController.options(
        name=CONTROLLER_NAME,
        num_cpus=0,
        max_concurrency=16,
        concurrency_groups={"longpoll": 32},
        get_if_exists=True,
    ).remote(ns)
