"""HTTP/SSE ingress: the overload-safe multi-tenant front door.

Everything used to enter through an in-process ``DeploymentHandle`` —
one abusive tenant could fill every engine admission queue and turn
overload into an outage. This module terminates streaming HTTP at the
edge and owns the front-door robustness policies (reference shape:
``serve/_private/proxy.py``, run as a *deployment* so it scales/heals
like any replica set; grounding: the Gemma-on-TPU serving comparison,
PAPERS.md arXiv:2605.25645, which scores SLO attainment under
contention, not raw tok/s):

* **per-tenant fairness** — a cost-denominated token bucket per tenant
  (cost = prompt tokens + ``max_new_tokens``), with per-tenant
  rate/burst overrides and a priority class (``interactive`` >
  ``standard`` > ``batch``). 429 + ``Retry-After`` (the exact bucket
  refill wait) instead of queueing.
* **shed BEFORE queue** — the shed decision reads the engine
  queue-depth / outstanding-token gossip the router already receives
  (``Router.cluster_pressure()``, zero extra RPCs): a request that
  would only park in an engine admission queue is refused at the door
  with 429 + ``Retry-After``, so a shed request consumes **zero**
  engine queue slots. Lower classes shed first (``shed_verdict``);
  the class also rides downstream as the engine ``priority``, so
  degradation continues inside the scheduler (batch work is preempted
  for interactive work under block pressure).
* **client-disconnect propagation** — a client that goes away
  mid-stream closes the value iterator, which abandons the ref stream,
  which cooperatively cancels the replica-side producer, which closes
  the engine generator and ``cancel()``s the request: KV blocks and
  the decode slot free within ~one token (core/streaming.py,
  core/task_executor.py, serve/router.py).
* **per-request deadlines** — ``x-request-timeout-s`` (clamped to
  ``serve_ingress_default_timeout_s``) is stamped into the ambient
  ``core/deadline`` budget, so the engine stops decoding for callers
  that already gave up.
* **tenant/session affinity** — :func:`pick_ingress` rendezvous-hashes
  a tenant onto one ingress replica; that replica's router (optimistic
  load bumps + the PR 6 prefix-affinity scorer) keeps the tenant's
  shared system prompt hot on one backend replica subset.

Downstream of the door, requests ride the resumable-stream path
(``Router.execute_stream`` tier 3): a mid-stream backend replica death
is invisible through HTTP — the stream resumes on a survivor with
exactly-once token delivery.

The ingress replica is a PLAIN serve class (no jax import) — it holds a
``DeploymentHandle`` to the engine deployment, which works inside an
actor because handles pickle with their controller handle (PR 9).
Observability: ``raytpu_ingress_requests_total{tenant_class,outcome}``,
``raytpu_ingress_shed_total{reason}``, ``raytpu_ingress_ttfb_seconds``;
``serve.status()`` surfaces ``{shed_total, queue_depth,
outstanding_tokens}`` per deployment from the same gossip channel.
"""

from __future__ import annotations

import asyncio
import json
import math
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Sequence

from ray_tpu.core.config import GLOBAL_CONFIG
from ray_tpu.core.deadline import deadline_scope

#: priority classes, most sheddable first; the value doubles as the
#: engine ``priority`` (the continuous-batching scheduler preempts
#: lowest-priority-first, so the ladder applies inside the engine too)
CLASS_PRIORITY: Dict[str, int] = {"batch": 0, "standard": 1, "interactive": 2}

_TOP_PRIORITY = max(CLASS_PRIORITY.values())

_SENTINEL = object()


class TokenBucket:
    """Cost-denominated token bucket (fairness primitive). Not
    thread-safe on its own — the ingress serializes access under one
    lock. ``now`` is injectable for deterministic unit tests."""

    __slots__ = ("rate", "burst", "level", "stamp")

    def __init__(self, rate: float, burst: float):
        self.rate = max(1e-9, float(rate))
        self.burst = max(1.0, float(burst))
        self.level = self.burst
        self.stamp = time.monotonic()

    def try_take(self, cost: float, now: Optional[float] = None) -> float:
        """0.0 → taken. Otherwise the seconds until the bucket could
        cover ``cost`` (the honest ``Retry-After``); nothing is taken.
        A cost above the burst capacity is quoted against the cap —
        the wait is the time to refill a FULL bucket, after which the
        request is admitted with the bucket driven negative (a tenant
        whose single request exceeds its whole burst must still be
        servable, just slowly)."""
        now = time.monotonic() if now is None else now
        self.level = min(self.burst, self.level + (now - self.stamp) * self.rate)
        self.stamp = now
        need = min(float(cost), self.burst)
        if need <= self.level:
            self.level -= float(cost)
            return 0.0
        return (need - self.level) / self.rate


@dataclass
class TenantPolicy:
    """Per-tenant knobs; ``None`` falls through to the config/global
    defaults."""

    rate: Optional[float] = None
    burst: Optional[float] = None
    tenant_class: str = "standard"


@dataclass
class IngressConfig:
    #: downstream deployment name (informational once a handle is bound)
    target: str = "llm"
    #: downstream streaming method (must be LLM-shaped: dict request
    #: with a token ``prompt``) — ``generate`` rides the resumable path
    method: str = "generate"
    default_class: str = "standard"
    #: None → the ``serve_ingress_default_rate``/``_burst`` knobs
    default_rate: Optional[float] = None
    default_burst: Optional[float] = None
    tenants: Dict[str, TenantPolicy] = field(default_factory=dict)
    #: load watermark: priority-0 (batch) requests shed once the
    #: gossiped outstanding tokens per REPORTING replica exceed this;
    #: class k sheds above ``(k+1) ×`` it — interactive traffic keeps
    #: flowing until 3× the pressure that sheds batch. <= 0 disables.
    shed_outstanding_per_replica: float = 2048.0
    #: queue watermark: below-top classes shed once the summed engine
    #: admission queues reach this fraction of their gossiped bound;
    #: at >= 1.0 (queues actually full) every class sheds — queueing
    #: further would only park the request until its deadline
    shed_queue_fraction: float = 0.5
    #: None → the ``serve_ingress_default_timeout_s`` knob
    default_timeout_s: Optional[float] = None
    #: None → the ``serve_ingress_retry_after_s`` knob
    retry_after_s: Optional[float] = None
    #: thread pool sizing for the blocking stream plumbing: each ACTIVE
    #: stream parks one worker in next() between tokens, so this is the
    #: per-replica concurrent-stream ceiling (excess requests queue at
    #: dispatch — explicit backpressure, not starvation of the shared
    #: default pool, whose min(32, cpus+4) workers would otherwise cap
    #: concurrency far below max_concurrent_queries)
    max_concurrent_streams: int = 64
    #: bucket-persistence key (set by ``ingress_deployment`` to the door
    #: deployment's name): per-tenant token-bucket fill levels snapshot
    #: to the serve controller on a timer and are restored by a
    #: replacement replica — a restart no longer refills every tenant's
    #: budget. None (standalone/driver use) disables persistence.
    snapshot_key: Optional[str] = None
    #: SLO autopilot: ITL p99 budget (seconds). When set, the load
    #: watermark above stops being a static constant — it is scaled by
    #: target/measured ITL (worst fresh replica's windowed p99, from
    #: gossip), so the door tightens admission while decode steps are
    #: slow and relaxes it when ITL runs comfortably under budget. See
    #: ``effective_shed_threshold``. None = static watermark.
    shed_itl_target_s: Optional[float] = None

    def resolved_rate(self, pol: TenantPolicy) -> float:
        if pol.rate is not None:
            return pol.rate
        if self.default_rate is not None:
            return self.default_rate
        return GLOBAL_CONFIG.serve_ingress_default_rate

    def resolved_burst(self, pol: TenantPolicy) -> float:
        if pol.burst is not None:
            return pol.burst
        if self.default_burst is not None:
            return self.default_burst
        return GLOBAL_CONFIG.serve_ingress_default_burst


#: bounds on the ITL-derived watermark adjustment: the closed loop may
#: tighten the static base to 1/4 or relax it to 4x, never beyond —
#: a latency spike (or an idle, instantly-fast engine) must not swing
#: admission to zero or infinity on one gossip window
ITL_ADJUST_MIN = 0.25
ITL_ADJUST_MAX = 4.0


def effective_shed_threshold(
    base: float,
    itl_target_s: Optional[float],
    measured_itl_p99_s: float,
) -> float:
    """The load watermark the door actually applies, as a pure function
    (cluster-free testable). Static ``base`` when no ITL target is
    configured or no measurement has gossiped yet; otherwise the base
    scaled by target/measured — at-budget ITL reproduces the static
    threshold exactly, 2x-over-budget halves it, half-budget doubles it
    — clamped to [ITL_ADJUST_MIN, ITL_ADJUST_MAX] times the base."""
    if base <= 0 or not itl_target_s or measured_itl_p99_s <= 0.0:
        return base
    adjust = float(itl_target_s) / measured_itl_p99_s
    adjust = max(ITL_ADJUST_MIN, min(ITL_ADJUST_MAX, adjust))
    return base * adjust


def shed_verdict(
    pressure: Dict[str, Any], priority: int, cfg: IngressConfig
) -> Optional[str]:
    """Shed-before-queue policy, as a pure function (unit-tested
    without a cluster). ``pressure`` is ``Router.cluster_pressure()``
    output. Returns None (admit) or a shed reason.

    No fresh gossip → ADMIT: shedding blind would turn a gossip hiccup
    into an outage; the engine's own admission bound remains the
    backstop."""
    reporting = int(pressure.get("reporting") or 0)
    if reporting <= 0:
        return None
    max_queue = int(pressure.get("max_queue_depth") or 0)
    if max_queue > 0:
        frac = float(pressure.get("queue_depth") or 0) / max_queue
        if frac >= 1.0 or (
            frac >= cfg.shed_queue_fraction and priority < _TOP_PRIORITY
        ):
            return "queue_pressure"
    base = effective_shed_threshold(
        cfg.shed_outstanding_per_replica,
        cfg.shed_itl_target_s,
        float(pressure.get("itl_p99_s") or 0.0),
    )
    if base > 0:
        per_replica = float(pressure.get("outstanding_tokens") or 0.0) / reporting
        if per_replica > base * (priority + 1):
            return "load"
    return None


def _ingress_metrics():
    from ray_tpu.observability.rpc_metrics import (
        INGRESS_REQUESTS,
        INGRESS_SHED,
        INGRESS_TTFB,
    )

    return INGRESS_REQUESTS, INGRESS_SHED, INGRESS_TTFB


class HttpIngress:
    """One ingress replica: an aiohttp HTTP/SSE server owning the
    front-door policies, forwarding admitted requests through a
    ``DeploymentHandle``'s router. Defined undecorated at module level
    so cloudpickle exports it by reference (see serve/replica.py)."""

    def __init__(
        self,
        config: Optional[IngressConfig] = None,
        handle=None,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
    ):
        self.cfg = config or IngressConfig()
        if handle is None:
            # driver-side standalone use; inside a replica the handle is
            # bound at deploy time (ingress_deployment) — a bare name
            # can't find the controller from the worker's namespace
            from ray_tpu import serve as _serve

            handle = _serve.get_deployment_handle(self.cfg.target)
        self._target_handle = handle
        self._router = handle._router
        self._lock = threading.Lock()
        self._buckets: Dict[str, TokenBucket] = {}
        #: restored bucket states from the serve controller (tenant ->
        #: {"level", "wall"}), consumed lazily as tenants re-appear —
        #: refill since the snapshot is credited at consumption time
        self._bucket_seed: Dict[str, Dict[str, float]] = {}
        self._snapshot_stop = threading.Event()
        if self.cfg.snapshot_key:
            self._restore_buckets()
            period = GLOBAL_CONFIG.serve_ingress_bucket_snapshot_period_s
            if period > 0:
                threading.Thread(
                    target=self._bucket_snapshot_loop,
                    args=(period,),
                    daemon=True,
                    name="ingress-bucket-snapshot",
                ).start()
        #: local mirrors of the prometheus counters — gossiped to the
        #: serve controller (routing_stats) and returned by debug_stats
        #: so tests/operators read them without scraping /metrics
        self._shed_total = 0
        self._sheds: Dict[str, int] = {}
        self._outcomes: Dict[str, int] = {}
        self._forwarded = 0
        #: independent intake count (stamped at request entry, BEFORE
        #: any policy runs) — the left-hand side of the ingress
        #: conservation identity seen == shed + bad_request + forwarded
        self._seen = 0
        #: last flight-recorder shed entry per reason (1/s sampling —
        #: see _count_shed)
        self._shed_flight_at: Dict[str, float] = {}
        #: (monotonic, ttfb_s) client-observed first-byte latencies —
        #: the windowed p99 gossiped through routing_stats. The door's
        #: clock includes router-side waits (replica death, dispatch
        #: queues) that the engines' own TTFT windows never contain, so
        #: the controller's SLO-autopilot burn signal for the TARGET
        #: deployment reads it alongside the engines' gossip
        self._recent_ttfb: deque = deque(maxlen=512)
        #: forwarded requests still waiting for their FIRST byte
        #: (request_id -> forward monotonic). Their current age is a
        #: live lower bound on the eventual TTFB, folded into
        #: ``_ttfb_p99`` — without it a total stall (every replica dead)
        #: produces NO samples and the burn signal goes blind exactly
        #: when it matters
        self._inflight_t0: Dict[str, float] = {}
        self.host = host
        self.port = int(port)
        # dedicated pool for the blocking stream plumbing (dispatch +
        # per-item next): sized to the stream ceiling. Iterator CLOSES
        # deliberately run on the loop's default pool instead — a close
        # must never queue behind 64 parked next() calls, or a
        # disconnected client's engine work outlives it (exactly the
        # overload regime disconnect propagation exists for).
        from concurrent.futures import ThreadPoolExecutor

        self._exec = ThreadPoolExecutor(
            max_workers=max(1, int(self.cfg.max_concurrent_streams)),
            thread_name_prefix="ingress-stream",
        )
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._started = threading.Event()
        self._startup_error: Optional[BaseException] = None
        self._thread = threading.Thread(
            target=self._serve_loop, daemon=True, name="serve-ingress"
        )
        self._thread.start()
        if not self._started.wait(15) or self._startup_error is not None:
            raise RuntimeError(
                f"ingress http server failed to start: {self._startup_error!r}"
            )

    # -- server thread ----------------------------------------------------
    def _serve_loop(self) -> None:
        from aiohttp import web

        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop
        app = web.Application()
        app.router.add_get("/healthz", self._handle_health)
        app.router.add_route("*", "/{tail:.*}", self._handle)
        # access_log=None: per-request log lines would be forwarded to
        # every connected driver by the worker log tailer — pure noise
        # at serving rates (the request counters carry the signal)
        runner = web.AppRunner(app, access_log=None)

        async def _start():
            await runner.setup()
            site = web.TCPSite(runner, self.host, self.port)
            await site.start()
            addrs = runner.addresses
            if addrs:
                self.port = int(addrs[0][1])  # port=0 → the bound port
            self._started.set()

        try:
            loop.run_until_complete(_start())
        except BaseException as e:  # noqa: BLE001 — surface to __init__
            self._startup_error = e
            self._started.set()
            return
        loop.run_forever()

    async def _handle_health(self, request):
        from aiohttp import web

        return web.json_response({"ok": True})

    # -- bucket persistence (the restart-refill fix) ----------------------
    def _restore_buckets(self) -> None:
        """Pull the door's persisted bucket table from the serve
        controller. Best-effort: an unreachable controller means fresh
        buckets (the pre-persistence behavior), never a failed start."""
        import ray_tpu

        try:
            controller = self._target_handle._controller
            self._bucket_seed = dict(
                ray_tpu.get(
                    controller.load_ingress_buckets.remote(
                        self.cfg.snapshot_key
                    ),
                    timeout=10,
                )
                or {}
            )
        except Exception:  # noqa: BLE001
            self._bucket_seed = {}

    def _bucket_snapshot_loop(self, period: float) -> None:
        """Timer push of per-tenant fill levels. Wall-clock stamps, not
        monotonic — the restoring replica is a different process, and it
        credits refill for the downtime from the stamp."""
        controller = self._target_handle._controller
        while not self._snapshot_stop.wait(period):
            try:
                now_mono = time.monotonic()
                now_wall = time.time()
                with self._lock:
                    snap = {
                        tenant: {
                            "level": b.level,
                            "wall": now_wall - (now_mono - b.stamp),
                        }
                        for tenant, b in self._buckets.items()
                    }
                if snap:
                    controller.save_ingress_buckets.remote(
                        self.cfg.snapshot_key, snap
                    )
            except Exception:  # noqa: BLE001 — drop the tick, keep looping
                pass

    # -- accounting -------------------------------------------------------
    def _count(self, tenant_class: str, outcome: str) -> None:
        requests, _shed, _ttfb = _ingress_metrics()
        with self._lock:
            key = f"{tenant_class}:{outcome}"
            self._outcomes[key] = self._outcomes.get(key, 0) + 1
        requests.inc(labels={"tenant_class": tenant_class, "outcome": outcome})

    def _count_shed(self, tenant_class: str, reason: str, tenant: str = "") -> None:
        _requests, shed, _ttfb = _ingress_metrics()
        with self._lock:
            self._shed_total += 1
            self._sheds[reason] = self._sheds.get(reason, 0) + 1
        shed.inc(labels={"reason": reason})
        self._count(tenant_class, "shed")
        # SLO ledger: sheds file flagged flight-recorder entries —
        # capacity-protection decisions the operator audits when goodput
        # dips — but SAMPLED at ~1/s per reason: an overload burst sheds
        # hundreds per second, and unsampled they would flush every
        # resumed/slow/error outlier out of the shared newest-win ring
        # exactly when the operator needs it (totals live in the
        # counters; the ring only needs a representative)
        now = time.monotonic()
        with self._lock:
            last = self._shed_flight_at.get(reason, 0.0)
            if now - last < 1.0:
                return
            self._shed_flight_at[reason] = now
        from ray_tpu.observability.slo import flight_recorder

        flight_recorder().add(
            {
                "tier": "ingress",
                "request_id": None,
                "deployment": self.cfg.target,
                "tenant_class": tenant_class,
                "tenant": tenant,
                "outcome": "shed",
                "shed_reason": reason,
                "flags": ["shed"],
                "stages": {},
            },
            flagged=True,
        )

    #: bucket-table bound: past it the least-recently-used quarter is
    #: evicted (an evicted tenant's next request refills a fresh burst —
    #: acceptable for cold tenants, and the table can't grow forever)
    _MAX_BUCKETS = 4096

    def _take(self, tenant: str, pol: TenantPolicy, cost: float) -> float:
        """NOTE: the tenant id is caller-supplied — fairness is only as
        strong as the authentication in front of this header. A client
        minting a fresh id per request starts each one on a fresh burst;
        deploy behind an authenticating edge (or derive the tenant from
        credentials) for adversarial traffic. The cluster-pressure shed
        (class-blind on unknown tenants: default class) remains the
        backstop either way."""
        with self._lock:
            bucket = self._buckets.get(tenant)
            if bucket is None:
                if len(self._buckets) >= self._MAX_BUCKETS:
                    # TokenBucket.stamp is the last-touch time: evict the
                    # coldest quarter in one pass instead of per-insert
                    for victim in sorted(
                        self._buckets, key=lambda t: self._buckets[t].stamp
                    )[: self._MAX_BUCKETS // 4]:
                        del self._buckets[victim]
                bucket = self._buckets[tenant] = TokenBucket(
                    self.cfg.resolved_rate(pol), self.cfg.resolved_burst(pol)
                )
                seed = self._bucket_seed.pop(tenant, None)
                if seed is not None:
                    # resume the persisted fill level, crediting refill
                    # for the time since the snapshot — a restart must
                    # not hand a depleted tenant a fresh burst, nor
                    # freeze its refill clock
                    bucket.level = min(
                        bucket.burst,
                        float(seed.get("level", bucket.burst))
                        + max(0.0, time.time() - float(seed.get("wall", 0.0)))
                        * bucket.rate,
                    )
            return bucket.try_take(cost)

    def _budget(self, request, body: Dict[str, Any]) -> float:
        ceiling = (
            self.cfg.default_timeout_s
            if self.cfg.default_timeout_s is not None
            else GLOBAL_CONFIG.serve_ingress_default_timeout_s
        )
        raw = request.headers.get("x-request-timeout-s")
        if raw is None:
            raw = body.get("timeout_s")
        if raw is None:
            return ceiling
        try:
            return max(0.1, min(float(raw), ceiling))
        except (TypeError, ValueError):
            return ceiling

    # -- request path -----------------------------------------------------
    async def _handle(self, request):
        from aiohttp import web

        t0 = time.monotonic()
        _requests, _shed, ttfb = _ingress_metrics()
        if request.method != "POST":
            return web.json_response(
                {"error": "POST a generation request"}, status=405
            )
        with self._lock:
            self._seen += 1
        try:
            raw = await request.read()
            body = json.loads(raw) if raw else {}
            if not isinstance(body, dict):
                raise ValueError("request body must be a JSON object")
            prompt = [int(t) for t in (body.get("prompt") or ())]
            if not prompt:
                raise ValueError("request needs a 'prompt' (list of token ids)")
            max_new = int(body.get("max_new_tokens") or 64)
            if max_new < 1:
                raise ValueError("max_new_tokens must be >= 1")
        except Exception as e:  # noqa: BLE001 — malformed input
            self._count("unknown", "bad_request")
            return web.json_response({"error": f"bad request: {e!r}"}, status=400)

        tenant = request.headers.get("x-tenant-id") or str(
            body.get("tenant") or "anonymous"
        )
        pol = self.cfg.tenants.get(tenant) or TenantPolicy(
            tenant_class=self.cfg.default_class
        )
        tenant_class = (
            pol.tenant_class if pol.tenant_class in CLASS_PRIORITY else "standard"
        )
        priority = CLASS_PRIORITY[tenant_class]
        cost = len(prompt) + max_new

        # 1. per-tenant fairness — the bucket sheds BEFORE any
        # downstream work; Retry-After is the exact refill wait
        retry_after = self._take(tenant, pol, cost)
        if retry_after > 0.0:
            self._count_shed(tenant_class, "rate_limit", tenant)
            return self._shed_response(web, "rate_limit", retry_after)

        # 2. cluster pressure — gossiped engine stats the router already
        # holds; a shed here provably never consumed an engine queue slot
        reason = shed_verdict(self._router.cluster_pressure(), priority, self.cfg)
        if reason is not None:
            self._count_shed(tenant_class, reason, tenant)
            retry = (
                self.cfg.retry_after_s
                if self.cfg.retry_after_s is not None
                else GLOBAL_CONFIG.serve_ingress_retry_after_s
            )
            return self._shed_response(web, reason, retry)

        # 3. forward on the resumable-stream path, class stamped as the
        # engine priority, deadline stamped into the ambient budget
        req = dict(body)
        req["prompt"] = prompt
        req["max_new_tokens"] = max_new
        req["priority"] = priority  # the CLASS decides, never the client
        # SLO ledger: the class labels the latency histograms downstream,
        # and pinning the request id HERE (the first tier that sees the
        # request) lets slo_report() join this tier's flight-recorder
        # entry with the router's and the engine's for one request
        req["tenant_class"] = tenant_class
        import uuid as _uuid

        req.setdefault("request_id", _uuid.uuid4().hex[:16])
        rid = str(req["request_id"])
        req.pop("tenant", None)
        req.pop("timeout_s", None)
        budget = self._budget(request, body)
        model_id = request.headers.get("serve-multiplexed-model-id", "")
        method = self.cfg.method
        router = self._router
        loop = asyncio.get_event_loop()

        def _dispatch():
            with deadline_scope(budget):
                return router.execute_stream(
                    method, (req,), {}, model_id=model_id, timeout=budget
                )

        with self._lock:
            self._forwarded += 1
            self._inflight_t0[rid] = time.monotonic()
        try:
            values = await loop.run_in_executor(self._exec, _dispatch)
        except Exception as e:  # noqa: BLE001 — dispatch failed
            self._inflight_t0.pop(rid, None)
            self._count(tenant_class, "error")
            return web.json_response({"error": repr(e)}, status=503)

        streaming = "text/event-stream" in request.headers.get("Accept", "")
        it = iter(values)
        if not streaming:
            try:
                tokens = await loop.run_in_executor(self._exec, list, it)
            except Exception as e:  # noqa: BLE001
                self._count(tenant_class, "error")
                self._flight_ttfb(rid, tenant_class, time.monotonic() - t0, "error")
                return web.json_response({"error": repr(e)}, status=503)
            finally:
                await loop.run_in_executor(None, _close_iterator, it)
            dur = time.monotonic() - t0
            ttfb.observe(dur)
            self._count(tenant_class, "ok")
            self._flight_ttfb(rid, tenant_class, dur, "ok")
            return web.json_response({"tokens": tokens})
        return await self._stream_sse(request, it, tenant_class, t0, rid)

    def _flight_ttfb(
        self, rid: str, tenant_class: str, ttfb_s: float, outcome: str
    ) -> None:
        """File an ingress-tier flight entry for a slow or failed
        request (cheap predicate per request; the joined record then
        shows whether the time went to the door, the router, or the
        engine)."""
        with self._lock:
            # first-byte-pending entry still here → the SSE first-byte
            # hook never sampled this request (JSON path, or it died
            # before any byte): record its TTFB now, exactly once
            if self._inflight_t0.pop(rid, None) is not None:
                self._recent_ttfb.append((time.monotonic(), float(ttfb_s)))
        slow = ttfb_s > GLOBAL_CONFIG.slo_ttft_slow_s
        if not slow and outcome == "ok":
            return
        from ray_tpu.observability.slo import flight_recorder

        flags = (["slow_ttfb"] if slow else []) + (
            [outcome] if outcome != "ok" else []
        )
        flight_recorder().add(
            {
                "tier": "ingress",
                "request_id": rid,
                "deployment": self.cfg.target,
                "tenant_class": tenant_class,
                "outcome": outcome,
                "ttft_s": round(ttfb_s, 6),
                "stages": {"ttfb": round(ttfb_s, 6)},
                "flags": flags,
            },
            flagged=True,
            slow_key=ttfb_s,
        )

    async def _stream_sse(self, request, it, tenant_class: str, t0: float, rid: str = ""):
        """SSE the stream out. Once the response is prepared this ALWAYS
        returns it; a client disconnect must not bubble out (a second
        response would be sent) and MUST close the value iterator — that
        close is what cancels the engine request and frees its blocks."""
        from aiohttp import web

        _requests, _shed, ttfb = _ingress_metrics()
        loop = asyncio.get_event_loop()
        resp = web.StreamResponse(
            headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
            }
        )
        await resp.prepare(request)
        outcome = "ok"
        first = True
        first_dur: Optional[float] = None
        try:
            while True:
                try:
                    item = await loop.run_in_executor(self._exec, next, it, _SENTINEL)
                except Exception as e:  # noqa: BLE001 — mid-stream failure
                    outcome = "error"
                    await resp.write(
                        f"event: error\ndata: {json.dumps(repr(e))}\n\n".encode()
                    )
                    break
                if item is _SENTINEL:
                    await resp.write(b"event: done\ndata: {}\n\n")
                    break
                if first:
                    first = False
                    first_dur = time.monotonic() - t0
                    ttfb.observe(first_dur)
                    with self._lock:
                        self._inflight_t0.pop(rid, None)
                        self._recent_ttfb.append(
                            (time.monotonic(), float(first_dur))
                        )
                await resp.write(f"data: {json.dumps(item)}\n\n".encode())
            await resp.write_eof()
        except (ConnectionError, asyncio.CancelledError):
            outcome = "disconnect"  # client went away mid-stream
        finally:
            await loop.run_in_executor(None, _close_iterator, it)
            self._count(tenant_class, outcome)
            self._flight_ttfb(
                rid,
                tenant_class,
                first_dur if first_dur is not None else time.monotonic() - t0,
                outcome,
            )
        return resp

    @staticmethod
    def _shed_response(web, reason: str, retry_after: float):
        retry_after = max(0.05, float(retry_after))
        return web.json_response(
            {"error": "shed", "reason": reason, "retry_after": retry_after},
            status=429,
            headers={"Retry-After": f"{retry_after:.3f}"},
        )

    # -- introspection / serve plumbing -----------------------------------
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def _ttfb_p99(self, window_s: float = 30.0) -> float:
        """Windowed client-observed first-byte p99. Requests still
        WAITING for their first byte contribute their current age —
        a live lower bound on their eventual TTFB — so a total stall
        (every replica dead) registers immediately instead of only
        after the stalled requests finally complete."""
        now = time.monotonic()
        with self._lock:
            samples = list(self._recent_ttfb)
            pending = [now - t0 for t0 in self._inflight_t0.values()]
        vals = sorted(
            [v for ts, v in samples if now - ts <= window_s] + pending
        )
        if not vals:
            return 0.0
        idx = min(len(vals) - 1, max(0, math.ceil(0.99 * len(vals)) - 1))
        return vals[idx]

    def routing_stats(self) -> Dict[str, Any]:
        """Opts ingress replicas into the serve gossip reporter
        (serve/replica.py): the shed counter reaches ``serve.status()``
        through the same replica→controller channel the engines' queue
        stats ride — no new control-plane path. ``ttfb_p99_s`` +
        ``target`` feed the controller's SLO-autopilot burn signal for
        the target deployment (see controller._autoscale_once)."""
        ttfb = self._ttfb_p99()
        with self._lock:
            return {
                "shed_total": self._shed_total,
                "forwarded_total": self._forwarded,
                "ingress": True,
                "target": self.cfg.target,
                "ttfb_p99_s": round(ttfb, 6),
            }

    def ledger_books(self) -> Dict[str, Any]:
        """Front-door conservation books (slo.books_balanced): every
        request seen was shed, rejected as bad input, or forwarded —
        exactly one of the three, so ``seen == shed + bad_request +
        forwarded`` holds at all times (each request increments its
        bucket BEFORE the handler returns)."""
        with self._lock:
            bad = sum(
                v for k, v in self._outcomes.items()
                if k.endswith(":bad_request")
            )
            completed = sum(
                v for k, v in self._outcomes.items()
                if k.split(":", 1)[1] in ("ok", "error", "disconnect")
            )
            return {
                "kind": "ingress",
                "seen": self._seen,
                "shed": self._shed_total,
                "bad_request": bad,
                "forwarded": self._forwarded,
                "completed": completed,
                "in_flight": self._forwarded - completed,
            }

    def slo_snapshot(self) -> Dict[str, Any]:
        """SLO-ledger dump for ``serve.slo_report()``: this door
        process's flight recorder + counters (its ROUTER lives here too,
        so resumed-stream entries ride along) plus the ingress books."""
        from ray_tpu.observability import slo as _slo

        snap = _slo.snapshot()
        snap["books"] = self.ledger_books()
        snap["tier"] = "ingress"
        snap["deployment"] = self.cfg.target
        return snap

    def debug_stats(self) -> Dict[str, Any]:
        """Full counter snapshot for tests/operators: shed breakdown,
        per-class outcomes, the live pressure view, and this replica's
        router-decision / stream-resume counters (the scored-path and
        failover evidence lives in THIS process — the driver can't read
        it from its own registry)."""
        from ray_tpu.observability.rpc_metrics import (
            ROUTER_DECISIONS,
            STREAM_RESUMES,
        )

        with self._lock:
            out: Dict[str, Any] = {
                "shed_total": self._shed_total,
                "sheds": dict(self._sheds),
                "outcomes": dict(self._outcomes),
                "forwarded_total": self._forwarded,
                "tenants": sorted(self._buckets),
            }
        out["pressure"] = self._router.cluster_pressure()
        out["router_decisions"] = {
            f"{k[0]}:{k[1]}": v for k, v in ROUTER_DECISIONS._values.items()
        }
        out["stream_resumes"] = {
            k[0]: v for k, v in STREAM_RESUMES._values.items()
        }
        return out

    def check_health(self) -> bool:
        return self._thread.is_alive() and self._startup_error is None

    def stop(self) -> None:
        self._snapshot_stop.set()
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)
        self._exec.shutdown(wait=False)

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass


def _close_iterator(it) -> None:
    """Close a value iterator from a cleanup path. A generator whose
    next() is still blocked in ANOTHER executor thread (abandoned by a
    cancelled handler) raises 'generator already executing' — retry
    briefly: the pending next() returns with the next token, the frame
    suspends, and the close lands, which is what propagates the cancel
    to the engine. Best-effort after that (GC abandon is the backstop)."""
    close = getattr(it, "close", None)
    if close is None:
        return
    for _ in range(100):
        try:
            close()
            return
        except ValueError:
            time.sleep(0.05)
        except Exception:  # noqa: BLE001 — cleanup must never raise
            return


def ingress_deployment(
    target: str = "llm",
    config: Optional[IngressConfig] = None,
    *,
    name: str = "ingress",
    num_replicas: int = 1,
    max_concurrent_queries: int = 64,
    ray_actor_options: Optional[Dict[str, Any]] = None,
):
    """Build the ingress as a regular serve deployment: N replicas, each
    terminating HTTP/SSE on its own auto-assigned port (production puts
    an L4 balancer in front; tests/bench talk to replica addresses
    directly via :func:`ingress_addresses` + :func:`pick_ingress`).

    Call AFTER ``serve.run`` of the target deployment — the downstream
    ``DeploymentHandle`` is built at ``bind()`` time and pickled into
    every replica (the PR 9 handle-pickling fix is what makes this
    ≥3-process serve chain work)."""
    from ray_tpu import serve

    # the explicit ``target`` argument always names the downstream
    # deployment; the caller's config object is never mutated (one
    # IngressConfig can parameterize several doors). The door's own
    # deployment name keys bucket persistence: every replica of this
    # door shares (and a replacement restores) one tenant-bucket table.
    import dataclasses

    if config is None:
        cfg = IngressConfig(target=target, snapshot_key=name)
    else:
        cfg = dataclasses.replace(config, target=target, snapshot_key=name)
    dep = serve.deployment(
        name=name,
        num_replicas=num_replicas,
        max_concurrent_queries=max_concurrent_queries,
        ray_actor_options=dict(ray_actor_options or {"num_cpus": 0.1}),
    )(HttpIngress)

    class _BoundIngress:
        def __init__(self, inner):
            self._inner = inner

        def bind(self, **overrides):
            handle = serve.get_deployment_handle(cfg.target)
            return self._inner.bind(cfg, handle, **overrides)

        def __getattr__(self, item):
            return getattr(self._inner, item)

    return _BoundIngress(dep)


def ingress_addresses(name: str = "ingress", timeout: float = 60.0) -> List[str]:
    """``host:port`` of every READY ingress replica."""
    import ray_tpu
    from ray_tpu.serve.controller import get_or_create_controller

    controller = get_or_create_controller()
    replicas = ray_tpu.get(controller.get_replicas.remote(name), timeout=timeout)
    return [
        ray_tpu.get(
            r.handle_request.remote("address", [], {}, ""), timeout=timeout
        )
        for r in replicas
    ]


def pick_ingress(tenant: str, addresses: Sequence[str]) -> str:
    """Rendezvous-hash a tenant onto one ingress replica: the tenant's
    whole session enters through one door, whose router state (local
    load bumps + the backend prefix-affinity scorer) keeps the tenant's
    shared system prompt hot on one backend replica subset, while
    tenants as a population spread evenly across ingress replicas."""
    if not addresses:
        raise ValueError("no ingress addresses")
    import hashlib

    return max(
        addresses,
        key=lambda a: hashlib.blake2b(
            f"{tenant}|{a}".encode(), digest_size=8
        ).digest(),
    )


class IngressShedError(RuntimeError):
    """Client-side 429: the front door refused the request."""

    def __init__(self, reason: str, retry_after: float):
        super().__init__(f"shed ({reason}), retry after {retry_after:.3f}s")
        self.reason = reason
        self.retry_after = retry_after


def http_stream(
    address: str,
    request: Dict[str, Any],
    *,
    tenant: Optional[str] = None,
    timeout_s: Optional[float] = None,
    connect_timeout: float = 60.0,
) -> Iterator[Any]:
    """Minimal stdlib SSE client (tests + bench; a real client is any
    HTTP/SSE stack). Yields stream items; raises :class:`IngressShedError`
    on 429. Closing the returned generator closes the connection — the
    server observes the disconnect and cancels the engine request."""
    import urllib.error
    import urllib.request

    headers = {
        "Content-Type": "application/json",
        "Accept": "text/event-stream",
    }
    if tenant:
        headers["x-tenant-id"] = tenant
    if timeout_s is not None:
        headers["x-request-timeout-s"] = str(timeout_s)
    http_req = urllib.request.Request(
        f"http://{address}/generate",
        data=json.dumps(request).encode(),
        headers=headers,
    )
    try:
        resp = urllib.request.urlopen(http_req, timeout=connect_timeout)
    except urllib.error.HTTPError as e:
        if e.code == 429:
            try:
                info = json.loads(e.read() or b"{}")
            except Exception:  # noqa: BLE001
                info = {}
            raise IngressShedError(
                str(info.get("reason", "unknown")),
                float(e.headers.get("Retry-After") or 0.0),
            ) from None
        raise

    def _events():
        try:
            event = None
            for raw in resp:
                line = raw.decode("utf-8", "replace").rstrip("\r\n")
                if not line:
                    event = None  # blank line = event boundary
                elif line.startswith("event: "):
                    event = line[len("event: "):]
                elif line.startswith("data: "):
                    data = json.loads(line[len("data: "):])
                    if event == "error":
                        raise RuntimeError(f"ingress stream error: {data}")
                    if event == "done":
                        return
                    yield data
        finally:
            resp.close()

    return _events()
