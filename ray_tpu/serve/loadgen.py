"""Trace-driven chaos load harness (the SLO autopilot's test bench).

Replays production-shaped traffic against a multi-replica deployment —
heavy-tailed prompt/output lengths (bounded Pareto), bursty + diurnal
arrival processes (nonhomogeneous Poisson via thinning), a tenant mix
with priority classes, and shared-prefix populations that exercise the
radix cache — while the three seeded fault plans (``RpcFaultPlan``,
``DataFaultPlan``, ``ReplicaFaultPlan``, armed through the ONE master
chaos seed) inject kills, stalls and data-plane corruption underneath.

Determinism contract: :func:`build_trace` is a PURE function of the
:class:`LoadSpec` — every draw comes from one ``random.Random(seed)``
stream in a fixed order, so the same spec is bit-identical arrivals,
tenants, prompts and output lengths, run after run. Together with the
master chaos seed (``util/chaos.py::derive_plan_seed``) a whole harness
run — traffic AND fault schedule — reproduces from one logged line
(:func:`repro_line`).

Scoring (:func:`score`) turns a run into SLO attainment: TTFT/ITL
p99/p99.9 against budgets, goodput fraction from ``serve.slo_report()``,
autoscaler lag from the controller's ``last_scale`` stamp, and every
miss attributed to a named stage via the flight recorder
(:func:`attribute_misses`).

Methodology follows the Ray paper's fault-recovery-under-load runs
(arXiv:1712.05889 §5.4) and the goodput-per-chip serving-economics
framing of arXiv:2605.25645."""

from __future__ import annotations

import math
import random
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence

from ray_tpu.serve.ingress import IngressShedError, http_stream, pick_ingress

#: tenant classes in priority order (must mirror ingress CLASS_PRIORITY)
_CLASSES = ("interactive", "standard", "batch")


@dataclass
class LoadSpec:
    """Everything a harness run is a function of. One seed, one line."""

    seed: int = 1
    #: trace horizon (scheduled-arrival seconds, before ``time_scale``)
    duration_s: float = 10.0
    # -- arrival process --------------------------------------------------
    base_rate_rps: float = 8.0
    #: diurnal swing: rate *= 1 + amplitude*sin(2πt/period) (clamped <1)
    diurnal_period_s: float = 60.0
    diurnal_amplitude: float = 0.5
    #: bursts: rate *= burst_factor during the first ``burst_duty``
    #: fraction of every ``burst_period_s`` window
    burst_period_s: float = 20.0
    burst_duty: float = 0.2
    burst_factor: float = 3.0
    # -- tenant mix -------------------------------------------------------
    n_tenants: int = 8
    #: zipf-ish per-tenant traffic share: weight_i = 1/(i+1)^a
    tenant_zipf_a: float = 1.2
    class_weights: Dict[str, float] = field(
        default_factory=lambda: {"interactive": 0.5, "standard": 0.3, "batch": 0.2}
    )
    # -- request shapes (bounded Pareto) ----------------------------------
    prompt_alpha: float = 1.3
    prompt_min: int = 4
    prompt_max: int = 64
    output_alpha: float = 1.5
    output_min: int = 2
    output_max: int = 32
    # -- shared-prefix populations (radix-cache exercise) -----------------
    n_prefixes: int = 4
    prefix_len: int = 12
    #: probability a request leads with its tenant group's shared prefix
    prefix_reuse: float = 0.7
    vocab: int = 250
    # -- seeded chaos (injected while the trace replays) ------------------
    #: master seed: derives every armed plan's seed (util/chaos.py)
    chaos_master_seed: int = 0
    rpc_chaos: str = ""
    pull_chaos: str = ""
    replica_chaos: str = ""


@dataclass
class TraceRequest:
    index: int
    t_s: float  # scheduled arrival, seconds from run start
    tenant: str
    tenant_class: str
    prompt: List[int]
    max_new_tokens: int
    request_id: str


def _bounded_pareto(rnd: random.Random, alpha: float, lo: int, hi: int) -> int:
    """Inverse-CDF bounded Pareto draw — the heavy-tailed length
    distribution production prompt/output sizes actually follow."""
    lo_f, hi_f = float(lo), float(max(lo, hi))
    if hi_f <= lo_f:
        return int(lo_f)
    u = rnd.random()
    ratio = (lo_f / hi_f) ** alpha
    x = lo_f / (1.0 - u * (1.0 - ratio)) ** (1.0 / alpha)
    return max(lo, min(int(hi), int(x)))


def _rate_at(spec: LoadSpec, t: float) -> float:
    amp = min(0.95, max(0.0, spec.diurnal_amplitude))
    rate = spec.base_rate_rps * (
        1.0 + amp * math.sin(2.0 * math.pi * t / max(spec.diurnal_period_s, 1e-6))
    )
    if spec.burst_period_s > 0 and (
        t % spec.burst_period_s
    ) < spec.burst_duty * spec.burst_period_s:
        rate *= spec.burst_factor
    return max(rate, 1e-9)


def build_trace(spec: LoadSpec) -> List[TraceRequest]:
    """The full request schedule, as a pure function of the spec. Draw
    order is part of the replay contract — do not reorder the RNG
    consumption below."""
    rnd = random.Random(spec.seed)
    # tenant population: class per tenant, zipf traffic weights, shared
    # prefix per tenant GROUP (tenants i, i+n_prefixes, ... share one)
    classes = [c for c in _CLASSES if spec.class_weights.get(c, 0.0) > 0.0]
    cweights = [spec.class_weights[c] for c in classes]
    tenants = [f"t{i:02d}" for i in range(max(1, spec.n_tenants))]
    tenant_class = {t: rnd.choices(classes, weights=cweights)[0] for t in tenants}
    tweights = [1.0 / (i + 1) ** spec.tenant_zipf_a for i in range(len(tenants))]
    n_groups = max(1, spec.n_prefixes)
    prefixes = [
        [rnd.randrange(1, max(2, spec.vocab)) for _ in range(spec.prefix_len)]
        for _ in range(n_groups)
    ]
    # arrivals: nonhomogeneous Poisson by thinning against the peak rate
    peak = (
        spec.base_rate_rps
        * (1.0 + min(0.95, max(0.0, spec.diurnal_amplitude)))
        * max(1.0, spec.burst_factor)
    )
    trace: List[TraceRequest] = []
    t = 0.0
    while True:
        t += rnd.expovariate(peak)
        if t >= spec.duration_s:
            break
        if rnd.random() >= _rate_at(spec, t) / peak:
            continue  # thinned (the draw still happened — determinism)
        i = len(trace)
        tenant_i = rnd.choices(range(len(tenants)), weights=tweights)[0]
        tenant = tenants[tenant_i]
        n_prompt = _bounded_pareto(
            rnd, spec.prompt_alpha, spec.prompt_min, spec.prompt_max
        )
        use_prefix = rnd.random() < spec.prefix_reuse
        fresh = max(1, n_prompt - (spec.prefix_len if use_prefix else 0))
        tail = [rnd.randrange(1, max(2, spec.vocab)) for _ in range(fresh)]
        prompt = (
            list(prefixes[tenant_i % n_groups]) + tail if use_prefix else tail
        )
        trace.append(
            TraceRequest(
                index=i,
                t_s=t,
                tenant=tenant,
                tenant_class=tenant_class[tenant],
                prompt=prompt,
                max_new_tokens=_bounded_pareto(
                    rnd, spec.output_alpha, spec.output_min, spec.output_max
                ),
                request_id=f"lg{spec.seed:x}-{i:05d}",
            )
        )
    return trace


# -- chaos plumbing (one logged line reproduces the whole run) -------------
def chaos_env(spec: LoadSpec) -> Dict[str, str]:
    """The env vars that arm this spec's fault plans — export them
    BEFORE ``ray_tpu.init`` so replica processes inherit the plans."""
    env: Dict[str, str] = {}
    if spec.chaos_master_seed:
        env["RAY_TPU_testing_chaos_seed"] = str(int(spec.chaos_master_seed))
    for knob, value in (
        ("testing_rpc_chaos", spec.rpc_chaos),
        ("testing_pull_chaos", spec.pull_chaos),
        ("testing_replica_chaos", spec.replica_chaos),
    ):
        if value:
            env["RAY_TPU_" + knob] = value
    return env


def repro_line(spec: LoadSpec) -> str:
    """ONE line that replays the run: chaos env + the trace seed."""
    parts = [f"{k}={v}" for k, v in sorted(chaos_env(spec).items())]
    parts.append(f"LOADGEN_SEED={spec.seed}")
    return " ".join(parts)


# -- replay ----------------------------------------------------------------
@dataclass
class HarnessRun:
    spec: LoadSpec
    records: List[Dict[str, Any]]
    itl_gaps: List[float]
    started_wall: float
    duration_s: float
    #: (t_rel_s, serve.status() dict) samples when a status_fn was given
    samples: List[Any] = field(default_factory=list)


def run_trace(
    trace: Sequence[TraceRequest],
    *,
    spec: Optional[LoadSpec] = None,
    addresses: Optional[Sequence[str]] = None,
    stream_fn: Optional[Callable[[TraceRequest], Iterable[Any]]] = None,
    time_scale: float = 1.0,
    max_workers: int = 32,
    timeout_s: float = 30.0,
    status_fn: Optional[Callable[[], Any]] = None,
    status_period_s: float = 0.5,
) -> HarnessRun:
    """Replay a built trace against the serving stack: each request
    fires at ``t_s * time_scale`` after run start — through the tenant's
    rendezvous-hashed ingress door (``addresses``) or an injected
    ``stream_fn`` (unit tests). Client-side TTFT/ITL/e2e are measured
    per request; an optional ``status_fn`` (e.g. ``serve.status``) is
    sampled on a timer for target-timeline/autoscaler-lag scoring."""
    if addresses is None and stream_fn is None:
        raise ValueError("run_trace needs addresses or a stream_fn")
    records: List[Optional[Dict[str, Any]]] = [None] * len(trace)
    gaps: List[float] = []
    samples: List[Any] = []
    lock = threading.Lock()
    start = time.monotonic()
    started_wall = time.time()
    done = threading.Event()

    def _sample_loop() -> None:
        while not done.is_set():
            try:
                samples.append((time.monotonic() - start, status_fn()))
            except Exception:  # noqa: BLE001 — status poll must not kill the run
                pass
            done.wait(status_period_s)

    def _one(req: TraceRequest) -> None:
        rec: Dict[str, Any] = {
            "request_id": req.request_id,
            "tenant": req.tenant,
            "tenant_class": req.tenant_class,
            "t_s": req.t_s,
            "prompt_tokens": len(req.prompt),
            "max_new_tokens": req.max_new_tokens,
        }
        delay = start + req.t_s * time_scale - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        sent = time.monotonic()
        rec["sent_at_s"] = sent - start
        try:
            if stream_fn is not None:
                it = iter(stream_fn(req))
            else:
                addr = pick_ingress(req.tenant, addresses)
                it = http_stream(
                    addr,
                    {
                        "prompt": req.prompt,
                        "max_new_tokens": req.max_new_tokens,
                        "request_id": req.request_id,
                    },
                    tenant=req.tenant,
                    timeout_s=timeout_s,
                )
            ttft: Optional[float] = None
            itl_max = 0.0
            last = sent
            n = 0
            for _tok in it:
                now = time.monotonic()
                if ttft is None:
                    ttft = now - sent
                else:
                    gap = now - last
                    itl_max = max(itl_max, gap)
                    with lock:
                        gaps.append(gap)
                last = now
                n += 1
            rec.update(
                outcome="ok",
                ttft_s=ttft,
                itl_max_s=itl_max,
                n_tokens=n,
                e2e_s=time.monotonic() - sent,
            )
        except IngressShedError as e:
            rec.update(outcome="shed", shed_reason=e.reason)
        except Exception as e:  # noqa: BLE001 — a failed request is a data point
            rec.update(outcome="error", error=repr(e))
        records[req.index] = rec

    sampler = None
    if status_fn is not None:
        sampler = threading.Thread(target=_sample_loop, daemon=True)
        sampler.start()
    try:
        with ThreadPoolExecutor(max_workers=max_workers) as pool:
            list(pool.map(_one, trace))
    finally:
        done.set()
        if sampler is not None:
            sampler.join(timeout=2.0)
    return HarnessRun(
        spec=spec or LoadSpec(),
        records=[r for r in records if r is not None],
        itl_gaps=gaps,
        started_wall=started_wall,
        duration_s=time.monotonic() - start,
        samples=samples,
    )


# -- scoring ---------------------------------------------------------------
def _pct(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, int(math.ceil(q * len(sorted_vals))) - 1))
    return sorted_vals[idx]


def attribute_misses(
    records: Sequence[Dict[str, Any]],
    report: Optional[Dict[str, Any]],
    ttft_slo_s: float,
) -> Dict[str, Dict[str, Any]]:
    """request_id -> {outcome, ttft_s, stage, flags} for every SLO miss
    (TTFT over budget, or an outright error), joined against the flight
    recorder's per-request slowest-stage breakdown. ``"untracked"``
    means the recorder's ring had already evicted the request — raise
    ``slo_flight_recorder_slots`` for gated runs that must attribute
    every miss."""
    flights = {
        str(f.get("request_id")): f
        for f in (report or {}).get("flight_recorder") or []
    }
    out: Dict[str, Dict[str, Any]] = {}
    for r in records:
        missed = r.get("outcome") == "error" or (
            r.get("outcome") == "ok"
            and float(r.get("ttft_s") or 0.0) > ttft_slo_s
        )
        if not missed:
            continue
        f = flights.get(str(r["request_id"])) or {}
        out[str(r["request_id"])] = {
            "outcome": r.get("outcome"),
            "ttft_s": r.get("ttft_s"),
            "stage": f.get("slowest_stage") or "untracked",
            "flags": f.get("flags") or [],
        }
    return out


def score(
    run: HarnessRun,
    *,
    ttft_slo_s: float,
    itl_slo_s: Optional[float] = None,
    report: Optional[Dict[str, Any]] = None,
    status: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """SLO attainment for one run. Attainment counts errors as misses
    (a dead request never met its budget) and excludes shed requests
    (the door's explicit no — rate-limited/overloaded tenants are
    accounted separately, and a shed well-behaved tenant shows up in
    the ``by_class`` shed counts, not as silent forgiveness)."""
    ok = [r for r in run.records if r.get("outcome") == "ok"]
    shed = [r for r in run.records if r.get("outcome") == "shed"]
    errors = [r for r in run.records if r.get("outcome") == "error"]
    ttfts = sorted(float(r["ttft_s"]) for r in ok if r.get("ttft_s") is not None)
    e2es = sorted(float(r["e2e_s"]) for r in ok if r.get("e2e_s") is not None)
    gaps = sorted(run.itl_gaps)
    served = len(ok) + len(errors)
    attained = sum(
        1
        for r in ok
        if r.get("ttft_s") is not None and float(r["ttft_s"]) <= ttft_slo_s
    )
    out: Dict[str, Any] = {
        "requests": len(run.records),
        "ok": len(ok),
        "shed": len(shed),
        "errors": len(errors),
        "duration_s": round(run.duration_s, 3),
        "ttft": {
            "p50": _pct(ttfts, 0.50),
            "p99": _pct(ttfts, 0.99),
            "p999": _pct(ttfts, 0.999),
        },
        "itl": {
            "p50": _pct(gaps, 0.50),
            "p99": _pct(gaps, 0.99),
            "p999": _pct(gaps, 0.999),
        },
        "e2e_p99": _pct(e2es, 0.99),
        "ttft_slo_s": ttft_slo_s,
        "ttft_attainment": attained / served if served else 1.0,
        "by_class": {},
        "repro": repro_line(run.spec),
    }
    if itl_slo_s is not None:
        out["itl_slo_s"] = itl_slo_s
        out["itl_attainment"] = (
            sum(1 for g in gaps if g <= itl_slo_s) / len(gaps) if gaps else 1.0
        )
    for cls in _CLASSES:
        crecs = [r for r in run.records if r.get("tenant_class") == cls]
        if not crecs:
            continue
        cok = [r for r in crecs if r.get("outcome") == "ok"]
        cserved = len(cok) + sum(1 for r in crecs if r.get("outcome") == "error")
        cattained = sum(
            1
            for r in cok
            if r.get("ttft_s") is not None and float(r["ttft_s"]) <= ttft_slo_s
        )
        out["by_class"][cls] = {
            "requests": len(crecs),
            "shed": sum(1 for r in crecs if r.get("outcome") == "shed"),
            "errors": sum(1 for r in crecs if r.get("outcome") == "error"),
            "ttft_attainment": cattained / cserved if cserved else 1.0,
        }
    if report is not None:
        deps = report.get("deployments") or {}
        for name, block in deps.items():
            if "goodput_fraction" in block:
                out.setdefault("goodput_fraction", {})[name] = block[
                    "goodput_fraction"
                ]
        out["miss_attribution"] = attribute_misses(
            run.records, report, ttft_slo_s
        )
    if status is not None:
        # autoscaler lag: run start -> the first APPLIED scale-out, from
        # the controller's wall-clock last_scale stamp
        lags = [
            float(blk["last_scale"]["ts"]) - run.started_wall
            for blk in status.values()
            if blk.get("last_scale")
            and blk["last_scale"].get("to", 0) > blk["last_scale"].get("from", 0)
            and float(blk["last_scale"].get("ts", 0.0)) >= run.started_wall
        ]
        out["autoscaler_lag_s"] = round(min(lags), 3) if lags else None
    return out


__all__ = [
    "HarnessRun",
    "LoadSpec",
    "TraceRequest",
    "attribute_misses",
    "build_trace",
    "chaos_env",
    "repro_line",
    "run_trace",
    "score",
]
