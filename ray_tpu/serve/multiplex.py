"""Model multiplexing: many models LRU-cached across a replica pool.

Reference: ``python/ray/serve/multiplex.py:22`` (``_ModelMultiplexWrapper``)
+ model-aware routing in ``replica_scheduler``: a replica holds up to
``max_num_models_per_replica`` models; requests carry a model id; the
router prefers replicas that already have the model loaded (avoiding a
cold load), falling back to pow-2 among all replicas (the chosen one
then loads + possibly evicts LRU).

TPU framing: one replica process pins the base weights on its chip and
hot-swaps LoRA/adapter deltas — the LRU wrapper is the adapter cache.

    @serve.deployment
    class LLM:
        @serve.multiplexed(max_num_models_per_replica=3)
        async def get_model(self, model_id: str):
            return load_adapter(model_id)

        async def __call__(self, prompt):
            model = await self.get_model(serve.get_multiplexed_model_id())
            return model(prompt)
"""

from __future__ import annotations

import asyncio
import contextvars
import inspect
import threading
from collections import OrderedDict
from typing import Any, Callable, List, Optional

_model_id_ctx: contextvars.ContextVar = contextvars.ContextVar(
    "serve_multiplexed_model_id", default=""
)

_MODELS_ATTR = "__serve_multiplex_models__"


class _Loading:
    """In-flight-load placeholder in the model cache (dedups concurrent
    cold loads of one model)."""

    def __init__(self):
        self.done = threading.Event()


def get_multiplexed_model_id() -> str:
    """The model id of the request being handled (set by the replica
    from the handle/proxy-supplied id; reference
    ``serve.get_multiplexed_model_id``)."""
    return _model_id_ctx.get()


def loaded_model_ids(callable_obj: Any) -> List[str]:
    """Model ids currently cached on a replica's callable (the router's
    model-locality signal, newest last)."""
    models = getattr(callable_obj, _MODELS_ATTR, None)
    if not models:
        return []
    return [k for k, v in models.items() if not isinstance(v, _Loading)]


class multiplexed:
    """Decorator for the model-loader method (``@serve.multiplexed``).

    The wrapped loader becomes an async LRU cache keyed by model id:
    a hit refreshes recency; a miss calls the user loader and evicts the
    least-recently-used model beyond ``max_num_models_per_replica``
    (calling the evicted model's ``__del__`` implicitly by dropping the
    reference)."""

    def __init__(self, _fn: Optional[Callable] = None, *, max_num_models_per_replica: int = 3):
        self._fn = None
        self.max_models = max_num_models_per_replica
        if callable(_fn):
            self._fn = _fn

    def __call__(self, fn: Callable) -> "multiplexed":
        self._fn = fn
        return self

    def __set_name__(self, owner, name):
        self._name = name

    def __get__(self, instance, owner=None):
        if instance is None:
            return self
        return _BoundMultiplexLoader(self._fn, instance, self.max_models)


class _BoundMultiplexLoader:
    """Per-instance bound loader; model cache lives on the instance so
    the replica can report loaded ids."""

    def __init__(self, fn, instance, max_models: int):
        self._fn = fn
        self._instance = instance
        self._max = max(1, max_models)
        if not hasattr(instance, _MODELS_ATTR):
            setattr(instance, _MODELS_ATTR, OrderedDict())
            setattr(instance, _MODELS_ATTR + "_lock", threading.Lock())

    def _cache(self) -> OrderedDict:
        return getattr(self._instance, _MODELS_ATTR)

    def _lock(self):
        return getattr(self._instance, _MODELS_ATTR + "_lock")

    async def __call__(self, model_id: Optional[str] = None):
        if model_id is None:
            model_id = get_multiplexed_model_id()
        if not model_id:
            raise ValueError(
                "no model id: pass one explicitly or set "
                "multiplexed_model_id on the handle/request"
            )
        cache = self._cache()
        loop = asyncio.get_event_loop()
        while True:
            with self._lock():
                if model_id in cache:
                    entry = cache[model_id]
                    if not isinstance(entry, _Loading):
                        cache.move_to_end(model_id)
                        return entry
                    pending = entry
                else:
                    # claim the load: concurrent requests for the same
                    # cold model must NOT each run the (expensive,
                    # device-memory-hungry) loader
                    pending = None
                    cache[model_id] = _Loading()
            if pending is not None:
                # another request is loading it — wait off-loop
                await loop.run_in_executor(None, pending.done.wait)
                continue  # re-check (load may have failed/been evicted)
            break
        marker = cache[model_id]
        try:
            result = self._fn(self._instance, model_id)
            if inspect.iscoroutine(result):
                result = await result
        except BaseException:
            with self._lock():
                if cache.get(model_id) is marker:
                    del cache[model_id]
            marker.done.set()
            raise
        with self._lock():
            cache[model_id] = result
            cache.move_to_end(model_id)
            while len(cache) > self._max:
                evict_id = next(
                    (k for k, v in cache.items() if not isinstance(v, _Loading)),
                    None,
                )
                if evict_id is None:
                    break  # everything in flight — nothing evictable
                del cache[evict_id]  # evict LRU — ref drop unloads
        marker.done.set()
        return result

    def load_sync(self, model_id: Optional[str] = None):
        """Synchronous entry for sync callables."""
        coro = self(model_id)
        try:
            asyncio.get_running_loop()
        except RuntimeError:
            return asyncio.run(coro)
        raise RuntimeError(
            "load_sync called from an async context — await the loader"
        )
