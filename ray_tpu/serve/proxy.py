"""HTTP proxy: aiohttp server routing requests to deployments.

Reference: ``serve/_private/proxy.py`` (uvicorn/starlette ASGI proxy +
``proxy_router``). Here: one aiohttp app per node (started on demand by
``serve.start_http``), routes ``{route_prefix}`` → deployment via the
controller's routing table, JSON in/out."""

from __future__ import annotations

import asyncio
import json
import threading
from typing import Any, Dict, Optional

import ray_tpu
from ray_tpu.serve.router import Router

_proxy = None
_lock = threading.Lock()
_SENTINEL = object()


class HttpProxy:
    def __init__(self, controller, host: str = "127.0.0.1", port: int = 8000):
        self._controller = controller
        self._routers: Dict[str, Router] = {}
        self.host = host
        self.port = port
        self._started = threading.Event()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread = threading.Thread(target=self._serve, daemon=True, name="serve-proxy")
        self._thread.start()
        if not self._started.wait(15):
            raise RuntimeError("http proxy failed to start")

    def _router_for(self, deployment: str) -> Router:
        r = self._routers.get(deployment)
        if r is None:
            r = self._routers[deployment] = Router(self._controller, deployment)
        return r

    def _routes_cached(self) -> Dict[str, str]:
        import time

        now = time.monotonic()
        if now - getattr(self, "_routes_ts", 0.0) > 1.0:
            self._routes = ray_tpu.get(self._controller.routes.remote(), timeout=30)
            self._routes_ts = now
        return self._routes

    async def _handle(self, request):
        from aiohttp import web

        loop = asyncio.get_event_loop()
        # the controller RPC blocks — never run it on the proxy loop (one
        # slow controller would freeze ALL in-flight HTTP traffic)
        routes = await loop.run_in_executor(None, self._routes_cached)
        path = request.path
        target = None
        for prefix, name in sorted(routes.items(), key=lambda kv: -len(kv[0])):
            if path == prefix or path.startswith(prefix.rstrip("/") + "/"):
                target = name
                break
        if target is None:
            return web.json_response({"error": f"no route for {path}"}, status=404)
        model_id = request.headers.get("serve-multiplexed-model-id", "")
        streaming = "text/event-stream" in request.headers.get("Accept", "")
        try:
            body: Any = None
            if request.can_read_body:
                raw = await request.read()
                if raw:
                    try:
                        body = json.loads(raw)
                    except json.JSONDecodeError:
                        body = raw.decode()
            router = self._router_for(target)
            loop = asyncio.get_event_loop()
            if streaming:
                return await self._handle_stream(
                    request, router, body, model_id
                )
            # retry-until-executed: replica death mid-rolling-update must
            # not surface to the HTTP client (reference router semantics)
            result = await loop.run_in_executor(
                None,
                lambda: router.execute(
                    "__call__", (body,), {}, model_id=model_id, timeout=60
                ),
            )
            if isinstance(result, Exception):
                raise result
            return web.json_response({"result": result})
        except Exception as e:  # noqa: BLE001
            return web.json_response({"error": repr(e)}, status=500)

    async def _handle_stream(self, request, router, body, model_id):
        """SSE: each yielded item becomes one ``data:`` event (reference
        gRPC/HTTP streaming proxy responses, proxy.py:536). Once the
        response is prepared this method ALWAYS returns it — a client
        disconnect mid-stream must not bubble to the outer handler
        (which would try to send a second response) and must close the
        value generator so the replica stops producing."""
        from aiohttp import web

        loop = asyncio.get_event_loop()
        values = await loop.run_in_executor(
            None,
            lambda: router.execute_stream(
                "__call__", (body,), {}, model_id=model_id, timeout=60
            ),
        )
        resp = web.StreamResponse(
            headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
            }
        )
        await resp.prepare(request)
        it = iter(values)
        try:
            while True:
                try:
                    item = await loop.run_in_executor(None, next, it, _SENTINEL)
                except Exception as e:  # noqa: BLE001 — mid-stream failure
                    await resp.write(
                        f"event: error\ndata: {json.dumps(repr(e))}\n\n".encode()
                    )
                    break
                if item is _SENTINEL:
                    break
                await resp.write(f"data: {json.dumps(item)}\n\n".encode())
            await resp.write_eof()
        except (ConnectionError, asyncio.CancelledError):
            pass  # client went away mid-stream
        finally:
            close = getattr(it, "close", None)
            if close is not None:
                await loop.run_in_executor(None, close)
        return resp

    def _serve(self) -> None:
        from aiohttp import web

        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop
        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self._handle)
        runner = web.AppRunner(app)

        async def _start():
            await runner.setup()
            site = web.TCPSite(runner, self.host, self.port)
            await site.start()
            self._started.set()

        loop.run_until_complete(_start())
        loop.run_forever()

    def stop(self) -> None:
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)


def start_http(controller, host: str = "127.0.0.1", port: int = 8000) -> HttpProxy:
    global _proxy
    with _lock:
        if _proxy is None:
            _proxy = HttpProxy(controller, host, port)
        return _proxy


def stop_http() -> None:
    global _proxy
    with _lock:
        if _proxy is not None:
            _proxy.stop()
            _proxy = None
