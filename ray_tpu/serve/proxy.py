"""HTTP proxy: aiohttp server routing requests to deployments.

Reference: ``serve/_private/proxy.py`` (uvicorn/starlette ASGI proxy +
``proxy_router``). Here: one aiohttp app per node (started on demand by
``serve.start_http``), routes ``{route_prefix}`` → deployment via the
controller's routing table, JSON in/out."""

from __future__ import annotations

import asyncio
import json
import threading
from typing import Any, Dict, Optional

import ray_tpu
from ray_tpu.serve.router import Router

_proxy = None
_lock = threading.Lock()


class HttpProxy:
    def __init__(self, controller, host: str = "127.0.0.1", port: int = 8000):
        self._controller = controller
        self._routers: Dict[str, Router] = {}
        self.host = host
        self.port = port
        self._started = threading.Event()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread = threading.Thread(target=self._serve, daemon=True, name="serve-proxy")
        self._thread.start()
        if not self._started.wait(15):
            raise RuntimeError("http proxy failed to start")

    def _router_for(self, deployment: str) -> Router:
        r = self._routers.get(deployment)
        if r is None:
            r = self._routers[deployment] = Router(self._controller, deployment)
        return r

    def _routes_cached(self) -> Dict[str, str]:
        import time

        now = time.monotonic()
        if now - getattr(self, "_routes_ts", 0.0) > 1.0:
            self._routes = ray_tpu.get(self._controller.routes.remote(), timeout=30)
            self._routes_ts = now
        return self._routes

    async def _handle(self, request):
        from aiohttp import web

        loop = asyncio.get_event_loop()
        # the controller RPC blocks — never run it on the proxy loop (one
        # slow controller would freeze ALL in-flight HTTP traffic)
        routes = await loop.run_in_executor(None, self._routes_cached)
        path = request.path
        target = None
        for prefix, name in sorted(routes.items(), key=lambda kv: -len(kv[0])):
            if path == prefix or path.startswith(prefix.rstrip("/") + "/"):
                target = name
                break
        if target is None:
            return web.json_response({"error": f"no route for {path}"}, status=404)
        try:
            body: Any = None
            if request.can_read_body:
                raw = await request.read()
                if raw:
                    try:
                        body = json.loads(raw)
                    except json.JSONDecodeError:
                        body = raw.decode()
            router = self._router_for(target)
            loop = asyncio.get_event_loop()
            ref = await loop.run_in_executor(
                None, lambda: router.dispatch("__call__", (body,), {})
            )
            result = await loop.run_in_executor(
                None, lambda: ray_tpu.get(ref, timeout=60)
            )
            if isinstance(result, Exception):
                raise result
            return web.json_response({"result": result})
        except Exception as e:  # noqa: BLE001
            return web.json_response({"error": repr(e)}, status=500)

    def _serve(self) -> None:
        from aiohttp import web

        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop
        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self._handle)
        runner = web.AppRunner(app)

        async def _start():
            await runner.setup()
            site = web.TCPSite(runner, self.host, self.port)
            await site.start()
            self._started.set()

        loop.run_until_complete(_start())
        loop.run_forever()

    def stop(self) -> None:
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)


def start_http(controller, host: str = "127.0.0.1", port: int = 8000) -> HttpProxy:
    global _proxy
    with _lock:
        if _proxy is None:
            _proxy = HttpProxy(controller, host, port)
        return _proxy


def stop_http() -> None:
    global _proxy
    with _lock:
        if _proxy is not None:
            _proxy.stop()
            _proxy = None
