"""Replica actor: hosts one copy of the user callable.

Reference: ``serve/_private/replica.py`` — wraps the deployment's
class (or function), counts ongoing requests (the router's pow-2 signal
and the autoscaler's input), supports sync, async, and STREAMING
callables (generator/async-generator methods stream tokens back through
the runtime's streaming-generator path), plus model multiplexing
(``multiplex.py:22``): requests carry a model id, the replica reports
its loaded ids so the router can route model-local."""

from __future__ import annotations

import asyncio
import inspect
import threading
from typing import Any

import ray_tpu
from ray_tpu.core.config import GLOBAL_CONFIG
from ray_tpu.serve.multiplex import _model_id_ctx, loaded_model_ids


class _Replica:
    """Defined undecorated so cloudpickle exports by module reference
    (see tune/trial.py for the rationale)."""

    def __init__(self, cls_or_fn, init_args, init_kwargs, deployment: str = "", controller_namespace=None):
        if inspect.isclass(cls_or_fn):
            self._callable = cls_or_fn(*init_args, **(init_kwargs or {}))
        else:
            self._callable = cls_or_fn
        self._ongoing = 0
        self._total = 0
        self._deployment = deployment
        self._controller_namespace = controller_namespace
        self._reported_models: list = []
        # SLO-ledger label hook: a callable that wants its metrics
        # labeled by deployment (the LLM engine's TTFT/ITL/e2e series)
        # learns its name here, BEFORE any request can arrive
        if deployment and hasattr(self._callable, "set_deployment_name"):
            try:
                self._callable.set_deployment_name(deployment)
            except Exception:  # noqa: BLE001 — labeling must not fail init
                pass
        # routing-stats gossip (cache-affinity routing): a callable that
        # exposes routing_stats() gets a reporter thread pushing load +
        # prefix digest to the controller on a timer — request-driven
        # reporting alone would leave an IDLE replica invisible to the
        # scored router (fresh stats are the fallback gate), so a cold
        # scale-up replica would never attract traffic
        self._stats_stop = threading.Event()
        if (
            deployment
            and hasattr(self._callable, "routing_stats")
            and GLOBAL_CONFIG.serve_replica_stats_period_s > 0
        ):
            threading.Thread(
                target=self._stats_report_loop,
                daemon=True,
                name=f"replica-stats-{deployment}",
            ).start()

    def _stats_report_loop(self) -> None:
        period = GLOBAL_CONFIG.serve_replica_stats_period_s
        controller = None
        me = ""
        while not self._stats_stop.wait(period):
            try:
                if controller is None:
                    from ray_tpu.serve.controller import CONTROLLER_NAME

                    me = ray_tpu.get_runtime_context().get_actor_id() or ""
                    controller = ray_tpu.get_actor(
                        CONTROLLER_NAME, namespace=self._controller_namespace
                    )
                stats = dict(self._callable.routing_stats())
                stats["ongoing"] = self._ongoing
                controller.report_replica_stats.remote(
                    self._deployment, me, stats
                )
            except Exception:
                # controller briefly unreachable (failover, startup
                # race): drop this tick, keep the loop alive
                controller = None

    def _resolve(self, method: str):
        if method == "__call__":
            return self._callable
        return getattr(self._callable, method)

    def _maybe_report_models(self) -> None:
        """Push the loaded-model set to the controller when it changes
        (reference: multiplexed model ids flow replica -> controller ->
        routers through the long-poll, so model-local routing reacts to
        loads/evictions immediately, not on a stats-poll TTL)."""
        models = loaded_model_ids(self._callable)
        if models == self._reported_models or not self._deployment:
            return
        try:
            from ray_tpu.serve.controller import CONTROLLER_NAME

            me = ray_tpu.get_runtime_context().get_actor_id() or ""
            controller = ray_tpu.get_actor(
                CONTROLLER_NAME, namespace=self._controller_namespace
            )
            controller.report_models.remote(self._deployment, me, models)
        except Exception:
            # controller briefly unreachable: leave _reported_models
            # unchanged so the NEXT request retries the report
            return
        self._reported_models = list(models)

    async def handle_request(self, method: str, args, kwargs, model_id: str = "") -> Any:
        self._ongoing += 1
        self._total += 1
        token = _model_id_ctx.set(model_id) if model_id else None
        try:
            fn = self._resolve(method)
            if inspect.iscoroutinefunction(fn) or (
                not inspect.isfunction(fn)
                and not inspect.ismethod(fn)
                and inspect.iscoroutinefunction(getattr(fn, "__call__", None))
            ):
                return await fn(*args, **(kwargs or {}))
            # Sync callables run on a worker thread: executing them inline
            # would block this actor's single async loop and serialize all
            # max_concurrent_queries requests (and starve stats()).
            loop = asyncio.get_event_loop()
            ctx = _model_id_ctx.get()

            def _call():
                t = _model_id_ctx.set(ctx)
                try:
                    return fn(*args, **(kwargs or {}))
                finally:
                    _model_id_ctx.reset(t)

            result = await loop.run_in_executor(None, _call)
            if inspect.iscoroutine(result):
                result = await result
            return result
        finally:
            if token is not None:
                _model_id_ctx.reset(token)
            self._ongoing -= 1
            if model_id:
                self._maybe_report_models()

    def handle_request_streaming(self, method: str, args, kwargs, model_id: str = ""):
        """Generator entry: invoked with ``num_returns="streaming"`` so
        every yielded item streams to the caller immediately (reference:
        streaming replica responses, ``replica.py`` + the
        ObjectRefStream protocol). Runs on a lane thread — blocking
        user generators don't stall the actor's async loop."""
        self._ongoing += 1
        self._total += 1
        token = _model_id_ctx.set(model_id) if model_id else None
        try:
            fn = self._resolve(method)
            out = fn(*args, **(kwargs or {}))
            if inspect.isasyncgen(out):
                from ray_tpu.core.task_executor import _drain_async_gen

                yield from _drain_async_gen(out)
            elif inspect.isgenerator(out) or hasattr(out, "__iter__"):
                yield from out
            else:
                raise TypeError(
                    f"streaming call to {method!r} needs a generator/"
                    f"iterable return, got {type(out).__name__}"
                )
        finally:
            if token is not None:
                _model_id_ctx.reset(token)
            self._ongoing -= 1
            if model_id:
                self._maybe_report_models()

    def stats(self):
        return {
            "ongoing": self._ongoing,
            "total": self._total,
            "models": loaded_model_ids(self._callable),
        }

    def health(self) -> bool:
        check = getattr(self._callable, "check_health", None)
        if check is None:
            return True
        result = check()
        return bool(result) if not inspect.iscoroutine(result) else True


Replica = ray_tpu.remote(_Replica)
