"""Replica actor: hosts one copy of the user callable.

Reference: ``serve/_private/replica.py`` — wraps the deployment's
class (or function), counts ongoing requests (the router's pow-2 signal
and the autoscaler's input), supports sync and async callables."""

from __future__ import annotations

import asyncio
import inspect
from typing import Any

import ray_tpu


class _Replica:
    """Defined undecorated so cloudpickle exports by module reference
    (see tune/trial.py for the rationale)."""

    def __init__(self, cls_or_fn, init_args, init_kwargs):
        if inspect.isclass(cls_or_fn):
            self._callable = cls_or_fn(*init_args, **(init_kwargs or {}))
        else:
            self._callable = cls_or_fn
        self._ongoing = 0
        self._total = 0

    async def handle_request(self, method: str, args, kwargs) -> Any:
        self._ongoing += 1
        self._total += 1
        try:
            if method == "__call__":
                fn = self._callable
            else:
                fn = getattr(self._callable, method)
            if inspect.iscoroutinefunction(fn) or (
                not inspect.isfunction(fn)
                and not inspect.ismethod(fn)
                and inspect.iscoroutinefunction(getattr(fn, "__call__", None))
            ):
                return await fn(*args, **(kwargs or {}))
            # Sync callables run on a worker thread: executing them inline
            # would block this actor's single async loop and serialize all
            # max_concurrent_queries requests (and starve stats()).
            loop = asyncio.get_event_loop()
            result = await loop.run_in_executor(
                None, lambda: fn(*args, **(kwargs or {}))
            )
            if inspect.iscoroutine(result):
                result = await result
            return result
        finally:
            self._ongoing -= 1

    def stats(self):
        return {"ongoing": self._ongoing, "total": self._total}

    def health(self) -> bool:
        check = getattr(self._callable, "check_health", None)
        if check is None:
            return True
        result = check()
        return bool(result) if not inspect.iscoroutine(result) else True


Replica = ray_tpu.remote(_Replica)
