"""Router: pow-2 replica choice.

Reference: ``serve/_private/replica_scheduler/pow_2_scheduler.py:52`` —
sample two replicas, compare their queue lengths, send to the shorter.
The replica list refreshes from the controller periodically (long-poll
equivalent of the reference's LongPollClient config push).

Routing is at-most-once: a dispatch racing a replica death surfaces
ActorDiedError on the returned ref (callers retry); the next refresh
drops the dead replica from the candidate set."""

from __future__ import annotations

import random
import time
from typing import Any, List, Optional

import ray_tpu

_REFRESH_S = 1.0
_STATS_TTL_S = 0.25


class Router:
    def __init__(self, controller, deployment: str):
        self._controller = controller
        self._deployment = deployment
        self._replicas: List[Any] = []
        self._last_refresh = 0.0
        # replica -> (fetched_at, ongoing + local optimistic bumps):
        # fresh stats RPCs per dispatch would double request latency and
        # add 2x load (the reference compares CACHED queue lengths)
        self._stats: dict = {}

    def _refresh(self, force: bool = False) -> None:
        now = time.monotonic()
        if not force and now - self._last_refresh < _REFRESH_S and self._replicas:
            return
        self._replicas = ray_tpu.get(
            self._controller.get_replicas.remote(self._deployment), timeout=30
        )
        self._last_refresh = now
        # prune stats for replicas that no longer exist (cache is keyed by
        # actor id — handle objects change identity every refresh)
        live = {r.actor_id for r in self._replicas}
        self._stats = {k: v for k, v in self._stats.items() if k in live}

    def choose_replica(self):
        self._refresh()
        deadline = time.monotonic() + 30
        while not self._replicas:
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"no replicas for deployment {self._deployment!r}"
                )
            time.sleep(0.1)
            self._refresh(force=True)
        if len(self._replicas) == 1:
            return self._replicas[0]
        a, b = random.sample(self._replicas, 2)
        qa, qb = self._queue_len(a), self._queue_len(b)
        return a if qa <= qb else b

    def _queue_len(self, replica) -> float:
        now = time.monotonic()
        key = replica.actor_id
        entry = self._stats.get(key)
        if entry is not None and now - entry[0] < _STATS_TTL_S:
            return entry[1]
        try:
            ongoing = float(
                ray_tpu.get(replica.stats.remote(), timeout=10)["ongoing"]
            )
        except Exception:
            self._refresh(force=True)
            ongoing = 0.0
        self._stats[key] = (now, ongoing)
        return ongoing

    def dispatch(self, method: str, args, kwargs):
        replica = self.choose_replica()
        # optimistic local bump so a burst within the TTL window spreads
        # instead of dogpiling the momentarily-shortest queue
        key = replica.actor_id
        entry = self._stats.get(key)
        if entry is not None:
            self._stats[key] = (entry[0], entry[1] + 1.0)
        return replica.handle_request.remote(method, list(args), dict(kwargs or {}))
