"""Router: pow-2 replica choice over a PUSH-updated replica set.

Reference: ``serve/_private/replica_scheduler/pow_2_scheduler.py:52`` —
sample two replicas, compare queue lengths, send to the shorter — fed by
``long_poll.py``: the replica list arrives via a controller long-poll
(a background thread parks in ``poll_replicas`` and wakes the moment
the routing set changes), not a periodic poll. Deploys/scale-ups/
replica deaths propagate to routers in milliseconds.

Routing is at-most-once: a dispatch racing a replica death surfaces
ActorDiedError on the returned ref (callers retry); the next push drops
the dead replica from the candidate set."""

from __future__ import annotations

import random
import threading
import time
import weakref
from typing import Any, List

import ray_tpu

_STATS_TTL_S = 0.25


def _poll_loop(router_ref: "weakref.ref", controller, deployment: str) -> None:
    """Long-poll thread body. Holds only a WEAK ref to its router: when
    the handle (and router) are garbage-collected, the thread notices on
    its next wakeup and exits — dropped handles must not park controller
    long-poll slots forever."""
    version = -1  # first poll returns immediately with current state
    while True:
        r = router_ref()
        if r is None or r._closed:
            return
        del r
        try:
            version, replicas = ray_tpu.get(
                controller.poll_replicas.remote(deployment, version, 30.0),
                timeout=45,
            )
            r = router_ref()
            if r is None or r._closed:
                return
            r._apply(replicas)
            del r
        except Exception:
            # controller briefly unavailable: back off, keep serving
            # from the cached set
            time.sleep(0.5)


class Router:
    def __init__(self, controller, deployment: str):
        self._controller = controller
        self._deployment = deployment
        self._replicas: List[Any] = []
        self._replicas_lock = threading.Lock()
        self._have_replicas = threading.Event()
        # replica -> (fetched_at, ongoing + local optimistic bumps):
        # fresh stats RPCs per dispatch would double request latency and
        # add 2x load (the reference compares CACHED queue lengths)
        self._stats: dict = {}
        self._poller_started = False
        self._poller_lock = threading.Lock()
        self._closed = False

    def close(self) -> None:
        self._closed = True

    def __del__(self):
        self._closed = True

    # -- push subscription ----------------------------------------------
    def _ensure_poller(self) -> None:
        with self._poller_lock:
            if self._poller_started:
                return
            self._poller_started = True
            threading.Thread(
                target=_poll_loop,
                args=(weakref.ref(self), self._controller, self._deployment),
                daemon=True,
                name=f"serve-router-{self._deployment}",
            ).start()

    def _apply(self, replicas: List[Any]) -> None:
        with self._replicas_lock:
            self._replicas = replicas
            live = {r.actor_id for r in replicas}
            self._stats = {k: v for k, v in self._stats.items() if k in live}
        if replicas:
            self._have_replicas.set()
        else:
            self._have_replicas.clear()

    # -- choice ----------------------------------------------------------
    def choose_replica(self):
        self._ensure_poller()
        if not self._have_replicas.wait(timeout=30):
            raise RuntimeError(f"no replicas for deployment {self._deployment!r}")
        with self._replicas_lock:
            replicas = list(self._replicas)
        if not replicas:
            return self.choose_replica()  # raced a scale-to-zero push
        if len(replicas) == 1:
            return replicas[0]
        a, b = random.sample(replicas, 2)
        qa, qb = self._queue_len(a), self._queue_len(b)
        return a if qa <= qb else b

    def _queue_len(self, replica) -> float:
        now = time.monotonic()
        key = replica.actor_id
        entry = self._stats.get(key)
        if entry is not None and now - entry[0] < _STATS_TTL_S:
            return entry[1]
        try:
            ongoing = float(
                ray_tpu.get(replica.stats.remote(), timeout=10)["ongoing"]
            )
        except Exception:
            ongoing = 0.0
        self._stats[key] = (now, ongoing)
        return ongoing

    def dispatch(self, method: str, args, kwargs):
        replica = self.choose_replica()
        # optimistic local bump so a burst within the TTL window spreads
        # instead of dogpiling the momentarily-shortest queue
        key = replica.actor_id
        entry = self._stats.get(key)
        if entry is not None:
            self._stats[key] = (entry[0], entry[1] + 1.0)
        return replica.handle_request.remote(method, list(args), dict(kwargs or {}))
