"""Router: cache-affinity + load-scored replica choice, pow-2 fallback.

Reference: ``serve/_private/replica_scheduler/pow_2_scheduler.py:52`` —
sample two replicas, compare queue lengths, send to the shorter — fed by
``long_poll.py``: the replica list arrives via a controller long-poll
(a background thread parks in ``poll_replicas`` and wakes the moment
the routing set changes), not a periodic poll. Deploys/scale-ups/
replica deaths propagate to routers in milliseconds.

LLM-aware routing (the multi-replica serving tentpole): replicas that
gossip routing stats (load in OUTSTANDING TOKENS + a compact digest of
their prefix cache, pushed replica -> controller -> long-poll) are
scored instead of sampled: ``score = outstanding_tokens + local_bump -
affinity_weight * matched_prefix_tokens``, lowest wins. A conversation
whose system prompt is warm on replica A costs A nothing to prefill, so
A wins until its queue outweighs the cache benefit — locality-aware
scheduling exactly as the Ray paper frames it, with the blend weight as
the knob. The scored path engages ONLY when every candidate has fresh
gossip (``serve_routing_stats_ttl_s``); stale or absent signals fall
back to pow-2 over cached queue lengths — a wrong load guess
self-corrects, a stale digest would keep dogpiling one replica.

Execution semantics (reference ``router.py``): ``execute``/
``execute_stream`` are retry-until-executed — a dispatch that races a
replica death re-chooses among the survivors instead of surfacing
ActorDiedError to the caller (what keeps rolling updates zero-drop).
The raw ``dispatch`` remains at-most-once for callers that manage
their own refs.

Model multiplexing: a request carrying ``model_id`` prefers replicas
whose cached stats report that model loaded (reference model-aware
replica scheduling), falling back to pow-2 over all replicas."""

from __future__ import annotations

import random
import threading
import time
import weakref
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.core.config import GLOBAL_CONFIG
from ray_tpu.core.deadline import Deadline, effective_timeout
from ray_tpu.core.exceptions import ActorDiedError, WorkerCrashedError
from ray_tpu.observability import tracing as _tracing

_STATS_TTL_S = 0.25


def _count_decision(deployment: str, policy: str, affinity_hit: bool = False) -> None:
    from ray_tpu.observability.rpc_metrics import (
        ROUTER_AFFINITY_HITS,
        ROUTER_DECISIONS,
    )

    ROUTER_DECISIONS.inc(labels={"deployment": deployment, "policy": policy})
    if affinity_hit:
        ROUTER_AFFINITY_HITS.inc(labels={"deployment": deployment})


def _request_prompt(args) -> Optional[List[int]]:
    """Token prompt of an LLM-shaped request payload (the affinity
    scorer's input), or None for anything else."""
    if not args:
        return None
    req = args[0]
    if isinstance(req, dict):
        prompt = req.get("prompt")
        if isinstance(prompt, (list, tuple)) and prompt:
            return list(prompt)
    return None


def _poll_loop(router_ref: "weakref.ref", controller, deployment: str) -> None:
    """Long-poll thread body. Holds only a WEAK ref to its router: when
    the handle (and router) are garbage-collected, the thread notices on
    its next wakeup and exits — dropped handles must not park controller
    long-poll slots forever."""
    version = -1  # first poll returns immediately with current state
    while True:
        r = router_ref()
        if r is None or r._closed:
            return
        del r
        try:
            version, routing_set = ray_tpu.get(
                controller.poll_replicas.remote(deployment, version, 30.0),
                timeout=45,
            )
            r = router_ref()
            if r is None or r._closed:
                return
            r._apply(routing_set)
            del r
        except Exception:
            # controller briefly unavailable: back off, keep serving
            # from the cached set
            time.sleep(0.5)


class Router:
    def __init__(self, controller, deployment: str):
        self._controller = controller
        self._deployment = deployment
        self._replicas: List[Any] = []
        self._replicas_lock = threading.Lock()
        self._have_replicas = threading.Event()
        # replica -> (fetched_at, ongoing + local optimistic bumps):
        # fresh stats RPCs per dispatch would double request latency and
        # add 2x load (the reference compares CACHED queue lengths)
        self._stats: dict = {}
        # replica actor_id -> loaded model ids (controller-pushed)
        self._models: dict = {}
        # replica actor_id -> (received_at_local, routing stats dict,
        # digest set, report stamp) — controller-relayed gossip for
        # scored routing; aged on OUR monotonic clock (controller ships
        # age_s at poll time, clocks don't compare across processes);
        # the stamp identifies the underlying REPORT so re-relays of an
        # unchanged one are recognizable
        self._rstats: Dict[Any, tuple] = {}
        # replica actor_id -> optimistic token bump: requests dispatched
        # since that replica's last gossip (cleared by fresher gossip) so
        # a burst inside one gossip period spreads instead of dogpiling
        self._local_tokens: Dict[Any, float] = {}
        self._poller_started = False
        self._poller_lock = threading.Lock()
        self._closed = False

    def close(self) -> None:
        self._closed = True

    def __del__(self):
        self._closed = True

    # -- push subscription ----------------------------------------------
    def _ensure_poller(self) -> None:
        with self._poller_lock:
            if self._poller_started:
                return
            self._poller_started = True
            threading.Thread(
                target=_poll_loop,
                args=(weakref.ref(self), self._controller, self._deployment),
                daemon=True,
                name=f"serve-router-{self._deployment}",
            ).start()

    def _apply(self, routing_set: List[Any]) -> None:
        """routing_set entries from the controller's long-poll:
        ``(handle, loaded_model_ids)`` pairs (legacy) or ``(handle,
        loaded_model_ids, stats_entry)`` triples, where ``stats_entry``
        is None or ``{"stats": <routing gossip>, "age_s": <age at poll
        time>}`` for gossip-capable (LLM) replicas."""
        now = time.monotonic()
        replicas, models, rstats = [], {}, {}
        for entry in routing_set:
            handle, mids = entry[0], entry[1]
            replicas.append(handle)
            models[handle.actor_id] = tuple(mids)
            stats_entry = entry[2] if len(entry) > 2 else None
            if stats_entry is not None:
                stats = stats_entry["stats"]
                received = now - float(stats_entry.get("age_s", 0.0))
                digest = frozenset(stats.get("prefix_digest") or ())
                stamp = stats_entry.get("stamp")
                rstats[handle.actor_id] = (received, stats, digest, stamp)
        with self._replicas_lock:
            self._replicas = replicas
            self._models = models
            live = set(models)
            self._stats = {k: v for k, v in self._stats.items() if k in live}
            for aid, ent in rstats.items():
                prev = self._rstats.get(aid)
                self._rstats[aid] = ent
                if prev is None or ent[3] != prev[3]:
                    # a genuinely NEW report already reflects what we
                    # dispatched — drop the optimistic bump. Comparing
                    # the report STAMP, not reconstructed receipt times:
                    # every routing-set relay recomputes received as
                    # now-age_s, so delivery jitter alone would look
                    # "fresher" and wipe bumps mid-burst.
                    self._local_tokens.pop(aid, None)
            self._rstats = {k: v for k, v in self._rstats.items() if k in live}
            self._local_tokens = {
                k: v for k, v in self._local_tokens.items() if k in live
            }
        if replicas:
            self._have_replicas.set()
        else:
            self._have_replicas.clear()

    def _drop_replica(self, replica) -> None:
        """Locally remove a replica observed dead — the controller push
        will confirm shortly, but requests in THIS window must not keep
        choosing the corpse."""
        with self._replicas_lock:
            self._replicas = [
                r for r in self._replicas if r.actor_id != replica.actor_id
            ]
            self._stats.pop(replica.actor_id, None)
            self._models.pop(replica.actor_id, None)
            self._rstats.pop(replica.actor_id, None)
            self._local_tokens.pop(replica.actor_id, None)
            if not self._replicas:
                self._have_replicas.clear()

    # -- choice ----------------------------------------------------------
    def choose_replica(self, model_id: str = "", request_args=None):
        self._ensure_poller()
        if not self._have_replicas.wait(timeout=30):
            raise RuntimeError(f"no replicas for deployment {self._deployment!r}")
        with self._replicas_lock:
            replicas = list(self._replicas)
        if not replicas:
            # raced a scale-to-zero push
            return self.choose_replica(model_id, request_args)
        if model_id:
            # model-aware: prefer replicas the controller says already
            # hold the model (replica-pushed, so no stats-TTL staleness)
            with_model = [
                r for r in replicas
                if model_id in self._models.get(r.actor_id, ())
            ]
            if with_model:
                replicas = with_model
        if len(replicas) == 1:
            _count_decision(self._deployment, "single")
            return replicas[0]
        chosen = self._choose_scored(replicas, request_args)
        if chosen is not None:
            return chosen
        a, b = random.sample(replicas, 2)
        qa, qb = self._queue_len(a), self._queue_len(b)
        _count_decision(self._deployment, "pow2")
        return a if qa <= qb else b

    def _choose_scored(self, replicas, request_args):
        """Least-outstanding-tokens blended with prefix affinity, over
        replica-gossiped stats. Returns None (→ pow-2 fallback) unless
        EVERY candidate has gossip fresher than the staleness TTL — a
        replica without fresh signals scored at an assumed load would
        either starve (assumed busy) or drown (assumed idle)."""
        now = time.monotonic()
        ttl = GLOBAL_CONFIG.serve_routing_stats_ttl_s
        entries = []
        with self._replicas_lock:
            for r in replicas:
                ent = self._rstats.get(r.actor_id)
                if ent is None or now - ent[0] > ttl:
                    return None  # absent/stale signal: fall back
                entries.append((r, ent[1], ent[2]))
            bumps = dict(self._local_tokens)
        prompt = _request_prompt(request_args)
        prompt_hashes: List[int] = []
        block_size = 0
        if prompt is not None:
            block_size = int(entries[0][1].get("block_size") or 0)
            if block_size > 0 and len(prompt) >= block_size:
                from ray_tpu.inference.kv_cache import prefix_block_hashes

                prompt_hashes = prefix_block_hashes(prompt, block_size)
        weight = GLOBAL_CONFIG.serve_affinity_weight
        best = None
        best_key = None
        best_matched = 0
        for r, stats, digest in entries:
            if stats.get("draining"):
                continue
            matched = 0
            if prompt_hashes and digest:
                # consecutive-prefix match: a hit on block k only helps
                # if blocks 0..k-1 are warm too (the engine acquires the
                # LONGEST cached prefix, nothing past the first miss)
                for h in prompt_hashes:
                    if h not in digest:
                        break
                    matched += 1
            matched_tokens = matched * block_size
            load = float(stats.get("outstanding_tokens", 0.0)) + bumps.get(
                r.actor_id, 0.0
            )
            key = (load - weight * matched_tokens, load)
            if best_key is None or key < best_key:
                best, best_key, best_matched = r, key, matched_tokens
        if best is None:
            return None  # every gossiping replica is draining
        # optimistic local debit: what this dispatch will add to the
        # winner's backlog before its next gossip lands
        est = 64.0
        if prompt is not None:
            est = max(1.0, len(prompt) - best_matched) + 64.0
        with self._replicas_lock:
            self._local_tokens[best.actor_id] = (
                self._local_tokens.get(best.actor_id, 0.0) + est
            )
        _count_decision(
            self._deployment, "affinity", affinity_hit=best_matched > 0
        )
        return best

    def _queue_len(self, replica) -> float:
        now = time.monotonic()
        key = replica.actor_id
        entry = self._stats.get(key)
        if entry is not None and now - entry[0] < _STATS_TTL_S:
            return entry[1]
        try:
            ongoing = float(
                ray_tpu.get(replica.stats.remote(), timeout=10)["ongoing"]
            )
        except Exception:
            ongoing = 0.0
        self._stats[key] = (now, ongoing)
        return ongoing

    def _bump(self, replica) -> None:
        # optimistic local bump so a burst within the TTL window spreads
        # instead of dogpiling the momentarily-shortest queue
        entry = self._stats.get(replica.actor_id)
        if entry is not None:
            self._stats[replica.actor_id] = (entry[0], entry[1] + 1.0)

    # -- dispatch ---------------------------------------------------------
    def dispatch(self, method: str, args, kwargs, model_id: str = ""):
        """At-most-once: returns the replica call's ObjectRef."""
        # a serve request is a trace ENTRY POINT: sample a root here (or
        # inherit the caller's ambient trace) so the replica push — and
        # everything the replica does — parents to this dispatch span
        with _tracing.root_span(
            f"serve::{self._deployment}.{method}", "serve"
        ):
            replica = self.choose_replica(model_id, args)
            self._bump(replica)
            return replica.handle_request.remote(
                method, list(args), dict(kwargs or {}), model_id
            )

    def dispatch_stream(self, method: str, args, kwargs, model_id: str = ""):
        """Streaming call: returns the replica generator's ref iterator."""
        with _tracing.root_span(
            f"serve::{self._deployment}.{method}", "serve"
        ):
            replica = self.choose_replica(model_id, args)
            self._bump(replica)
            return replica.handle_request_streaming.options(
                num_returns="streaming"
            ).remote(method, list(args), dict(kwargs or {}), model_id)

    def execute(
        self,
        method: str,
        args,
        kwargs,
        *,
        model_id: str = "",
        timeout: Optional[float] = 60.0,
        idempotent: bool = True,
    ):
        """Retry-until-executed (reference router semantics): a dispatch
        that lands on a dying replica re-chooses. App-level exceptions
        are NOT retried — only replica death/crash.

        RETRY CONTRACT. While the chosen replica is REACHABLE, every
        call — idempotent or not — is exactly-once-effective: the actor
        push rides the RPC layer's request-id dedup (core/rpc.py via
        core_worker request-id reuse), so a lost reply or a transient
        connection reset is retried transparently and answered from the
        replica's reply cache instead of re-executing. What remains
        AT-LEAST-ONCE is replica DEATH: the runtime cannot tell "replica
        died before it saw the push" apart from "replica executed (part
        of) the request, then died" — the reply cache died with the
        process. With ``idempotent=True`` (default) the router
        re-executes on a survivor either way, so a non-idempotent
        request (LLM generation, a payment, an append) can run twice
        after an unlucky crash. Pass ``idempotent=False`` to confine
        auto-retry to the provably-safe cases (submission-side failure,
        or the dedup-protected reachable-replica retries above); a
        post-dispatch replica death then propagates to the caller, who
        owns the cross-replica dedupe/retry decision. Streaming callers
        get the tighter contract for free: ``execute_stream`` only ever
        replays before the first item.

        One Deadline covers the whole call (core/deadline.py): dispatch
        retries AND the result get draw from the same budget, clamped by
        any ambient deadline of the caller — inner timeouts never stack."""
        budget = effective_timeout(timeout)
        deadline = Deadline.after(budget if budget is not None else 3600)
        last_err: Optional[Exception] = None
        # trace root covering dispatch retries AND the result get: the
        # replica-side spans parent to this one
        with _tracing.root_span(f"serve::{self._deployment}.{method}", "serve"):
            while not deadline.expired:
                replica = self.choose_replica(model_id, args)
                self._bump(replica)
                try:
                    ref = replica.handle_request.remote(
                        method, list(args), dict(kwargs or {}), model_id
                    )
                except (ActorDiedError, WorkerCrashedError) as e:
                    # submission failed: the request never reached a
                    # replica, safe to re-choose even for non-idempotent
                    # work
                    last_err = e
                    self._drop_replica(replica)
                    continue
                try:
                    remaining = max(1.0, deadline.remaining())
                    return ray_tpu.get(ref, timeout=remaining)
                except (ActorDiedError, WorkerCrashedError) as e:
                    last_err = e
                    self._drop_replica(replica)
                    if not idempotent:
                        # the push may have been delivered and executed —
                        # replaying could duplicate a side effect
                        raise
                    continue
        raise last_err or TimeoutError(
            f"no replica executed {self._deployment}.{method} in time"
        )

    def execute_stream(
        self,
        method: str,
        args,
        kwargs,
        *,
        model_id: str = "",
        timeout: Optional[float] = 60.0,
    ):
        """Streaming with dispatch retry: re-chooses if the stream dies
        BEFORE the first item (nothing was delivered, safe to replay);
        mid-stream death propagates — replaying would duplicate items.

        The Deadline budget covers dispatch + time-to-first-item; after
        that, each item get inherits the CALLER's timeout (None = wait
        forever) — a slow producer mid-stream is backpressure, not a
        dispatch failure, so it must not trip a fixed 60s timer."""
        budget = effective_timeout(timeout)
        deadline = Deadline.after(budget if budget is not None else 3600)
        # per-item patience once streaming: the caller's timeout with any
        # tighter ambient deadline already folded in; None = wait forever
        item_timeout = budget
        last_err: Optional[Exception] = None
        # trace root spanning dispatch → first item (the serve TTFT
        # window); the replica's streaming task span parents to it
        with _tracing.root_span(f"serve::{self._deployment}.{method}", "serve"):
            while not deadline.expired:
                replica = self.choose_replica(model_id, args)
                self._bump(replica)
                gen = replica.handle_request_streaming.options(
                    num_returns="streaming"
                ).remote(method, list(args), dict(kwargs or {}), model_id)
                try:
                    # bounded time-to-first-item: a replica stuck before
                    # its first yield must not park this request forever
                    first_ref = gen.next_with_timeout(
                        max(1.0, deadline.remaining())
                    )
                    first = ray_tpu.get(
                        first_ref, timeout=max(1.0, deadline.remaining())
                    )
                except StopIteration:
                    def _empty():
                        return
                        yield  # pragma: no cover
                    return _empty()
                except (ActorDiedError, WorkerCrashedError) as e:
                    last_err = e
                    self._drop_replica(replica)
                    continue
                it = iter(gen)

                def _rest(first=first, it=it):
                    yield first
                    for ref in it:
                        yield ray_tpu.get(ref, timeout=item_timeout)

                return _rest()
        raise last_err or TimeoutError(
            f"no replica started stream {self._deployment}.{method} in time"
        )
