"""Router: pow-2 replica choice over a PUSH-updated replica set.

Reference: ``serve/_private/replica_scheduler/pow_2_scheduler.py:52`` —
sample two replicas, compare queue lengths, send to the shorter — fed by
``long_poll.py``: the replica list arrives via a controller long-poll
(a background thread parks in ``poll_replicas`` and wakes the moment
the routing set changes), not a periodic poll. Deploys/scale-ups/
replica deaths propagate to routers in milliseconds.

Execution semantics (reference ``router.py``): ``execute``/
``execute_stream`` are retry-until-executed — a dispatch that races a
replica death re-chooses among the survivors instead of surfacing
ActorDiedError to the caller (what keeps rolling updates zero-drop).
The raw ``dispatch`` remains at-most-once for callers that manage
their own refs.

Model multiplexing: a request carrying ``model_id`` prefers replicas
whose cached stats report that model loaded (reference model-aware
replica scheduling), falling back to pow-2 over all replicas."""

from __future__ import annotations

import random
import threading
import time
import weakref
from typing import Any, List, Optional

import ray_tpu
from ray_tpu.core.deadline import Deadline, effective_timeout
from ray_tpu.core.exceptions import ActorDiedError, WorkerCrashedError

_STATS_TTL_S = 0.25


def _poll_loop(router_ref: "weakref.ref", controller, deployment: str) -> None:
    """Long-poll thread body. Holds only a WEAK ref to its router: when
    the handle (and router) are garbage-collected, the thread notices on
    its next wakeup and exits — dropped handles must not park controller
    long-poll slots forever."""
    version = -1  # first poll returns immediately with current state
    while True:
        r = router_ref()
        if r is None or r._closed:
            return
        del r
        try:
            version, routing_set = ray_tpu.get(
                controller.poll_replicas.remote(deployment, version, 30.0),
                timeout=45,
            )
            r = router_ref()
            if r is None or r._closed:
                return
            r._apply(routing_set)
            del r
        except Exception:
            # controller briefly unavailable: back off, keep serving
            # from the cached set
            time.sleep(0.5)


class Router:
    def __init__(self, controller, deployment: str):
        self._controller = controller
        self._deployment = deployment
        self._replicas: List[Any] = []
        self._replicas_lock = threading.Lock()
        self._have_replicas = threading.Event()
        # replica -> (fetched_at, ongoing + local optimistic bumps):
        # fresh stats RPCs per dispatch would double request latency and
        # add 2x load (the reference compares CACHED queue lengths)
        self._stats: dict = {}
        # replica actor_id -> loaded model ids (controller-pushed)
        self._models: dict = {}
        self._poller_started = False
        self._poller_lock = threading.Lock()
        self._closed = False

    def close(self) -> None:
        self._closed = True

    def __del__(self):
        self._closed = True

    # -- push subscription ----------------------------------------------
    def _ensure_poller(self) -> None:
        with self._poller_lock:
            if self._poller_started:
                return
            self._poller_started = True
            threading.Thread(
                target=_poll_loop,
                args=(weakref.ref(self), self._controller, self._deployment),
                daemon=True,
                name=f"serve-router-{self._deployment}",
            ).start()

    def _apply(self, routing_set: List[Any]) -> None:
        """routing_set: [(handle, loaded_model_ids)] pairs from the
        controller's long-poll (model ids drive model-local routing)."""
        replicas, models = [], {}
        for entry in routing_set:
            handle, mids = entry
            replicas.append(handle)
            models[handle.actor_id] = tuple(mids)
        with self._replicas_lock:
            self._replicas = replicas
            self._models = models
            live = set(models)
            self._stats = {k: v for k, v in self._stats.items() if k in live}
        if replicas:
            self._have_replicas.set()
        else:
            self._have_replicas.clear()

    def _drop_replica(self, replica) -> None:
        """Locally remove a replica observed dead — the controller push
        will confirm shortly, but requests in THIS window must not keep
        choosing the corpse."""
        with self._replicas_lock:
            self._replicas = [
                r for r in self._replicas if r.actor_id != replica.actor_id
            ]
            self._stats.pop(replica.actor_id, None)
            self._models.pop(replica.actor_id, None)
            if not self._replicas:
                self._have_replicas.clear()

    # -- choice ----------------------------------------------------------
    def choose_replica(self, model_id: str = ""):
        self._ensure_poller()
        if not self._have_replicas.wait(timeout=30):
            raise RuntimeError(f"no replicas for deployment {self._deployment!r}")
        with self._replicas_lock:
            replicas = list(self._replicas)
        if not replicas:
            return self.choose_replica(model_id)  # raced a scale-to-zero push
        if model_id:
            # model-aware: prefer replicas the controller says already
            # hold the model (replica-pushed, so no stats-TTL staleness)
            with_model = [
                r for r in replicas
                if model_id in self._models.get(r.actor_id, ())
            ]
            if with_model:
                replicas = with_model
        if len(replicas) == 1:
            return replicas[0]
        a, b = random.sample(replicas, 2)
        qa, qb = self._queue_len(a), self._queue_len(b)
        return a if qa <= qb else b

    def _queue_len(self, replica) -> float:
        now = time.monotonic()
        key = replica.actor_id
        entry = self._stats.get(key)
        if entry is not None and now - entry[0] < _STATS_TTL_S:
            return entry[1]
        try:
            ongoing = float(
                ray_tpu.get(replica.stats.remote(), timeout=10)["ongoing"]
            )
        except Exception:
            ongoing = 0.0
        self._stats[key] = (now, ongoing)
        return ongoing

    def _bump(self, replica) -> None:
        # optimistic local bump so a burst within the TTL window spreads
        # instead of dogpiling the momentarily-shortest queue
        entry = self._stats.get(replica.actor_id)
        if entry is not None:
            self._stats[replica.actor_id] = (entry[0], entry[1] + 1.0)

    # -- dispatch ---------------------------------------------------------
    def dispatch(self, method: str, args, kwargs, model_id: str = ""):
        """At-most-once: returns the replica call's ObjectRef."""
        replica = self.choose_replica(model_id)
        self._bump(replica)
        return replica.handle_request.remote(
            method, list(args), dict(kwargs or {}), model_id
        )

    def dispatch_stream(self, method: str, args, kwargs, model_id: str = ""):
        """Streaming call: returns the replica generator's ref iterator."""
        replica = self.choose_replica(model_id)
        self._bump(replica)
        return replica.handle_request_streaming.options(
            num_returns="streaming"
        ).remote(method, list(args), dict(kwargs or {}), model_id)

    def execute(
        self,
        method: str,
        args,
        kwargs,
        *,
        model_id: str = "",
        timeout: Optional[float] = 60.0,
        idempotent: bool = True,
    ):
        """Retry-until-executed (reference router semantics): a dispatch
        that lands on a dying replica re-chooses. App-level exceptions
        are NOT retried — only replica death/crash.

        RETRY CONTRACT. While the chosen replica is REACHABLE, every
        call — idempotent or not — is exactly-once-effective: the actor
        push rides the RPC layer's request-id dedup (core/rpc.py via
        core_worker request-id reuse), so a lost reply or a transient
        connection reset is retried transparently and answered from the
        replica's reply cache instead of re-executing. What remains
        AT-LEAST-ONCE is replica DEATH: the runtime cannot tell "replica
        died before it saw the push" apart from "replica executed (part
        of) the request, then died" — the reply cache died with the
        process. With ``idempotent=True`` (default) the router
        re-executes on a survivor either way, so a non-idempotent
        request (LLM generation, a payment, an append) can run twice
        after an unlucky crash. Pass ``idempotent=False`` to confine
        auto-retry to the provably-safe cases (submission-side failure,
        or the dedup-protected reachable-replica retries above); a
        post-dispatch replica death then propagates to the caller, who
        owns the cross-replica dedupe/retry decision. Streaming callers
        get the tighter contract for free: ``execute_stream`` only ever
        replays before the first item.

        One Deadline covers the whole call (core/deadline.py): dispatch
        retries AND the result get draw from the same budget, clamped by
        any ambient deadline of the caller — inner timeouts never stack."""
        budget = effective_timeout(timeout)
        deadline = Deadline.after(budget if budget is not None else 3600)
        last_err: Optional[Exception] = None
        while not deadline.expired:
            replica = self.choose_replica(model_id)
            self._bump(replica)
            try:
                ref = replica.handle_request.remote(
                    method, list(args), dict(kwargs or {}), model_id
                )
            except (ActorDiedError, WorkerCrashedError) as e:
                # submission failed: the request never reached a replica,
                # safe to re-choose even for non-idempotent work
                last_err = e
                self._drop_replica(replica)
                continue
            try:
                remaining = max(1.0, deadline.remaining())
                return ray_tpu.get(ref, timeout=remaining)
            except (ActorDiedError, WorkerCrashedError) as e:
                last_err = e
                self._drop_replica(replica)
                if not idempotent:
                    # the push may have been delivered and executed —
                    # replaying could duplicate a side effect
                    raise
                continue
        raise last_err or TimeoutError(
            f"no replica executed {self._deployment}.{method} in time"
        )

    def execute_stream(
        self,
        method: str,
        args,
        kwargs,
        *,
        model_id: str = "",
        timeout: Optional[float] = 60.0,
    ):
        """Streaming with dispatch retry: re-chooses if the stream dies
        BEFORE the first item (nothing was delivered, safe to replay);
        mid-stream death propagates — replaying would duplicate items.

        The Deadline budget covers dispatch + time-to-first-item; after
        that, each item get inherits the CALLER's timeout (None = wait
        forever) — a slow producer mid-stream is backpressure, not a
        dispatch failure, so it must not trip a fixed 60s timer."""
        budget = effective_timeout(timeout)
        deadline = Deadline.after(budget if budget is not None else 3600)
        # per-item patience once streaming: the caller's timeout with any
        # tighter ambient deadline already folded in; None = wait forever
        item_timeout = budget
        last_err: Optional[Exception] = None
        while not deadline.expired:
            replica = self.choose_replica(model_id)
            self._bump(replica)
            gen = replica.handle_request_streaming.options(
                num_returns="streaming"
            ).remote(method, list(args), dict(kwargs or {}), model_id)
            try:
                # bounded time-to-first-item: a replica stuck before its
                # first yield must not park this request forever
                first_ref = gen.next_with_timeout(
                    max(1.0, deadline.remaining())
                )
                first = ray_tpu.get(first_ref, timeout=max(1.0, deadline.remaining()))
            except StopIteration:
                def _empty():
                    return
                    yield  # pragma: no cover
                return _empty()
            except (ActorDiedError, WorkerCrashedError) as e:
                last_err = e
                self._drop_replica(replica)
                continue
            it = iter(gen)

            def _rest(first=first, it=it):
                yield first
                for ref in it:
                    yield ray_tpu.get(ref, timeout=item_timeout)

            return _rest()
        raise last_err or TimeoutError(
            f"no replica started stream {self._deployment}.{method} in time"
        )
