"""Router: cache-affinity + load-scored replica choice, pow-2 fallback.

Reference: ``serve/_private/replica_scheduler/pow_2_scheduler.py:52`` —
sample two replicas, compare queue lengths, send to the shorter — fed by
``long_poll.py``: the replica list arrives via a controller long-poll
(a background thread parks in ``poll_replicas`` and wakes the moment
the routing set changes), not a periodic poll. Deploys/scale-ups/
replica deaths propagate to routers in milliseconds.

LLM-aware routing (the multi-replica serving tentpole): replicas that
gossip routing stats (load in OUTSTANDING TOKENS + a compact digest of
their prefix cache, pushed replica -> controller -> long-poll) are
scored instead of sampled: ``score = outstanding_tokens + local_bump -
affinity_weight * matched_prefix_tokens``, lowest wins. A conversation
whose system prompt is warm on replica A costs A nothing to prefill, so
A wins until its queue outweighs the cache benefit — locality-aware
scheduling exactly as the Ray paper frames it, with the blend weight as
the knob. The scored path engages ONLY when every candidate has fresh
gossip (``serve_routing_stats_ttl_s``); stale or absent signals fall
back to pow-2 over cached queue lengths — a wrong load guess
self-corrects, a stale digest would keep dogpiling one replica.

Execution semantics (reference ``router.py``): ``execute``/
``execute_stream`` are retry-until-executed — a dispatch that races a
replica death re-chooses among the survivors instead of surfacing
ActorDiedError to the caller (what keeps rolling updates zero-drop).
The raw ``dispatch`` remains at-most-once for callers that manage
their own refs. Streams of methods a deployment declares in
``resumable_streams`` get the strongest tier: seq-numbered items,
mid-stream replica death resumed on a survivor with the prompt
extended by the already-delivered tokens, duplicates suppressed —
exactly-once token delivery (see ``execute`` for the full three-tier
contract).

Model multiplexing: a request carrying ``model_id`` prefers replicas
whose cached stats report that model loaded (reference model-aware
replica scheduling), falling back to pow-2 over all replicas."""

from __future__ import annotations

import itertools
import os
import random
import threading
import time
import uuid
import weakref
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.core.config import GLOBAL_CONFIG
from ray_tpu.core.deadline import Deadline, effective_timeout
from ray_tpu.core.exceptions import ActorDiedError, WorkerCrashedError
from ray_tpu.core.rpc import ConnectionLost
from ray_tpu.core.streaming import SeqGate, TokenChunk
from ray_tpu.observability import tracing as _tracing

_STATS_TTL_S = 0.25

#: failures that mean "the replica is gone", never "the request is bad" —
#: the only class a resumable stream may fail over on (an app-level
#: exception from the callable must propagate: replaying it would just
#: raise it twice)
_REPLICA_GONE = (ActorDiedError, WorkerCrashedError, ConnectionLost)

#: consecutive zero-progress failover attempts before a resumable stream
#: gives up: every successful token resets the count, so this only trips
#: when replicas die faster than they can deliver a single token
_MAX_BARREN_RESUMES = 5

#: refresh window for the deployment's resumable_streams declaration — a
#: redeploy can change the callable, and a handle outliving it must not
#: pin the old contract forever (bounded staleness, one controller call
#: per window per router)
_RESUMABLE_META_TTL_S = 30.0


def _count_decision(deployment: str, policy: str, affinity_hit: bool = False) -> None:
    from ray_tpu.observability.rpc_metrics import (
        ROUTER_AFFINITY_HITS,
        ROUTER_DECISIONS,
    )

    ROUTER_DECISIONS.inc(labels={"deployment": deployment, "policy": policy})
    if affinity_hit:
        ROUTER_AFFINITY_HITS.inc(labels={"deployment": deployment})


def _count_stream_resume(deployment: str, replayed_tokens: int) -> None:
    from ray_tpu.observability.rpc_metrics import (
        STREAM_RESUME_REPLAY_TOKENS,
        STREAM_RESUMES,
    )
    from ray_tpu.observability.slo import slo_metrics

    STREAM_RESUMES.inc(labels={"deployment": deployment})
    if replayed_tokens > 0:
        STREAM_RESUME_REPLAY_TOKENS.inc(replayed_tokens)
        # the same increment feeds the SLO ledger's fault-cost split:
        # replayed tokens are work a fault forced (mostly absorbed by
        # the survivor's radix cache, but never goodput)
        slo_metrics()["fault"].inc(
            replayed_tokens,
            labels={"deployment": deployment, "reason": "resume_replay"},
        )


def _request_prompt(args) -> Optional[List[int]]:
    """Token prompt of an LLM-shaped request payload (the affinity
    scorer's input), or None for anything else."""
    if not args:
        return None
    req = args[0]
    if isinstance(req, dict):
        prompt = req.get("prompt")
        if isinstance(prompt, (list, tuple)) and prompt:
            return list(prompt)
    return None


def _poll_loop(router_ref: "weakref.ref", controller, deployment: str) -> None:
    """Long-poll thread body. Holds only a WEAK ref to its router: when
    the handle (and router) are garbage-collected, the thread notices on
    its next wakeup and exits — dropped handles must not park controller
    long-poll slots forever."""
    version = -1  # first poll returns immediately with current state
    while True:
        r = router_ref()
        if r is None or r._closed:
            return
        del r
        try:
            version, routing_set = ray_tpu.get(
                controller.poll_replicas.remote(deployment, version, 30.0),
                timeout=45,
            )
            r = router_ref()
            if r is None or r._closed:
                return
            r._apply(routing_set)
            del r
        except Exception:
            # controller briefly unavailable: back off, keep serving
            # from the cached set
            time.sleep(0.5)


class Router:
    def __init__(self, controller, deployment: str):
        self._controller = controller
        self._deployment = deployment
        self._replicas: List[Any] = []
        self._replicas_lock = threading.Lock()
        self._have_replicas = threading.Event()
        # replica -> (fetched_at, ongoing + local optimistic bumps):
        # fresh stats RPCs per dispatch would double request latency and
        # add 2x load (the reference compares CACHED queue lengths)
        self._stats: dict = {}
        # replica actor_id -> loaded model ids (controller-pushed)
        self._models: dict = {}
        # replica actor_id -> (received_at_local, routing stats dict,
        # digest set, report stamp) — controller-relayed gossip for
        # scored routing; aged on OUR monotonic clock (controller ships
        # age_s at poll time, clocks don't compare across processes);
        # the stamp identifies the underlying REPORT so re-relays of an
        # unchanged one are recognizable
        self._rstats: Dict[Any, tuple] = {}
        # replica actor_id -> optimistic token bump: requests dispatched
        # since that replica's last gossip (cleared by fresher gossip) so
        # a burst inside one gossip period spreads instead of dogpiling
        self._local_tokens: Dict[Any, float] = {}
        # cluster KV-tier directory (PR 17): chain-digest hex ->
        # (descriptor, holder actor_id, refreshed_at). Fed by the
        # replicas' "kv_tier" routing-gossip adverts; a LIVE holder
        # dropping a digest from its advert set RETRACTS the entry in
        # one gossip hop, while a DEAD holder's entries linger for
        # kv_tier_advert_ttl_s — the daemon, not the replica process,
        # owns the bytes, and a warm replacement re-adverts them
        self._tier_dir: Dict[str, tuple] = {}
        # replica actor_id -> frozenset of advertised digest hexes (the
        # previous report's view, diffed per report for retraction)
        self._tier_adverts: Dict[Any, frozenset] = {}
        self._poller_started = False
        self._poller_lock = threading.Lock()
        #: deployment meta (resumable_streams declaration + paired
        #: disagg prefill pool), fetched lazily from the serve
        #: controller and cached with a TTL
        self._meta: Optional[Dict[str, Any]] = None
        self._meta_fetched_at = 0.0
        #: lazily-built router for the paired prefill-pool deployment
        #: (disaggregated serving two-stage dispatch)
        self._prefill_router: Optional["Router"] = None
        self._closed = False

    def close(self) -> None:
        self._closed = True

    def __del__(self):
        self._closed = True

    # -- push subscription ----------------------------------------------
    def _ensure_poller(self) -> None:
        with self._poller_lock:
            if self._poller_started:
                return
            self._poller_started = True
            threading.Thread(
                target=_poll_loop,
                args=(weakref.ref(self), self._controller, self._deployment),
                daemon=True,
                name=f"serve-router-{self._deployment}",
            ).start()

    def _apply(self, routing_set: List[Any]) -> None:
        """routing_set entries from the controller's long-poll:
        ``(handle, loaded_model_ids)`` pairs (legacy) or ``(handle,
        loaded_model_ids, stats_entry)`` triples, where ``stats_entry``
        is None or ``{"stats": <routing gossip>, "age_s": <age at poll
        time>}`` for gossip-capable (LLM) replicas."""
        now = time.monotonic()
        replicas, models, rstats = [], {}, {}
        for entry in routing_set:
            handle, mids = entry[0], entry[1]
            replicas.append(handle)
            models[handle.actor_id] = tuple(mids)
            stats_entry = entry[2] if len(entry) > 2 else None
            if stats_entry is not None:
                stats = stats_entry["stats"]
                received = now - float(stats_entry.get("age_s", 0.0))
                digest = frozenset(stats.get("prefix_digest") or ())
                stamp = stats_entry.get("stamp")
                rstats[handle.actor_id] = (received, stats, digest, stamp)
        with self._replicas_lock:
            self._replicas = replicas
            self._models = models
            live = set(models)
            self._stats = {k: v for k, v in self._stats.items() if k in live}
            for aid, ent in rstats.items():
                prev = self._rstats.get(aid)
                self._rstats[aid] = ent
                if prev is None or ent[3] != prev[3]:
                    # a genuinely NEW report already reflects what we
                    # dispatched — drop the optimistic bump. Comparing
                    # the report STAMP, not reconstructed receipt times:
                    # every routing-set relay recomputes received as
                    # now-age_s, so delivery jitter alone would look
                    # "fresher" and wipe bumps mid-burst.
                    self._local_tokens.pop(aid, None)
            self._rstats = {k: v for k, v in self._rstats.items() if k in live}
            self._local_tokens = {
                k: v for k, v in self._local_tokens.items() if k in live
            }
            retractions = 0
            for aid, ent in rstats.items():
                adverts = ent[1].get("kv_tier") or {}
                advert_set = frozenset(adverts)
                prev_set = self._tier_adverts.get(aid)
                if prev_set:
                    # a digest a LIVE holder stopped advertising was
                    # evicted from its daemon's tier: purge in one hop
                    # instead of letting fault-ins chase it to a miss
                    for gone in prev_set - advert_set:
                        cur = self._tier_dir.get(gone)
                        if cur is not None and cur[1] == aid:
                            del self._tier_dir[gone]
                            retractions += 1
                for dh, desc in adverts.items():
                    self._tier_dir[dh] = (desc, aid, now)
                self._tier_adverts[aid] = advert_set
            self._tier_adverts = {
                k: v for k, v in self._tier_adverts.items() if k in live
            }
            if self._tier_dir:
                # dead-holder retention: keep the entry (the daemon may
                # still serve it to a warm replacement) but not forever
                ttl = GLOBAL_CONFIG.kv_tier_advert_ttl_s
                self._tier_dir = {
                    dh: ent for dh, ent in self._tier_dir.items()
                    if ent[1] in live or now - ent[2] < ttl
                }
        if retractions:
            from ray_tpu.observability.rpc_metrics import KV_TIER_RETRACTIONS

            KV_TIER_RETRACTIONS.inc(retractions)
        if replicas:
            self._have_replicas.set()
        else:
            self._have_replicas.clear()

    def _drop_replica(self, replica) -> None:
        """Locally remove a replica observed dead — the controller push
        will confirm shortly, but requests in THIS window must not keep
        choosing the corpse."""
        with self._replicas_lock:
            self._replicas = [
                r for r in self._replicas if r.actor_id != replica.actor_id
            ]
            self._stats.pop(replica.actor_id, None)
            self._models.pop(replica.actor_id, None)
            self._rstats.pop(replica.actor_id, None)
            self._local_tokens.pop(replica.actor_id, None)
            # death is NOT retraction: the holder's daemon still has the
            # tier bytes, so _tier_dir entries stay (TTL-bounded) for the
            # resume that is about to need them — only the per-actor
            # advert view goes, there will be no more reports to diff
            self._tier_adverts.pop(replica.actor_id, None)
            if not self._replicas:
                self._have_replicas.clear()

    # -- choice ----------------------------------------------------------
    def choose_replica(self, model_id: str = "", request_args=None, wait_s: float = 30.0):
        self._ensure_poller()
        if not self._have_replicas.wait(timeout=wait_s):
            raise RuntimeError(f"no replicas for deployment {self._deployment!r}")
        with self._replicas_lock:
            replicas = list(self._replicas)
        if not replicas:
            # raced a scale-to-zero push
            return self.choose_replica(model_id, request_args, wait_s)
        if model_id:
            # model-aware: prefer replicas the controller says already
            # hold the model (replica-pushed, so no stats-TTL staleness)
            with_model = [
                r for r in replicas
                if model_id in self._models.get(r.actor_id, ())
            ]
            if with_model:
                replicas = with_model
        if len(replicas) == 1:
            _count_decision(self._deployment, "single")
            return replicas[0]
        chosen, fallback = self._choose_scored(replicas, request_args)
        if chosen is not None:
            return chosen
        a, b = random.sample(replicas, 2)
        qa, qb = self._queue_len(a), self._queue_len(b)
        # a gossip-capable deployment falling back on STALE signals is a
        # DIFFERENT condition than a plain deployment (never had stats)
        # or an all-draining window (fresh gossip, nothing routable):
        # split it out so load tests can assert the scored path actually
        # engaged — a run whose decisions are all stale_fallback means
        # the gossip cadence (or TTL) is mistuned
        with self._replicas_lock:
            had_gossip = bool(self._rstats)
        _count_decision(
            self._deployment,
            "stale_fallback" if (fallback == "stale" and had_gossip) else "pow2",
        )
        return a if qa <= qb else b

    def _choose_scored(self, replicas, request_args):
        """Least-outstanding-tokens blended with prefix affinity, over
        replica-gossiped stats. Returns ``(choice, None)``, or
        ``(None, reason)`` (→ pow-2 fallback) — ``reason`` is "stale"
        unless EVERY candidate has gossip fresher than the staleness TTL
        (a replica without fresh signals scored at an assumed load would
        either starve or drown), or "draining" when the signals are
        fresh but every candidate is draining (an attributably different
        condition — fallback counters must not blame the gossip)."""
        now = time.monotonic()
        ttl = GLOBAL_CONFIG.serve_routing_stats_ttl_s
        entries = []
        with self._replicas_lock:
            for r in replicas:
                ent = self._rstats.get(r.actor_id)
                if ent is None or now - ent[0] > ttl:
                    return None, "stale"  # absent/stale signal: fall back
                entries.append((r, ent[1], ent[2]))
            bumps = dict(self._local_tokens)
        prompt = _request_prompt(request_args)
        prompt_hashes: List[int] = []
        block_size = 0
        if prompt is not None:
            block_size = int(entries[0][1].get("block_size") or 0)
            if block_size > 0 and len(prompt) >= block_size:
                from ray_tpu.inference.kv_cache import prefix_block_hashes

                prompt_hashes = prefix_block_hashes(prompt, block_size)
        weight = GLOBAL_CONFIG.serve_affinity_weight
        best = None
        best_key = None
        best_matched = 0
        for r, stats, digest in entries:
            if stats.get("draining"):
                continue
            matched = 0
            if prompt_hashes and digest:
                # consecutive-prefix match: a hit on block k only helps
                # if blocks 0..k-1 are warm too (the engine acquires the
                # LONGEST cached prefix, nothing past the first miss)
                for h in prompt_hashes:
                    if h not in digest:
                        break
                    matched += 1
            matched_tokens = matched * block_size
            load = float(stats.get("outstanding_tokens", 0.0)) + bumps.get(
                r.actor_id, 0.0
            )
            key = (load - weight * matched_tokens, load)
            if best_key is None or key < best_key:
                best, best_key, best_matched = r, key, matched_tokens
        if best is None:
            return None, "draining"  # every gossiping replica is draining
        # optimistic local debit: what this dispatch will add to the
        # winner's backlog before its next gossip lands
        est = 64.0
        if prompt is not None:
            est = max(1.0, len(prompt) - best_matched) + 64.0
        with self._replicas_lock:
            self._local_tokens[best.actor_id] = (
                self._local_tokens.get(best.actor_id, 0.0) + est
            )
        _count_decision(
            self._deployment, "affinity", affinity_hit=best_matched > 0
        )
        return best, None

    def cluster_pressure(self) -> Dict[str, Any]:
        """Aggregate gossiped engine pressure over the current routing
        set — the ingress tier's shed signal (serve/ingress.py). Sums
        FRESH reports only (``serve_routing_stats_ttl_s``); the local
        optimistic bumps (requests this router dispatched since each
        replica's last gossip) are folded into ``outstanding_tokens`` so
        a burst inside one gossip period registers as pressure
        immediately instead of after the next report lands.

        Non-blocking by design: a shed decision must cost a dict scan,
        never a controller round-trip — with no replicas (or no gossip)
        yet, ``reporting`` is 0 and the caller decides (the ingress
        admits: never shed blind)."""
        self._ensure_poller()
        now = time.monotonic()
        ttl = GLOBAL_CONFIG.serve_routing_stats_ttl_s
        with self._replicas_lock:
            n = len(self._replicas)
            entries = list(self._rstats.values())
            local = sum(self._local_tokens.values())
        queue_depth = 0
        outstanding = 0.0
        max_queue = 0
        reporting = 0
        itl = 0.0
        ttft = 0.0
        for received, stats, _digest, _stamp in entries:
            if now - received > ttl:
                continue
            reporting += 1
            queue_depth += int(stats.get("queue_depth") or 0)
            outstanding += float(stats.get("outstanding_tokens") or 0.0)
            max_queue += int(stats.get("max_queue_depth") or 0)
            # SLO autopilot signals: the WORST fresh replica's windowed
            # tail latencies — the ingress derives its load watermark
            # from measured ITL (effective_shed_threshold), and a tail
            # SLO is only as good as the slowest replica serving it
            itl = max(itl, float(stats.get("itl_p99_s", 0.0) or 0.0))
            ttft = max(ttft, float(stats.get("ttft_p99_s", 0.0) or 0.0))
        return {
            "replicas": n,
            "reporting": reporting,
            "queue_depth": queue_depth,
            "outstanding_tokens": outstanding + local,
            "max_queue_depth": max_queue,
            "itl_p99_s": itl,
            "ttft_p99_s": ttft,
        }

    def _queue_len(self, replica) -> float:
        now = time.monotonic()
        key = replica.actor_id
        entry = self._stats.get(key)
        if entry is not None and now - entry[0] < _STATS_TTL_S:
            return entry[1]
        try:
            ongoing = float(
                ray_tpu.get(replica.stats.remote(), timeout=10)["ongoing"]
            )
        except Exception:
            ongoing = 0.0
        self._stats[key] = (now, ongoing)
        return ongoing

    def _bump(self, replica) -> None:
        # optimistic local bump so a burst within the TTL window spreads
        # instead of dogpiling the momentarily-shortest queue
        entry = self._stats.get(replica.actor_id)
        if entry is not None:
            self._stats[replica.actor_id] = (entry[0], entry[1] + 1.0)

    # -- dispatch ---------------------------------------------------------
    def dispatch(self, method: str, args, kwargs, model_id: str = ""):
        """At-most-once: returns the replica call's ObjectRef."""
        # a serve request is a trace ENTRY POINT: sample a root here (or
        # inherit the caller's ambient trace) so the replica push — and
        # everything the replica does — parents to this dispatch span
        with _tracing.root_span(
            f"serve::{self._deployment}.{method}", "serve"
        ):
            replica = self.choose_replica(model_id, args)
            self._bump(replica)
            return replica.handle_request.remote(
                method, list(args), dict(kwargs or {}), model_id
            )

    def dispatch_stream(self, method: str, args, kwargs, model_id: str = ""):
        """Streaming call: returns the replica generator's ref iterator."""
        with _tracing.root_span(
            f"serve::{self._deployment}.{method}", "serve"
        ):
            replica = self.choose_replica(model_id, args)
            self._bump(replica)
            return replica.handle_request_streaming.options(
                num_returns="streaming"
            ).remote(method, list(args), dict(kwargs or {}), model_id)

    def execute(
        self,
        method: str,
        args,
        kwargs,
        *,
        model_id: str = "",
        timeout: Optional[float] = 60.0,
        idempotent: bool = True,
    ):
        """Retry-until-executed (reference router semantics): a dispatch
        that lands on a dying replica re-chooses. App-level exceptions
        are NOT retried — only replica death/crash.

        RETRY CONTRACT — three tiers, strongest guarantee that each call
        shape can soundly get:

        1. **Idempotent auto-retry** (``idempotent=True``, the default):
           retry-until-executed across ANY failure, including replica
           death. At-least-once — the runtime cannot tell "replica died
           before it saw the push" apart from "replica executed (part
           of) the request, then died", so a non-idempotent request (a
           payment, an append) can run twice after an unlucky crash.
           Only sound for idempotent handlers.
        2. **Exactly-once while reachable** (``idempotent=False``):
           auto-retry is confined to the provably-safe cases. While the
           chosen replica is REACHABLE every retry rides the RPC layer's
           request-id dedup (core/rpc.py via core_worker request-id
           reuse): a lost reply or transient connection reset is
           answered from the replica's reply cache instead of
           re-executing. Submission-side failures (the push provably
           never reached a replica) re-choose freely. A post-dispatch
           replica DEATH propagates — the reply cache died with the
           process, so the caller owns the cross-replica decision.
        3. **Exactly-once token delivery for resumable streams**
           (``execute_stream`` on methods the deployment declares in
           its callable's ``resumable_streams``): items carry a
           per-request monotonic seq; a mid-stream replica death is
           resumed on a survivor with the original prompt extended by
           the already-delivered tokens and ``resume_from=seq``, and
           the SeqGate suppresses boundary duplicates — the
           client-visible sequence has no gaps and no repeats even
           across multiple deaths. REPLAY-SAFETY CAVEAT: resume is only
           sound for side-effect-free DETERMINISTIC generation (same
           params + request seed + prompt → same items; the engine keys
           sampling on ``(seed, position)`` for exactly this). A stream
           with external side effects per item, or nondeterministic
           items, must not be declared resumable — the replayed prefix
           would re-run its effects or fork the sequence.
           Non-resumable streams keep the old contract: replay only
           before the first item, mid-stream death propagates.

        One Deadline covers the whole call (core/deadline.py): dispatch
        retries AND the result get draw from the same budget, clamped by
        any ambient deadline of the caller — inner timeouts never stack."""
        budget = effective_timeout(timeout)
        deadline = Deadline.after(budget if budget is not None else 3600)
        last_err: Optional[Exception] = None
        # trace root covering dispatch retries AND the result get: the
        # replica-side spans parent to this one
        with _tracing.root_span(f"serve::{self._deployment}.{method}", "serve"):
            while not deadline.expired:
                # the replica wait honors the SAME deadline as the call:
                # blocking 30s for a replacement inside a 2s-budget call
                # and then dispatching anyway would return results after
                # the caller's deadline instead of failing it honestly
                replica = self.choose_replica(
                    model_id, args, wait_s=max(1.0, deadline.remaining())
                )
                self._bump(replica)
                try:
                    ref = replica.handle_request.remote(
                        method, list(args), dict(kwargs or {}), model_id
                    )
                except (ActorDiedError, WorkerCrashedError) as e:
                    # submission failed: the request never reached a
                    # replica, safe to re-choose even for non-idempotent
                    # work
                    last_err = e
                    self._drop_replica(replica)
                    continue
                try:
                    remaining = max(1.0, deadline.remaining())
                    return ray_tpu.get(ref, timeout=remaining)
                except (ActorDiedError, WorkerCrashedError) as e:
                    last_err = e
                    self._drop_replica(replica)
                    if not idempotent:
                        # the push may have been delivered and executed —
                        # replaying could duplicate a side effect
                        raise
                    continue
        raise last_err or TimeoutError(
            f"no replica executed {self._deployment}.{method} in time"
        )

    # -- resumable streams -------------------------------------------------
    def _deployment_meta(self) -> Dict[str, Any]:
        """Deployment code/config meta (resumable-streams declaration +
        paired disagg prefill pool), read from the serve controller and
        cached with a TTL — both are properties of the deployed CODE/
        CONFIG, which a redeploy can change under a long-lived handle."""
        cached = self._meta
        if (
            cached is not None
            and time.monotonic() - self._meta_fetched_at < _RESUMABLE_META_TTL_S
        ):
            return cached
        try:
            meta = dict(
                ray_tpu.get(
                    self._controller.deployment_meta.remote(self._deployment),
                    timeout=10,
                )
            )
        except Exception:
            # controller briefly unreachable (failover): serve the stale
            # cache if there is one, else the legacy contract — and
            # retry on the next call either way
            return cached if cached is not None else {
                "resumable_streams": [], "disagg_prefill": None,
            }
        self._meta = meta
        self._meta_fetched_at = time.monotonic()
        return meta

    def _resumable_methods(self) -> frozenset:
        return frozenset(self._deployment_meta().get("resumable_streams") or ())

    # -- cluster KV tier (PR 17) -------------------------------------------
    def _tier_attach(self, prompt: List[int]) -> Optional[Dict[str, Any]]:
        """Longest consecutive root-anchored chain of tier-advertised
        prefix blocks covering ``prompt``, as the ``kv_tier`` request
        spec (``{"blocks": [[digest_hex, desc], ...], "tokens": n}``) —
        or None when the directory covers nothing. The chain digest is
        recomputed HERE from the request's own tokens, so a matched
        descriptor provably holds KV for exactly this prefix (same
        capability-name scheme the replica re-verifies on commit).
        Chains stop one token short of the full prompt: admission needs
        a tail to prefill, exactly like the disagg import."""
        with self._replicas_lock:
            if not self._tier_dir:
                return None
            tier_dir = dict(self._tier_dir)
        from ray_tpu.inference.kv_cache import _chain_digest

        bs = 0
        for ent in tier_dir.values():
            bs = int(ent[0].get("block_size") or 0)
            if bs > 0:
                break
        if bs <= 0 or len(prompt) <= bs:
            return None
        blocks: List[Any] = []
        prev = b""
        for i in range((len(prompt) - 1) // bs):
            d = _chain_digest(
                prev, tuple(int(t) for t in prompt[i * bs : (i + 1) * bs])
            )
            ent = tier_dir.get(d.hex())
            if ent is None:
                break
            blocks.append([d.hex(), ent[0]])
            prev = d
        if not blocks:
            return None
        return {"blocks": blocks, "tokens": len(blocks) * bs}

    def _tier_resume_spec(
        self, prompt: List[int], wait_s: float = 0.0
    ) -> tuple:
        """Tier chain for a RESUME attempt: ``(spec_or_None, covered)``
        where ``covered`` means the chain reaches everything but the
        sub-block tail — the resume is then a fault-in, not a replay,
        and the replay counters must not grow. ``wait_s`` bounds a brief
        poll for adverts still in flight through the gossip (the live-
        migration window: the source flushed its KV a beat ago and the
        stats report carrying the adverts may not have landed yet)."""
        deadline = time.monotonic() + wait_s

        def _covers(spec) -> bool:
            if spec is None:
                return False
            bs = int(spec["tokens"]) // max(1, len(spec["blocks"]))
            return int(spec["tokens"]) >= len(prompt) - bs

        spec = self._tier_attach(prompt)
        while not _covers(spec) and time.monotonic() < deadline:
            time.sleep(0.05)
            spec = self._tier_attach(prompt)
        return spec, _covers(spec)

    # -- disaggregated prefill/decode handoff ------------------------------
    def _disagg_handoff(
        self,
        prefill_dep: str,
        req: Dict[str, Any],
        model_id: str,
        caller_budget: Optional[float] = None,
    ) -> None:
        """Two-stage dispatch, stage one: run the prompt's prefill on
        the PREFILL pool (scored dispatch like any request) and attach
        the returned KV descriptor, so the decode-pool replica imports
        the prompt KV instead of recomputing it. Every failure rung —
        short prompt, prefill-pool death, handoff timeout, empty export
        — degrades to plain single-replica generation (the descriptor
        simply isn't attached) and is counted on
        ``raytpu_kv_migration_fallbacks_total``; the stream itself never
        fails because of the handoff."""
        from ray_tpu.inference.kv_transfer import (
            count_fallback,
            migration_metrics,
        )

        prompt = req.get("prompt") or []
        if len(prompt) < GLOBAL_CONFIG.serve_disagg_min_prompt_tokens:
            count_fallback("short_prompt")
            return
        with self._replicas_lock:
            pr = self._prefill_router
        if pr is None or pr._deployment != prefill_dep:
            pr = Router(self._controller, prefill_dep)
            with self._replicas_lock:
                self._prefill_router = pr
        # the handoff spends the CALLER's budget: blocking the full
        # handoff timeout inside a shorter-deadline stream would delay
        # the decode dispatch past the point the caller already gave up
        # (the same contract the choose_replica clamp enforces)
        handoff_timeout = GLOBAL_CONFIG.serve_disagg_handoff_timeout_s
        if caller_budget is not None:
            handoff_timeout = min(handoff_timeout, caller_budget)
        t0 = time.monotonic()
        try:
            desc = pr.execute(
                "prefill_export",
                [{
                    "prompt": [int(t) for t in prompt],
                    "priority": int(req.get("priority", 0)),
                    "request_id": f"{req['request_id']}.pf",
                }],
                {},
                model_id=model_id,
                timeout=handoff_timeout,
            )
        except Exception:  # noqa: BLE001 — any handoff failure → fallback
            count_fallback("prefill_dispatch")
            return
        if not desc:
            count_fallback("empty_export")
            return
        req["kv_import"] = desc
        migration_metrics()["handoff"].observe(time.monotonic() - t0)

    def execute_stream(
        self,
        method: str,
        args,
        kwargs,
        *,
        model_id: str = "",
        timeout: Optional[float] = 60.0,
    ):
        """Streaming with dispatch retry. Two contracts (tier 2 vs tier
        3 of the ``execute`` docstring):

        * methods the deployment declares in ``resumable_streams`` (and
          whose request is LLM-shaped: a dict with a token ``prompt``)
          get EXACTLY-ONCE TOKEN DELIVERY — mid-stream replica death is
          resumed on a survivor with the prompt extended by the
          already-delivered tokens, duplicates suppressed, no gaps and
          no repeats across any number of deaths;
        * everything else re-chooses only if the stream dies BEFORE the
          first item (nothing was delivered, trivially safe to replay);
          mid-stream death propagates — replaying would duplicate items.

        The Deadline budget covers dispatch + time-to-first-item (and is
        re-armed per failover attempt on the resumable path); after
        that, each item get inherits the CALLER's timeout (None = wait
        forever) — a slow producer mid-stream is backpressure, not a
        dispatch failure, so it must not trip a fixed 60s timer."""
        if method in self._resumable_methods():
            req = args[0] if args and isinstance(args[0], dict) else None
            if req is not None and _request_prompt(args) is not None:
                return self._execute_stream_resumable(
                    method, req, list(args[1:]), kwargs,
                    model_id=model_id, timeout=timeout,
                )
        budget = effective_timeout(timeout)
        deadline = Deadline.after(budget if budget is not None else 3600)
        # per-item patience once streaming: the caller's timeout with any
        # tighter ambient deadline already folded in; None = wait forever
        item_timeout = budget
        last_err: Optional[Exception] = None
        # trace root spanning dispatch → first item (the serve TTFT
        # window); the replica's streaming task span parents to it
        with _tracing.root_span(f"serve::{self._deployment}.{method}", "serve"):
            while not deadline.expired:
                replica = self.choose_replica(
                    model_id, args, wait_s=max(1.0, deadline.remaining())
                )
                self._bump(replica)
                gen = replica.handle_request_streaming.options(
                    num_returns="streaming"
                ).remote(method, list(args), dict(kwargs or {}), model_id)
                try:
                    # bounded time-to-first-item: a replica stuck before
                    # its first yield must not park this request forever
                    first_ref = gen.next_with_timeout(
                        max(1.0, deadline.remaining())
                    )
                    first = ray_tpu.get(
                        first_ref, timeout=max(1.0, deadline.remaining())
                    )
                except StopIteration:
                    def _empty():
                        return
                        yield  # pragma: no cover
                    return _empty()
                except (ActorDiedError, WorkerCrashedError) as e:
                    last_err = e
                    self._drop_replica(replica)
                    continue
                def _rest(first=first, gen=gen):
                    try:
                        # TokenChunk = a producer-coalesced burst (one
                        # ref per engine wake-up); flatten so consumers
                        # see the per-token stream. Bare lists pass
                        # through — a generic stream may yield them as
                        # VALUES.
                        if isinstance(first, TokenChunk):
                            yield from first
                        else:
                            yield first
                        for ref in gen:
                            item = ray_tpu.get(ref, timeout=item_timeout)
                            if isinstance(item, TokenChunk):
                                yield from item
                            else:
                                yield item
                    finally:
                        # consumer done OR walked away (close()/GC — an
                        # HTTP client disconnect closes this generator):
                        # release the ref stream and cooperatively cancel
                        # a still-running producer so the replica's
                        # engine request is cancelled and frees its KV
                        # blocks instead of decoding for nobody
                        gen.abandon()

                return _rest()
        raise last_err or TimeoutError(
            f"no replica started stream {self._deployment}.{method} in time"
        )

    def _execute_stream_resumable(
        self,
        method: str,
        req: Dict[str, Any],
        extra_args: List[Any],
        kwargs,
        *,
        model_id: str = "",
        timeout: Optional[float] = 60.0,
    ):
        """Exactly-once token delivery across replica death (tier 3).

        The request's identity is pinned BEFORE the first dispatch —
        ``request_id`` and, for sampled generation, an explicit ``seed``
        — so any replica that (re)runs it derives the identical token
        stream (engine sampling is keyed on ``(seed, position)``). Every
        attempt carries ``resume_from`` = the count of tokens already
        delivered to the client, with the prompt extended by exactly
        those tokens; replicas answer with ``(seq, token)`` pairs and
        the SeqGate admits each seq exactly once. The replayed prefix is
        an exact radix-cache prefix on any replica that served (part of)
        the stream's deployment traffic, so a warm survivor resumes at
        near-warm TTFT (bench: ``serve_llm_resume_ttft_p50``)."""
        budget = effective_timeout(timeout)
        req = dict(req)
        req.setdefault("request_id", uuid.uuid4().hex[:16])
        if req.get("seed") is None and float(req.get("temperature", 0.0)) > 0.0:
            # sampled generation MUST replay under one pinned seed; the
            # engine's id-derived fallback seed would also work, but an
            # explicit stamp survives request_id suffixing across attempts
            req["seed"] = int.from_bytes(os.urandom(4), "little")
        # disaggregated serving: compute the prompt KV on the prefill
        # pool first, attach the migration descriptor for the decode
        # replica (identity is already pinned, so the handoff changes
        # WHERE the prefill runs, never what the client sees)
        prefill_dep = self._deployment_meta().get("disagg_prefill")
        if prefill_dep and "kv_import" not in req:
            self._disagg_handoff(prefill_dep, req, model_id, budget)
        base_prompt = [int(t) for t in req["prompt"]]
        if "kv_import" not in req and "kv_tier" not in req:
            # cluster-tier warm admission: a fresh dispatch whose prefix
            # chain is tier-resident anywhere imports it instead of
            # prefilling — this is what makes a controller-spawned
            # replacement WARM from its first request (the dead
            # replica's adverts outlive it in the directory)
            spec = self._tier_attach(base_prompt)
            if spec is not None:
                req["kv_tier"] = spec
        base_rid = str(req["request_id"])
        gate = SeqGate(0)
        delivered: List[int] = []
        item_timeout = budget
        # router-tier SLO ledger: the router is the only tier that SEES
        # a failover (the engines on either side each saw a normal
        # request), so the stage that makes a resumed outlier slow —
        # detection + re-dispatch + warm replay — is stamped here and
        # joined with the engine-tier entries by request id in
        # serve.slo_report()
        led: Dict[str, Any] = {
            "tier": "router",
            "request_id": base_rid,
            "deployment": self._deployment,
            "tenant_class": str(req.get("tenant_class") or ""),
            "trace_id": None,
            "outcome": "abandoned",
            "resumes": 0,
            "replayed_tokens": 0,
            "stages": {},
            "flags": [],
        }
        # resumable streams observe the SLO latency histograms at THIS
        # tier, not the engine: the router sees what the client sees —
        # failover stalls count as real (slow) inter-token gaps, and the
        # samples survive a replica SIGKILL (an engine's in-memory
        # counts die with its process; the consumer's don't). The
        # replicas are told to stand down via ``slo_observer`` so one
        # request is never observed twice.
        from ray_tpu.observability.slo import slo_metrics

        _slo_hist = slo_metrics()
        _slo_labels = {
            "deployment": self._deployment,
            "tenant_class": led["tenant_class"],
        }

        def _finalize_led(t_start: float, first_at: Optional[float]) -> None:
            now = time.monotonic()
            if first_at is not None:
                led["ttft_s"] = round(first_at - t_start, 6)
            led["e2e_s"] = round(now - t_start, 6)
            if led["outcome"] != "abandoned":
                # a walked-away client's e2e is its own choice, not
                # service latency; completed and failed streams count
                _slo_hist["e2e"].observe(now - t_start, labels=_slo_labels)
            flags = []
            if led["resumes"]:
                flags.append("resumed")
            if led["outcome"] == "error":
                flags.append("error")
            if (
                led.get("ttft_s") is not None
                and led["ttft_s"] > GLOBAL_CONFIG.slo_ttft_slow_s
            ):
                flags.append("slow_ttft")
            if led.get("max_itl_s", 0.0) > GLOBAL_CONFIG.slo_itl_slow_s:
                flags.append("slow_itl")
            led["flags"] = flags
            from ray_tpu.observability.slo import flight_recorder

            flight_recorder().add(
                led,
                flagged=bool(flags),
                slow_key=led["e2e_s"],
            )

        def _gen():
            wire = _tracing.current_wire()
            if wire is not None:
                led["trace_id"] = wire[0]
            t_start = time.monotonic()
            first_at: Optional[float] = None
            last_tok_at: Optional[float] = None
            #: set when a failover is in progress: the wall time the
            #: death was observed — the next delivered token closes the
            #: "failover" stage (detection + re-dispatch + warm replay,
            #: measured from the LAST delivered token when one exists:
            #: that gap is exactly what the client perceived)
            failover_since: Optional[float] = None
            attempt = 0
            barren = 0
            #: tier chain computed at the LAST failover for the extended
            #: prompt (base + delivered) — attached to the next attempt
            #: so the survivor faults the stream's KV in instead of
            #: replaying it through prefill
            pending_tier: Optional[Dict[str, Any]] = None
            last_err: Optional[Exception] = None
            try:
                while True:
                    attempt_req = dict(req)
                    attempt_req["resume_from"] = gate.next_seq
                    # this tier owns the latency histograms (see above):
                    # the replica's engine must not observe its own —
                    # possibly warm-replayed — view of the same request
                    attempt_req["slo_observer"] = "router"
                    if attempt:
                        # replay identity: same logical request, new engine
                        # intake (a replica that already saw base_rid — e.g.
                        # one that stalled and recovered — must not reject
                        # the resume as a duplicate submission)
                        attempt_req["prompt"] = base_prompt + delivered
                        attempt_req["request_id"] = f"{base_rid}.r{attempt}"
                        # mark the attempt so the replica keeps its warm
                        # replay OUT of the SLO latency histograms (the
                        # failover cost the client saw is stamped on THIS
                        # tier's ledger entry below)
                        attempt_req["resume_attempt"] = attempt
                        # the KV descriptor belongs to attempt 0's dispatch:
                        # a resume survivor warm-replays through its own
                        # radix cache (PR 10) — or, preferably, faults the
                        # whole chain in from the cluster tier (PR 17):
                        # the pending_tier spec computed at failover time
                        # replaces the single-consumer kv_import
                        attempt_req.pop("kv_import", None)
                        attempt_req.pop("kv_tier", None)
                        if pending_tier is not None:
                            attempt_req["kv_tier"] = pending_tier
                    # per-attempt budget: a resume is a fresh dispatch +
                    # time-to-next-token window, not a continuation of the
                    # first attempt's (possibly spent) dispatch budget
                    deadline = Deadline.after(budget if budget is not None else 3600)
                    progress_before = gate.next_seq
                    replica = None
                    gen = None
                    try:
                        try:
                            replica = self.choose_replica(model_id, [attempt_req])
                        except RuntimeError as e:
                            # "no replicas": every candidate died and the
                            # controller's replacement hasn't registered yet
                            # — a routing condition, not a stream failure;
                            # retry under the barren-attempt bound
                            last_err = e
                            barren += 1
                            if barren >= _MAX_BARREN_RESUMES:
                                raise
                            attempt += 1
                            continue
                        self._bump(replica)
                        gen = replica.handle_request_streaming.options(
                            num_returns="streaming"
                        ).remote(
                            method, [attempt_req] + extra_args,
                            dict(kwargs or {}), model_id,
                        )
                        first = True
                        while True:
                            try:
                                if first:
                                    # bounded time-to-first(-resumed)-item
                                    ref = gen.next_with_timeout(
                                        max(1.0, deadline.remaining())
                                    )
                                else:
                                    # production wait is unbounded, like the
                                    # non-resumable path: a slow producer is
                                    # backpressure, and a DEAD one fails the
                                    # stream (waking this wait) regardless
                                    ref = gen.next_with_timeout(None)
                            except StopIteration:
                                led["outcome"] = "ok"
                                return
                            item = ray_tpu.get(
                                ref,
                                timeout=max(1.0, deadline.remaining())
                                if first
                                else item_timeout,
                            )
                            first = False
                            # one stream item = one producer burst
                            # (TokenChunk of (seq, token) pairs) or a
                            # single bare pair from an older callable
                            pairs = (
                                item
                                if isinstance(item, TokenChunk)
                                else [item]
                            )
                            for pair in pairs:
                                try:
                                    seq, token = pair
                                except (TypeError, ValueError):
                                    # a redeploy swapped in a callable
                                    # that no longer speaks the seq
                                    # protocol while this stream (or a
                                    # stale cache window) was live
                                    raise RuntimeError(
                                        f"resumable stream "
                                        f"{self._deployment}.{method} "
                                        f"yielded {type(pair).__name__}, "
                                        "not a (seq, item) pair — was "
                                        "the deployment redeployed "
                                        "without resumable_streams?"
                                    ) from None
                                if not gate.admit(seq):
                                    continue
                                now = time.monotonic()
                                if first_at is None:
                                    first_at = now
                                    _slo_hist["ttft"].observe(
                                        now - t_start, labels=_slo_labels
                                    )
                                elif last_tok_at is not None:
                                    # the client-perceived gap: a
                                    # failover stall lands HERE as one
                                    # honest slow sample
                                    gap = now - last_tok_at
                                    if gap > led.get("max_itl_s", 0.0):
                                        led["max_itl_s"] = round(gap, 6)
                                    _slo_hist["itl"].observe(
                                        gap, labels=_slo_labels
                                    )
                                if failover_since is not None:
                                    # the failover stage the client saw:
                                    # last delivered token (or the death,
                                    # when none was) → first resumed token
                                    led["stages"]["failover"] = round(
                                        led["stages"].get("failover", 0.0)
                                        + (
                                            now
                                            - (
                                                last_tok_at
                                                if last_tok_at is not None
                                                else failover_since
                                            )
                                        ),
                                        6,
                                    )
                                    failover_since = None
                                last_tok_at = now
                                delivered.append(token)
                                barren = 0
                                yield token
                    except _REPLICA_GONE as e:
                        last_err = e
                        if replica is not None:
                            self._drop_replica(replica)
                        if gate.next_seq == progress_before:
                            barren += 1
                            if barren >= _MAX_BARREN_RESUMES:
                                raise
                        attempt += 1
                        led["resumes"] += 1
                        # tier-first failover: when the directory holds
                        # the stream's whole chain (dead-holder entries
                        # included — the daemon outlives the replica),
                        # the survivor faults it in and the delivered
                        # tokens are NOT replay work — both replay sinks
                        # (counter and ledger) get the same gated value.
                        # A covered chain whose fault-in then FAILS on
                        # the survivor is reconciled replica-side
                        # (LLMServer._reconcile_tier_replay books the
                        # shortfall), so replayed=0 here is not final.
                        pending_tier, covered = self._tier_resume_spec(
                            base_prompt + delivered
                        )
                        replayed = 0 if covered else len(delivered)
                        led["replayed_tokens"] += replayed
                        if failover_since is None:
                            failover_since = time.monotonic()
                        _count_stream_resume(self._deployment, replayed)
                        continue
                    except Exception as e:
                        from ray_tpu.inference.kv_transfer import (
                            KV_MIGRATION_MARKER,
                        )

                        if KV_MIGRATION_MARKER not in str(e):
                            raise
                        # live decode migration: a draining replica
                        # flushed this stream's FULL KV (prompt +
                        # generated) into the tier and failed the
                        # request with the resumable marker. Same
                        # failover machinery as a death — but the
                        # replica is alive (don't drop it; its gossip
                        # says draining, so scoring routes around it)
                        # and the adverts may still be in flight, so
                        # the spec poll waits a few gossip beats.
                        last_err = e
                        if gate.next_seq == progress_before:
                            barren += 1
                            if barren >= _MAX_BARREN_RESUMES:
                                raise
                        attempt += 1
                        led["resumes"] += 1
                        pending_tier, covered = self._tier_resume_spec(
                            base_prompt + delivered,
                            wait_s=max(
                                1.0,
                                3 * GLOBAL_CONFIG.serve_replica_stats_period_s,
                            ),
                        )
                        replayed = 0 if covered else len(delivered)
                        led["replayed_tokens"] += replayed
                        if failover_since is None:
                            failover_since = time.monotonic()
                        _count_stream_resume(self._deployment, replayed)
                        continue
                    finally:
                        # every exit — normal end, failover to the next
                        # attempt, consumer close (GeneratorExit lands at the
                        # yield above) — releases this attempt's ref stream
                        # and cancels a still-running producer, so a client
                        # that disconnects mid-stream frees the engine slot
                        if gen is not None:
                            gen.abandon()
            except GeneratorExit:
                raise  # consumer walked away: outcome stays "abandoned"
            except BaseException as e:
                led["outcome"] = "error"
                led["error"] = repr(e)
                raise
            finally:
                _finalize_led(t_start, first_at)

        # prime the first token eagerly (matching the non-resumable
        # path: dispatch problems raise at call time, not first next())
        # under the serve trace root covering dispatch → first item
        with _tracing.root_span(f"serve::{self._deployment}.{method}", "serve"):
            g = _gen()
            try:
                first_token = next(g)
            except StopIteration:
                def _empty():
                    return
                    yield  # pragma: no cover
                return _empty()
        return itertools.chain([first_token], g)
