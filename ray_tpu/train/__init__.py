"""ray_tpu.train — distributed training orchestration, TPU-first.

Reference: ``python/ray/train`` (Trainer/BackendExecutor/WorkerGroup/
session — see trainer.py docstring for the mapping). The flagship entry
point is ``JaxTrainer``; sharding/parallelism *inside* the training step
lives in ``ray_tpu.parallel`` (mesh/pjit/shard_map) and ``ray_tpu.ops``
(pallas kernels) — the trainer orchestrates processes, XLA moves bytes.
"""

from ray_tpu.train.backend import Backend, BackendConfig, JaxBackend, JaxBackendConfig
from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager
from ray_tpu.train.config import (
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train.session import (
    TrainContext,
    get_checkpoint,
    get_context,
    get_dataset_shard,
    get_mesh,
    get_sharding_rules,
    report,
    urgent_checkpoint_requested,
)
from ray_tpu.train.trainer import JaxTrainer, Result, TrainingFailedError
from ray_tpu.train.worker_group import TrainWorker, WorkerGroup

__all__ = [
    "Backend",
    "BackendConfig",
    "Checkpoint",
    "CheckpointConfig",
    "CheckpointManager",
    "FailureConfig",
    "JaxBackend",
    "JaxBackendConfig",
    "JaxTrainer",
    "Result",
    "RunConfig",
    "ScalingConfig",
    "TrainContext",
    "TrainingFailedError",
    "TrainWorker",
    "WorkerGroup",
    "get_checkpoint",
    "get_context",
    "get_dataset_shard",
    "get_mesh",
    "get_sharding_rules",
    "report",
    "urgent_checkpoint_requested",
]
