"""Training backends: per-framework worker-group setup hooks.

Reference: ``train/_internal/backend_executor.py`` Backend plugin protocol
+ ``train/torch/config.py:66-116`` (the NCCL/gloo rendezvous this replaces)
and ``train/torch/xla/config.py`` (the XLA variant). TPU-native redesign:
the backend's job is *jax.distributed* bootstrap — rank 0 publishes a
coordinator address; every worker calls ``jax.distributed.initialize`` so
one global device mesh spans all slice hosts and XLA collectives ride ICI.
There is no per-op communicator plumbing to set up: collectives live
inside the compiled program.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Dict, Optional

from ray_tpu.parallel.mesh import MeshSpec
from ray_tpu.train.worker_group import WorkerGroup

logger = logging.getLogger(__name__)


@dataclass
class BackendConfig:
    """Base; subclasses pick the backend class."""

    def backend_cls(self):
        return Backend


class Backend:
    """Hooks around the worker group lifecycle."""

    def on_start(self, worker_group: WorkerGroup, backend_config: BackendConfig) -> None:
        pass

    def on_training_start(self, worker_group: WorkerGroup, backend_config: BackendConfig) -> None:
        pass

    def on_shutdown(self, worker_group: WorkerGroup, backend_config: BackendConfig) -> None:
        pass


@dataclass
class JaxBackendConfig(BackendConfig):
    """jax.distributed bootstrap config.

    distributed=None → auto (initialize when num_workers > 1).
    platform: force ``JAX_PLATFORMS`` in workers (tests: ``"cpu"``).

    mesh_spec/sharding: the gang's UNIFIED parallelism plan. When set,
    every worker can call ``train.get_mesh()`` /
    ``train.get_sharding_rules()`` after the rendezvous and receive the
    SAME global mesh (built over all gang devices; ``-1`` axes resolve
    against the global device count) and the same canonical rules table
    ("ddp" | "fsdp" | "tp") — the one named-sharding source of truth the
    constrained train step (``models/llama.py::make_train_step``)
    derives every param/grad/optimizer-state spec from. Declaring the
    plan HERE rather than in each worker loop is what guarantees all
    ranks compile the identical pjit program (SPMD requires it)."""

    distributed: Optional[bool] = None
    platform: Optional[str] = None
    extra_env: Optional[Dict[str, str]] = None
    mesh_spec: Optional[MeshSpec] = None
    sharding: Optional[str] = None  # "ddp" | "fsdp" | "tp"

    def backend_cls(self):
        return JaxBackend


def _jax_distributed_init(context) -> None:
    """Runs inside each worker, before the user loop (setup_fn)."""
    import os

    coordinator = os.environ.get("RAY_TPU_JAX_COORDINATOR")
    if not coordinator:
        return
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=context.world_size,
        process_id=context.world_rank,
    )


class JaxBackend(Backend):
    def on_start(self, worker_group: WorkerGroup, backend_config: JaxBackendConfig) -> None:
        n = worker_group.num_workers
        distributed = (
            backend_config.distributed
            if backend_config.distributed is not None
            else n > 1
        )
        env: Dict[str, str] = dict(backend_config.extra_env or {})
        if backend_config.platform:
            env["JAX_PLATFORMS"] = backend_config.platform
        if distributed:
            # Rank 0's host + a free port = the jax.distributed coordinator
            # (replaces the reference's torch worker-0 TCP rendezvous,
            # train/torch/config.py:66-116).
            addr = worker_group.execute_single(0, "get_address", timeout=30)
            env["RAY_TPU_JAX_COORDINATOR"] = f"{addr['host']}:{addr['free_port']}"
        if env:
            worker_group.execute("set_env", env, timeout=30)

    def setup_fn(self):
        """Per-worker pre-loop hook handed to start_training."""
        return _jax_distributed_init
