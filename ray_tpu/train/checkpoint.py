"""Checkpoints: directory handles + top-K retention.

Reference: ``python/ray/train/_checkpoint.py`` (Checkpoint = directory on
a filesystem), ``train/_internal/checkpoint_manager.py`` (top-K by score)
and ``train/_internal/storage.py`` (StorageContext path resolution).
Local/NFS/GCS-fuse paths only — no pyarrow.fs dependency; TPU pods mount
shared storage, which is the same assumption orbax makes.
"""

from __future__ import annotations

import json
import os
import pickle
import shutil
import tempfile
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional


class Checkpoint:
    """A directory handle (reference ``ray.train.Checkpoint``)."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    # -- constructors ----------------------------------------------------
    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Checkpoint":
        """Convenience for small states (tests, Tune trials)."""
        d = tempfile.mkdtemp(prefix="ray_tpu_ckpt_")
        with open(os.path.join(d, "_dict_checkpoint.pkl"), "wb") as f:
            pickle.dump(data, f)
        return cls(d)

    # -- accessors -------------------------------------------------------
    def to_directory(self, path: Optional[str] = None) -> str:
        if path is None:
            return self.path
        os.makedirs(path, exist_ok=True)
        shutil.copytree(self.path, path, dirs_exist_ok=True)
        return path

    @contextmanager
    def as_directory(self):
        yield self.path

    def to_dict(self) -> Dict[str, Any]:
        with open(os.path.join(self.path, "_dict_checkpoint.pkl"), "rb") as f:
            return pickle.load(f)

    def __reduce__(self):
        return (Checkpoint, (self.path,))

    def __repr__(self) -> str:
        return f"Checkpoint({self.path})"


class CheckpointManager:
    """Registers reported checkpoints under ``{run_dir}/checkpoint_N`` and
    enforces ``num_to_keep`` (best-by-score or most-recent)."""

    def __init__(self, run_dir: str, num_to_keep: Optional[int] = None,
                 score_attribute: Optional[str] = None, score_order: str = "max"):
        self.run_dir = run_dir
        self.num_to_keep = num_to_keep
        self.score_attribute = score_attribute
        self.score_order = score_order
        self._index = 0
        #: list of (path, metrics)
        self.registered: List[tuple] = []
        os.makedirs(run_dir, exist_ok=True)

    def register(self, checkpoint: Checkpoint, metrics: Dict[str, Any]) -> Checkpoint:
        """Move a worker-produced checkpoint into the run dir."""
        dest = os.path.join(self.run_dir, f"checkpoint_{self._index:06d}")
        self._index += 1
        src = os.path.abspath(checkpoint.path)
        if src != dest:
            staging_root = os.path.join(os.path.abspath(self.run_dir), "_staged")
            if src.startswith(staging_root + os.sep) and not os.path.exists(dest):
                # Session-staged copies are transport-only and already live
                # on the run_dir filesystem — a rename beats a second full
                # copy of a multi-GB checkpoint.
                shutil.move(src, dest)
            else:
                shutil.copytree(src, dest, dirs_exist_ok=True)
        final = Checkpoint(dest)
        self.registered.append((dest, dict(metrics)))
        self._write_manifest()
        self._enforce_retention()
        return final

    def latest(self) -> Optional[Checkpoint]:
        if not self.registered:
            return None
        return Checkpoint(self.registered[-1][0])

    def best(self) -> Optional[Checkpoint]:
        if not self.registered:
            return None
        if not self.score_attribute:
            return self.latest()
        scored = [r for r in self.registered if self.score_attribute in r[1]]
        if not scored:
            return self.latest()
        key = lambda r: r[1][self.score_attribute]  # noqa: E731
        pick = max(scored, key=key) if self.score_order == "max" else min(scored, key=key)
        return Checkpoint(pick[0])

    def _enforce_retention(self) -> None:
        if self.num_to_keep is None:
            return
        while len(self.registered) > self.num_to_keep:
            # Never delete the best checkpoint when scoring is configured.
            best = self.best()
            for i, (path, _) in enumerate(self.registered):
                if best is None or path != best.path:
                    victim = self.registered.pop(i)
                    shutil.rmtree(victim[0], ignore_errors=True)
                    break
            else:
                break
        self._write_manifest()

    def _write_manifest(self) -> None:
        manifest = {
            "updated_at": time.time(),
            "checkpoints": [
                {"path": p, "metrics": m} for p, m in self.registered
            ],
        }
        with open(os.path.join(self.run_dir, "checkpoints.json"), "w") as f:
            json.dump(manifest, f, indent=1)

    @classmethod
    def restore(cls, run_dir: str, **kwargs) -> "CheckpointManager":
        """Resume retention state from a previous run's manifest."""
        mgr = cls(run_dir, **kwargs)
        manifest_path = os.path.join(run_dir, "checkpoints.json")
        if os.path.exists(manifest_path):
            with open(manifest_path) as f:
                manifest = json.load(f)
            mgr.registered = [
                (c["path"], c["metrics"])
                for c in manifest.get("checkpoints", [])
                if os.path.isdir(c["path"])
            ]
            if mgr.registered:
                last = os.path.basename(mgr.registered[-1][0])
                try:
                    mgr._index = int(last.split("_")[-1]) + 1
                except ValueError:
                    mgr._index = len(mgr.registered)
        return mgr
