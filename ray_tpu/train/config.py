"""Train configuration dataclasses.

Reference: ``python/ray/air/config.py`` (`ScalingConfig`, `RunConfig`,
`FailureConfig`, `CheckpointConfig`). TPU-first deltas: ``use_tpu`` +
``topology`` (a pod-slice type like ``"v4-32"``) replace ``use_gpu``; a
topology implies one worker per slice host, gang-reserved via a
STRICT_SPREAD placement group (partial slices are useless).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class ScalingConfig:
    """How many training workers, and what each one holds.

    Reference: ``air/config.py`` ScalingConfig (num_workers,
    use_gpu→use_tpu, resources_per_worker, placement_strategy).
    """

    num_workers: int = 1
    use_tpu: bool = False
    #: Pod-slice type (e.g. ``"v4-32"``). Overrides num_workers to the
    #: slice's host count and gangs one worker per host.
    topology: Optional[str] = None
    resources_per_worker: Optional[Dict[str, float]] = None
    placement_strategy: str = "PACK"

    def worker_resources(self) -> Dict[str, float]:
        if self.resources_per_worker is not None:
            res = dict(self.resources_per_worker)
            # An explicit TPU count wins; otherwise topology decides.
            if self.topology and "TPU" not in res:
                res["TPU"] = self._chips_per_host()
        elif self.use_tpu or self.topology:
            # Topology is authoritative: a v5e/v6e host has 8 chips, not
            # the bare use_tpu default of 4.
            res = {"CPU": 1.0, "TPU": self._chips_per_host()}
        else:
            res = {"CPU": 1.0}
        return res

    def _chips_per_host(self) -> float:
        if self.topology:
            from ray_tpu.accelerators import (
                pod_type_chips_per_host,
                pod_type_num_chips,
            )

            # Sub-host slices (v5e-4 = 4 chips on an 8-chip host machine)
            # expose only their own chips — never request more than the
            # slice has in total.
            return float(
                min(
                    pod_type_chips_per_host(self.topology),
                    pod_type_num_chips(self.topology),
                )
            )
        return 4.0

    def resolved_num_workers(self) -> int:
        if self.topology:
            from ray_tpu.accelerators import pod_type_num_hosts

            return pod_type_num_hosts(self.topology)
        return self.num_workers

    def bundles(self) -> List[Dict[str, float]]:
        per_worker = self.worker_resources()
        n = self.resolved_num_workers()
        bundles = [dict(per_worker) for _ in range(n)]
        if self.topology:
            from ray_tpu.accelerators import slice_head_resource_name

            bundles[0][slice_head_resource_name(self.topology)] = 1.0
        return bundles

    def pg_strategy(self) -> str:
        # One worker per host for real slices; tests pack on one machine.
        if self.topology:
            return "STRICT_SPREAD"
        return self.placement_strategy


@dataclass
class FailureConfig:
    """Reference: ``air/config.py:394-408`` — how many times fit() may
    restart the worker group from the latest checkpoint."""

    max_failures: int = 0


@dataclass
class CheckpointConfig:
    """Reference: ``air/config.py`` CheckpointConfig (num_to_keep etc.)."""

    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"


@dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)
    #: LoggerCallback instances (reference ``RunConfig.callbacks`` —
    #: CSV/JSON/TensorBoard in ``ray_tpu.tune.loggers``)
    callbacks: list = field(default_factory=list)
