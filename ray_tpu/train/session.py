"""Worker-side training session: context + report channel.

Reference: ``train/_internal/session.py:111,403,667`` — the per-worker
session object behind ``train.report`` / ``train.get_context``. Redesign:
the user loop runs on a plain thread inside the TrainWorker actor; each
``report(metrics, checkpoint=...)`` enqueues onto a thread-safe queue the
trainer drains via the ``poll_results`` actor method (pull, not push — the
driver controls pacing, and a dead driver can't wedge a worker).
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from ray_tpu.train.checkpoint import Checkpoint


@dataclass
class TrainContext:
    world_size: int = 1
    world_rank: int = 0
    local_rank: int = 0
    node_rank: int = 0
    experiment_name: str = ""
    trial_dir: str = ""
    #: checkpoint to resume from (set on group restart)
    checkpoint: Optional[Checkpoint] = None
    metadata: Dict[str, Any] = field(default_factory=dict)

    def get_world_size(self) -> int:
        return self.world_size

    def get_world_rank(self) -> int:
        return self.world_rank

    def get_local_rank(self) -> int:
        return self.local_rank

    def get_node_rank(self) -> int:
        return self.node_rank

    def get_trial_dir(self) -> str:
        return self.trial_dir

    def get_experiment_name(self) -> str:
        return self.experiment_name


class _Session:
    """One per worker process while a training run is active."""

    def __init__(self, context: TrainContext):
        self.context = context
        self.results: "queue.Queue[Dict[str, Any]]" = queue.Queue()
        self.finished = threading.Event()
        self.error: Optional[BaseException] = None
        self._report_seq = 0
        # preemption warning: set when the trainer learns a node hosting
        # this gang is DRAINING — the user loop checkpoints at its next
        # step boundary instead of waiting for the periodic cadence
        self.urgent_checkpoint = threading.Event()

    def report(self, metrics: Dict[str, Any], checkpoint: Optional[Checkpoint] = None) -> None:
        checkpoint = self._stage_checkpoint(checkpoint)
        if checkpoint is not None:
            # any checkpoint satisfies an outstanding urgent request
            self.urgent_checkpoint.clear()
        self.results.put({"metrics": dict(metrics), "checkpoint": checkpoint})

    def _stage_checkpoint(self, checkpoint: Optional[Checkpoint]) -> Optional[Checkpoint]:
        """Re-home a node-local checkpoint under the shared trial dir.

        A Checkpoint pickles as a bare path; one created in a worker's
        /tmp is unreadable from the driver on a multi-host gang. The
        trial_dir is on shared storage (the same assumption orbax makes),
        so copying there at report time makes the path valid everywhere.
        """
        trial_dir = self.context.trial_dir
        if checkpoint is None or not trial_dir:
            return checkpoint
        import os
        import shutil

        abs_path = os.path.abspath(checkpoint.path)
        if abs_path.startswith(os.path.abspath(trial_dir) + os.sep):
            return checkpoint
        dest = os.path.join(
            trial_dir,
            "_staged",
            f"rank_{self.context.world_rank:04d}_{self._report_seq:06d}",
        )
        self._report_seq += 1
        shutil.copytree(abs_path, dest, dirs_exist_ok=True)
        return Checkpoint(dest)

    def drain(self, max_items: int = 64):
        out = []
        while len(out) < max_items:
            try:
                out.append(self.results.get_nowait())
            except queue.Empty:
                break
        return out


_session: Optional[_Session] = None
_session_lock = threading.Lock()


def _start_session(context: TrainContext) -> _Session:
    global _session
    with _session_lock:
        _session = _Session(context)
        return _session


def _end_session() -> None:
    global _session
    with _session_lock:
        _session = None


def _get_session() -> _Session:
    if _session is None:
        raise RuntimeError(
            "No training session active — report()/get_context() must be "
            "called inside a train_loop_per_worker"
        )
    return _session


# --- public API (``ray_tpu.train.report`` etc.) --------------------------


def report(metrics: Dict[str, Any], *, checkpoint: Optional[Checkpoint] = None) -> None:
    """Reference: ``train.report`` (``_internal/session.py:667``)."""
    _get_session().report(metrics, checkpoint)


def get_context() -> TrainContext:
    return _get_session().context


def get_dataset_shard(name: str = "train"):
    """This rank's split of a Dataset passed to ``JaxTrainer(datasets=)``
    (reference ``ray.train.get_dataset_shard``). Returns a
    ``ray_tpu.data.DataShard`` with ``iter_batches`` /
    ``iter_device_batches``, or None if no such dataset was configured."""
    ctx = get_context()
    shards = ctx.metadata.get("dataset_shards", {})
    return shards.get(name)


def get_checkpoint() -> Optional[Checkpoint]:
    """Checkpoint to resume from, if the group restarted after a failure."""
    return _get_session().context.checkpoint


def get_mesh():
    """The gang's global device mesh, built from the
    ``JaxBackendConfig.mesh_spec`` the trainer declared — every rank
    gets the SAME mesh over all gang devices (call AFTER the rendezvous,
    i.e. anywhere inside the user loop; the backend setup_fn ran
    ``jax.distributed.initialize`` before the loop started). ``-1`` axes
    resolve against the global device count. Returns None when no
    mesh_spec was configured."""
    ctx = get_context()
    spec_fields = ctx.metadata.get("mesh_spec")
    if spec_fields is None:
        return None
    from ray_tpu.parallel.mesh import MeshSpec, make_mesh

    return make_mesh(MeshSpec(**spec_fields))


def get_sharding_rules():
    """The gang's canonical ``ShardingRules`` table (from
    ``JaxBackendConfig.sharding``: "ddp" | "fsdp" | "tp") — pass it with
    ``get_mesh()`` into ``models.llama.make_train_step(mesh=, rules=)``
    for the unified constrained step. None when not configured."""
    ctx = get_context()
    name = ctx.metadata.get("sharding_rules")
    if name is None:
        return None
    from ray_tpu.parallel import sharding as _sh

    try:
        return {"ddp": _sh.ddp_rules, "fsdp": _sh.fsdp_rules, "tp": _sh.tp_rules}[name]()
    except KeyError:
        raise ValueError(
            f"unknown sharding rules table {name!r} (expected ddp|fsdp|tp)"
        ) from None


def urgent_checkpoint_requested() -> bool:
    """True when a preemption warning landed (a node hosting this gang is
    DRAINING): save a checkpoint with the next ``report()`` so the run
    loses at most steps-since-warning instead of steps-since-the-last
    periodic checkpoint. Cleared automatically once any checkpoint is
    reported."""
    return _get_session().urgent_checkpoint.is_set()
