"""JaxTrainer: the data-parallel trainer driving a TPU worker gang.

Reference shape: ``train/data_parallel_trainer.py:428`` (training_loop) +
``train/_internal/backend_executor.py:68,135,451``. Redesign for TPU:
  * the worker gang is one process per slice host (ScalingConfig.topology),
    gang-reserved via STRICT_SPREAD placement group;
  * backend bootstrap is ``jax.distributed.initialize`` (JaxBackend) —
    gradient all-reduce happens *inside* the user's pjit program over ICI,
    Ray-style control plane only carries metrics/checkpoints;
  * results flow by polling worker queues; failures restart the whole gang
    from the latest checkpoint (``FailureConfig.max_failures``), matching
    the reference's stop-the-world recovery semantics.
"""

from __future__ import annotations

import logging
import os
import pickle
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

from ray_tpu.train.backend import Backend, BackendConfig, JaxBackendConfig
from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager
from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.session import TrainContext
from ray_tpu.train.worker_group import WorkerGroup

logger = logging.getLogger(__name__)


@dataclass
class Result:
    """Reference: ``ray.train.Result``."""

    metrics: Dict[str, Any] = field(default_factory=dict)
    checkpoint: Optional[Checkpoint] = None
    path: str = ""
    error: Optional[BaseException] = None
    metrics_history: list = field(default_factory=list)


class TrainingFailedError(RuntimeError):
    pass


class JaxTrainer:
    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        backend_config: Optional[BackendConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
    ):
        self._train_fn = train_loop_per_worker
        self._train_config = train_loop_config
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.backend_config = backend_config or JaxBackendConfig()
        self.datasets = datasets or {}
        self._resume_checkpoint = resume_from_checkpoint

    # -- paths -----------------------------------------------------------
    def _run_dir(self) -> str:
        base = self.run_config.storage_path or os.path.join(
            os.path.expanduser("~"), "ray_tpu_results"
        )
        name = self.run_config.name or f"JaxTrainer_{int(time.time())}"
        return os.path.join(base, name)

    # -- main ------------------------------------------------------------
    def fit(self) -> Result:
        run_dir = self._run_dir()
        ckpt_cfg = self.run_config.checkpoint_config
        manager = CheckpointManager.restore(
            run_dir,
            num_to_keep=ckpt_cfg.num_to_keep,
            score_attribute=ckpt_cfg.checkpoint_score_attribute,
            score_order=ckpt_cfg.checkpoint_score_order,
        )
        max_failures = self.run_config.failure_config.max_failures
        failures = 0
        last_error: Optional[BaseException] = None
        resume = self._resume_checkpoint or manager.latest()
        while True:
            try:
                result = self._run_attempt(manager, run_dir, resume)
                result.path = run_dir
                return result
            except TrainingFailedError as e:
                failures += 1
                last_error = e
                if max_failures >= 0 and failures > max_failures:
                    result = Result(
                        metrics={}, checkpoint=manager.latest(), path=run_dir, error=e
                    )
                    raise TrainingFailedError(
                        f"training failed after {failures} attempt(s): {e}"
                    ) from e
                logger.warning(
                    "training attempt failed (%d/%d): %s — restarting from "
                    "latest checkpoint", failures, max_failures, e,
                )
                resume = manager.latest()

    def _run_attempt(
        self,
        manager: CheckpointManager,
        run_dir: str,
        resume: Optional[Checkpoint],
    ) -> Result:
        # Reap orphaned session-staged checkpoint copies from prior failed
        # attempts — queued reports that never drained leave their _staged
        # dirs behind, and nothing else ever deletes them.
        staged_root = os.path.join(run_dir, "_staged")
        if os.path.isdir(staged_root):
            import shutil

            shutil.rmtree(staged_root, ignore_errors=True)
        scaling = self.scaling_config
        n = scaling.resolved_num_workers()
        backend: Backend = self.backend_config.backend_cls()()
        group: Optional[WorkerGroup] = None
        try:
            try:
                group = WorkerGroup(n, scaling.bundles(), scaling.pg_strategy())
                backend.on_start(group, self.backend_config)
            except Exception as e:  # noqa: BLE001
                raise TrainingFailedError(f"worker group start failed: {e!r}") from e
            setup_fn = getattr(backend, "setup_fn", lambda: None)()
            name = self.run_config.name or os.path.basename(run_dir)
            # unified parallelism plan (JaxBackendConfig.mesh_spec/
            # sharding): declared ONCE on the trainer, delivered to every
            # rank via context metadata so train.get_mesh()/
            # get_sharding_rules() hand all workers the identical plan
            shared_meta: Dict[str, Any] = {"datasets": list(self.datasets)}
            mesh_spec = getattr(self.backend_config, "mesh_spec", None)
            if mesh_spec is not None:
                from dataclasses import asdict

                shared_meta["mesh_spec"] = asdict(mesh_spec)
            sharding = getattr(self.backend_config, "sharding", None)
            if sharding is not None:
                shared_meta["sharding_rules"] = sharding
            contexts = [
                TrainContext(
                    world_size=n,
                    world_rank=rank,
                    local_rank=0,
                    node_rank=rank,
                    experiment_name=name,
                    trial_dir=run_dir,
                    checkpoint=resume,
                    metadata=dict(shared_meta),
                )
                for rank in range(n)
            ]
            # dataset shards: each worker rank gets an iterator over its split
            shard_args: Dict[int, Dict[str, Any]] = {rank: {} for rank in range(n)}
            for ds_name, ds in self.datasets.items():
                try:
                    splits = ds.streaming_split(n)
                except AttributeError:
                    splits = [ds] * n
                for rank in range(n):
                    shard_args[rank][ds_name] = splits[rank]
            for rank in range(n):
                contexts[rank].metadata["dataset_shards"] = shard_args[rank]
            try:
                import ray_tpu

                ray_tpu.get(
                    [
                        group.workers[rank].start_training.remote(
                            self._train_fn, self._train_config, contexts[rank], setup_fn
                        )
                        for rank in range(n)
                    ],
                    timeout=120,
                )
            except Exception as e:  # noqa: BLE001
                raise TrainingFailedError(f"start_training failed: {e!r}") from e
            return self._poll_loop(group, manager)
        finally:
            try:
                backend.on_shutdown(group, self.backend_config)
            except Exception:
                pass
            if group is not None:
                group.shutdown()

    def _poll_loop(self, group: WorkerGroup, manager: CheckpointManager) -> Result:
        """Drain worker report queues until every rank finishes.

        Reference: ``backend_executor.get_next_results`` — rank 0's metrics
        win; any rank may attach the checkpoint (TPU SPMD: rank 0 saves).

        Preemption awareness: subscribes to controller node-state pushes
        for the run's duration; a node entering DRAINING triggers an
        urgent-checkpoint request on every rank (SPMD: rank 0 saves), so
        a preempted run loses at most steps-since-warning, not
        steps-since-the-last periodic checkpoint."""
        import threading

        drained_nodes: set = set()
        drain_lock = threading.Lock()
        drain_seen = threading.Event()

        def _on_node_event(msg: Dict[str, Any]) -> None:
            if msg.get("state") == "DRAINING" and msg.get("node_id") is not None:
                with drain_lock:
                    drained_nodes.add(msg["node_id"])
                drain_seen.set()

        listener_backend = None
        try:
            from ray_tpu.core.api import _global_worker

            listener_backend = _global_worker().backend
            listener_backend.add_node_event_listener(_on_node_event)
        except Exception:
            listener_backend = None  # local mode: no node events
        try:
            return self._poll_loop_inner(
                group, manager, drain_seen, drained_nodes, drain_lock,
                listener_backend,
            )
        finally:
            if listener_backend is not None:
                try:
                    listener_backend.remove_node_event_listener(_on_node_event)
                except Exception:
                    pass

    @staticmethod
    def _gang_node_ids(backend, group: WorkerGroup) -> set:
        """Node ids currently hosting the gang's workers (actor table)."""
        out = set()
        for w in group.workers:
            try:
                info = backend.io.run(
                    backend.controller.call(
                        "get_actor_info", {"actor_id": w.actor_id}
                    ),
                    timeout=5,
                )
                nid = getattr((info or {}).get("address"), "node_id", None)
                if nid is not None:
                    out.add(nid)
            except Exception:
                pass
        return out

    def _poll_loop_inner(
        self,
        group: WorkerGroup,
        manager: CheckpointManager,
        drain_seen,
        drained_nodes: set,
        drain_lock,
        listener_backend,
    ) -> Result:
        last_metrics: Dict[str, Any] = {}
        history = []
        done = [False] * group.num_workers
        while not all(done):
            if drain_seen.is_set():
                drain_seen.clear()
                with drain_lock:
                    pending = set(drained_nodes)
                    drained_nodes.clear()
                # only a drain of a node HOSTING this gang warrants the
                # checkpoint I/O — unrelated nodes (serve/data capacity)
                # drain without interrupting training
                gang_nodes = (
                    self._gang_node_ids(listener_backend, group)
                    if listener_backend is not None
                    else set()
                )
                if pending & gang_nodes:
                    # fire-and-forget: a rank already dying must not
                    # stall the warning to the survivors (SPMD: every
                    # rank flips its flag, rank 0 saves)
                    for w in group.workers:
                        try:
                            w.request_urgent_checkpoint.remote()
                        except Exception:
                            pass
                    logger.warning(
                        "drain of a gang-hosting node detected — requested "
                        "urgent checkpoint from all ranks"
                    )
            try:
                polls = group.execute("poll_results", timeout=60)
            except Exception as e:  # noqa: BLE001
                raise TrainingFailedError(f"worker poll failed: {e!r}") from e
            # pair up reports across ranks by arrival batch; rank 0 wins.
            # Reports are processed BEFORE any error is raised: a crashing
            # worker may have queued its final checkpoint, which the restart
            # needs.
            errors = []
            errors_drained = True
            for rank, poll in enumerate(polls):
                if poll["error"] is not None:
                    errors.append(poll["error"])
                    if poll["reports"]:
                        errors_drained = False
                for report in poll["reports"]:
                    ckpt = report.get("checkpoint")
                    if rank == 0:
                        last_metrics = report["metrics"]
                        history.append(report["metrics"])
                    if ckpt is not None:
                        final = manager.register(
                            ckpt, report["metrics"] if rank == 0 else {}
                        )
                        if rank == 0:
                            last_metrics["_checkpoint_path"] = final.path
                # A finished rank may still hold >drain-cap queued reports
                # (poll drains at most 64 at a time) — only count it done
                # once its queue comes back empty, so the final checkpoint
                # is never dropped.
                done[rank] = poll["done"] and not poll["reports"]
            if errors and errors_drained:
                # Raise once every *erroring* rank's queue came back empty:
                # a crashing worker may have >drain-cap reports queued with
                # its final checkpoint in the tail, which the restart needs.
                # Healthy ranks still streaming reports must not defer the
                # gang restart indefinitely.
                raise TrainingFailedError(str(pickle.loads(errors[0])))
            if not all(done):
                time.sleep(0.05)
        group.execute("finish", timeout=30)
        return Result(
            metrics=last_metrics,
            checkpoint=manager.latest(),
            metrics_history=history,
        )
