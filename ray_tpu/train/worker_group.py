"""Worker group: the gang of training-worker actors.

Reference: ``train/_internal/worker_group.py:19,102`` (actor gang in a
placement group) + ``train/_internal/backend_executor.py:68`` (start,
env setup, poll). Redesign: the user loop runs on a thread inside each
actor; the trainer pulls buffered reports via ``poll_results`` instead of
the reference's blocking session queue handoff.
"""

from __future__ import annotations

import os
import pickle
import socket
import threading
import traceback
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.session import TrainContext, _end_session, _start_session
from ray_tpu.util.placement_group import PlacementGroup, placement_group, remove_placement_group
from ray_tpu.util.scheduling_strategies import PlacementGroupSchedulingStrategy


def _node_ip_address() -> str:
    """Routed-address probe for this node's reachable IP.

    ``gethostbyname(gethostname())`` commonly resolves to loopback
    (127.0.1.1 in /etc/hosts on Debian images), which would publish an
    unreachable jax.distributed coordinator address. Connecting a UDP
    socket to a public address (no packets sent) asks the kernel which
    interface would route there — mirrors the reference's
    ``ray._private.services.get_node_ip_address``.
    """
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("8.8.8.8", 80))
            ip = s.getsockname()[0]
        if not ip.startswith("127."):
            return ip
    except OSError:
        pass
    ip = socket.gethostbyname(socket.gethostname())
    return ip


class TrainWorker:
    """Actor hosting one training process (one slice host on TPU)."""

    def __init__(self):
        self._session = None
        self._thread: Optional[threading.Thread] = None
        self._done = threading.Event()
        self._error: Optional[bytes] = None

    # -- host/topology info (backend rendezvous) ------------------------
    def get_address(self) -> Dict[str, Any]:
        host = _node_ip_address()
        with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
            s.bind(("", 0))
            free_port = s.getsockname()[1]
        return {"host": host, "free_port": free_port, "pid": os.getpid()}

    def set_env(self, env: Dict[str, str]) -> bool:
        """Backend env setup — must run before anything imports jax."""
        os.environ.update(env)
        return True

    def run_fn(self, fn: Callable, *args):
        """Run an arbitrary function in-actor (backend hooks)."""
        return fn(*args)

    # -- training lifecycle ---------------------------------------------
    def start_training(
        self,
        train_fn: Callable,
        config: Optional[Dict[str, Any]],
        context: TrainContext,
        setup_fn: Optional[Callable] = None,
    ) -> bool:
        if self._thread is not None and self._thread.is_alive():
            raise RuntimeError("training already running on this worker")
        self._done.clear()
        self._error = None
        self._session = _start_session(context)

        def _run():
            try:
                if setup_fn is not None:
                    setup_fn(context)
                if config is not None:
                    train_fn(config)
                else:
                    train_fn()
            except BaseException as e:  # noqa: BLE001
                self._session.error = e
                self._error = pickle.dumps(
                    RuntimeError(
                        f"train_loop_per_worker failed on rank "
                        f"{context.world_rank}: {e!r}\n{traceback.format_exc()}"
                    )
                )
            finally:
                self._done.set()

        self._thread = threading.Thread(target=_run, daemon=True, name="train-loop")
        self._thread.start()
        return True

    def request_urgent_checkpoint(self) -> bool:
        """Preemption warning relay (trainer → session): the user loop
        sees ``train.urgent_checkpoint_requested()`` flip and saves at
        its next step boundary."""
        if self._session is not None:
            self._session.urgent_checkpoint.set()
            return True
        return False

    def poll_results(self) -> Dict[str, Any]:
        """Drain buffered ``report()`` calls; reference
        ``backend_executor.get_next_results``."""
        # Snapshot done and error BEFORE draining: the train thread enqueues
        # its last report and only then sets _error/_done, so snapshotting
        # first guarantees that when done/error shows up in a poll, every
        # report enqueued before it is visible to this or a later drain —
        # the trainer raises only once the erroring rank's queue is empty.
        done = self._done.is_set()
        error = self._error
        reports = self._session.drain() if self._session else []
        return {
            "reports": reports,
            "done": done,
            "error": error,
        }

    def finish(self) -> bool:
        if self._thread is not None:
            self._thread.join(timeout=5)
        _end_session()
        self._session = None
        return True


class WorkerGroup:
    """N TrainWorker actors gang-placed in a placement group."""

    def __init__(
        self,
        num_workers: int,
        bundles: List[Dict[str, float]],
        pg_strategy: str,
        *,
        max_restarts: int = 0,
    ):
        self.num_workers = num_workers
        self.pg: PlacementGroup = placement_group(bundles, strategy=pg_strategy)
        self.pg.ready(timeout=60)
        cls = ray_tpu.remote(TrainWorker)
        self.workers = []
        for rank in range(num_workers):
            strategy = PlacementGroupSchedulingStrategy(
                placement_group=self.pg, placement_group_bundle_index=rank
            )
            res = dict(bundles[rank])
            num_cpus = res.pop("CPU", 1.0)
            self.workers.append(
                cls.options(
                    num_cpus=num_cpus,
                    resources=res or None,
                    scheduling_strategy=strategy,
                    max_restarts=0,
                ).remote()
            )
        # block until every worker process is up
        ray_tpu.get([w.__ray_ready__() for w in self.workers], timeout=120)

    def execute(self, method: str, *args, timeout: Optional[float] = None, **kwargs) -> List[Any]:
        """Call an actor method on every worker, gather results."""
        refs = [getattr(w, method).remote(*args, **kwargs) for w in self.workers]
        return ray_tpu.get(refs, timeout=timeout)

    def execute_single(self, rank: int, method: str, *args, timeout: Optional[float] = None, **kwargs) -> Any:
        ref = getattr(self.workers[rank], method).remote(*args, **kwargs)
        return ray_tpu.get(ref, timeout=timeout)

    def shutdown(self) -> None:
        for w in self.workers:
            try:
                ray_tpu.kill(w, no_restart=True)
            except Exception:
                pass
        self.workers = []
        try:
            remove_placement_group(self.pg)
        except Exception:
            pass
