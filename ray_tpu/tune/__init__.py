"""ray_tpu.tune — experiment execution: trials, search, schedulers.

Reference: ``python/ray/tune/`` (Tuner/TuneController, basic-variant
search, ASHA). See ``tuner.py`` for the controller design."""

from ray_tpu.tune.loggers import (
    CSVLoggerCallback,
    JSONLoggerCallback,
    LoggerCallback,
    TensorBoardLoggerCallback,
)
from ray_tpu.tune.schedulers import (
    ASHAScheduler,
    FIFOScheduler,
    MedianStoppingRule,
    PopulationBasedTraining,
)
from ray_tpu.tune.search import (
    ConcurrencyLimiter,
    OptunaSearch,
    RandomSearch,
    Searcher,
    TPESearcher,
    choice,
    grid_search,
    loguniform,
    qrandint,
    randint,
    uniform,
)
from ray_tpu.tune.trial import Trial, get_checkpoint, get_config, report
from ray_tpu.tune.tuner import ResultGrid, TrialResult, TuneConfig, Tuner

__all__ = [
    "ASHAScheduler",
    "CSVLoggerCallback",
    "ConcurrencyLimiter",
    "FIFOScheduler",
    "JSONLoggerCallback",
    "LoggerCallback",
    "MedianStoppingRule",
    "OptunaSearch",
    "PopulationBasedTraining",
    "RandomSearch",
    "Searcher",
    "TPESearcher",
    "TensorBoardLoggerCallback",
    "get_checkpoint",
    "ResultGrid",
    "Trial",
    "TrialResult",
    "TuneConfig",
    "Tuner",
    "choice",
    "get_config",
    "grid_search",
    "loguniform",
    "qrandint",
    "randint",
    "report",
    "uniform",
]
