"""Logger callbacks: per-trial progress to CSV / JSONL / TensorBoard.

Reference: ``tune/logger/`` (CSV/JSON/TBX logger callbacks wired
through ``RunConfig.callbacks``). Each trial gets a directory under the
experiment dir; every reported result appends a row/event.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional


class LoggerCallback:
    """Callback ABC (reference ``tune/logger/logger.py`` LoggerCallback)."""

    def setup(self, experiment_dir: Optional[str]) -> None:
        self.experiment_dir = experiment_dir

    def _trial_dir(self, trial) -> Optional[str]:
        if not getattr(self, "experiment_dir", None):
            return None
        d = os.path.join(self.experiment_dir, trial.trial_id)
        os.makedirs(d, exist_ok=True)
        return d

    def on_trial_result(self, trial, result: Dict[str, Any]) -> None:
        pass

    def on_trial_complete(self, trial) -> None:
        pass


class CSVLoggerCallback(LoggerCallback):
    """``progress.csv`` per trial (reference CSVLoggerCallback). Columns
    fix on the first result; later keys outside them are dropped (the
    reference behaves the same)."""

    def setup(self, experiment_dir):
        super().setup(experiment_dir)
        self._writers: Dict[str, Any] = {}
        self._files: Dict[str, Any] = {}

    def on_trial_result(self, trial, result):
        import csv

        d = self._trial_dir(trial)
        if d is None:
            return
        w = self._writers.get(trial.trial_id)
        if w is None:
            f = open(os.path.join(d, "progress.csv"), "w", newline="")
            fields = list(result.keys())
            w = csv.DictWriter(f, fieldnames=fields, extrasaction="ignore")
            w.writeheader()
            self._writers[trial.trial_id] = w
            self._files[trial.trial_id] = f
        w.writerow({k: result.get(k) for k in w.fieldnames})
        self._files[trial.trial_id].flush()

    def on_trial_complete(self, trial):
        f = self._files.pop(trial.trial_id, None)
        self._writers.pop(trial.trial_id, None)
        if f is not None:
            f.close()


class JSONLoggerCallback(LoggerCallback):
    """``result.json`` (JSON-lines) per trial + ``params.json``."""

    def on_trial_result(self, trial, result):
        d = self._trial_dir(trial)
        if d is None:
            return
        params = os.path.join(d, "params.json")
        if not os.path.exists(params):
            with open(params, "w") as f:
                json.dump(trial.config, f, default=str)
        with open(os.path.join(d, "result.json"), "a") as f:
            f.write(json.dumps(result, default=str) + "\n")


class TensorBoardLoggerCallback(LoggerCallback):
    """TensorBoard event files per trial (reference TBXLoggerCallback).
    Uses torch's SummaryWriter; raises at construction if unavailable."""

    def __init__(self):
        from torch.utils.tensorboard import SummaryWriter  # noqa: F401

        self._writer_cls = SummaryWriter
        self._writers: Dict[str, Any] = {}

    def on_trial_result(self, trial, result):
        d = self._trial_dir(trial)
        if d is None:
            return
        w = self._writers.get(trial.trial_id)
        if w is None:
            w = self._writers[trial.trial_id] = self._writer_cls(log_dir=d)
        step = int(result.get("training_iteration", len(trial.metrics_history)))
        for k, v in result.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                w.add_scalar(k, v, global_step=step)
        w.flush()

    def on_trial_complete(self, trial):
        w = self._writers.pop(trial.trial_id, None)
        if w is not None:
            w.close()
