"""Trial schedulers: FIFO + ASHA early stopping.

Reference: ``tune/schedulers/async_hyperband.py:19`` — Async Successive
Halving (ASHA): rungs at ``grace_period * reduction_factor^k``; when a
trial reports at a rung milestone it continues only if its metric is in
the top ``1/reduction_factor`` quantile of everything that has reached
that rung; everyone else stops. Asynchronous: no waiting for a full
bracket — decisions use whatever peers have arrived."""

from __future__ import annotations

from typing import Dict, List

CONTINUE = "CONTINUE"
STOP = "STOP"
#: (EXPLOIT, source_trial_id): restart this trial from source's checkpoint
#: with an explored config (PBT)
EXPLOIT = "EXPLOIT"


class FIFOScheduler:
    """No early stopping: every trial runs to completion."""

    def on_result(self, trial_id: str, iteration: int, metric_value: float) -> str:
        return CONTINUE

    def on_trial_complete(self, trial_id: str) -> None:
        pass


class ASHAScheduler:
    def __init__(
        self,
        *,
        metric: str | None = None,
        mode: str | None = None,
        max_t: int = 100,
        grace_period: int = 1,
        reduction_factor: int = 4,
    ):
        if mode not in (None, "min", "max"):
            raise ValueError(f"mode must be min|max, got {mode!r}")
        #: metric/mode may be left None and inherited from TuneConfig —
        #: the Tuner resolves them before the first on_result call.
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.grace_period = grace_period
        self.rf = reduction_factor
        # rung milestone -> {trial_id: best metric recorded at this rung}
        self._rungs: Dict[int, Dict[str, float]] = {}
        milestone = grace_period
        while milestone < max_t:
            self._rungs[milestone] = {}
            milestone *= reduction_factor

    def _milestones(self) -> List[int]:
        return sorted(self._rungs)

    def on_result(self, trial_id: str, iteration: int, metric_value: float) -> str:
        v = -metric_value if self.mode == "min" else metric_value
        for milestone in self._milestones():
            if iteration < milestone:
                break
            rung = self._rungs[milestone]
            if trial_id in rung:
                continue  # already judged at this rung
            rung[trial_id] = v
            # top-1/rf cutoff among peers that reached the rung
            values = sorted(rung.values(), reverse=True)
            k = max(1, len(values) // self.rf)
            cutoff = values[k - 1]
            if v < cutoff:
                return STOP
        return CONTINUE

    def on_trial_complete(self, trial_id: str) -> None:
        pass


class PopulationBasedTraining:
    """PBT (reference ``tune/schedulers/pbt.py:221``): every
    ``perturbation_interval`` iterations, a trial in the bottom quantile
    EXPLOITS a top-quantile peer — the Tuner restarts it from the peer's
    checkpoint — and EXPLORES a mutated copy of the peer's config.

    Requires trainables that checkpoint via
    ``tune.report(metrics, checkpoint=...)`` and resume via
    ``tune.get_checkpoint()``; trials that never checkpoint are skipped
    (nothing to exploit)."""

    def __init__(
        self,
        *,
        metric: str | None = None,
        mode: str | None = None,
        perturbation_interval: int = 4,
        quantile_fraction: float = 0.25,
        hyperparam_mutations: Dict[str, object] | None = None,
        seed: int | None = None,
    ):
        if not 0 < quantile_fraction <= 0.5:
            raise ValueError("quantile_fraction must be in (0, 0.5]")
        self.metric = metric
        self.mode = mode
        self.interval = max(1, perturbation_interval)
        self.quantile = quantile_fraction
        self.mutations = dict(hyperparam_mutations or {})
        import random as _random

        self._rng = _random.Random(seed)
        #: trial_id -> latest signed score (mode-normalized; higher=better)
        self._scores: Dict[str, float] = {}
        self._last_perturb: Dict[str, int] = {}
        self._complete: set = set()

    def on_result(self, trial_id: str, iteration: int, metric_value: float):
        v = -metric_value if self.mode == "min" else metric_value
        self._scores[trial_id] = v
        last = self._last_perturb.get(trial_id, 0)
        if iteration - last < self.interval:
            return CONTINUE
        self._last_perturb[trial_id] = iteration
        live = {
            t: s for t, s in self._scores.items() if t not in self._complete
        }
        if len(live) < 2:
            return CONTINUE
        ranked = sorted(live, key=lambda t: live[t], reverse=True)
        k = max(1, int(len(ranked) * self.quantile))
        bottom = set(ranked[-k:])
        if trial_id not in bottom:
            return CONTINUE
        top = [t for t in ranked[:k] if t != trial_id]
        if not top:
            return CONTINUE
        return (EXPLOIT, self._rng.choice(top))

    def explore(self, config: Dict[str, object]) -> Dict[str, object]:
        """Mutate an exploited config (reference ``explore()``): resample
        from a list/callable, or perturb numerics by 0.8x / 1.2x."""
        out = dict(config)
        for key, spec in self.mutations.items():
            if callable(spec):
                out[key] = spec()
            elif isinstance(spec, (list, tuple)):
                out[key] = self._rng.choice(list(spec))
            elif isinstance(out.get(key), (int, float)):
                factor = self._rng.choice((0.8, 1.2))
                val = out[key] * factor
                # ints ROUND (int() would truncate 1*0.8 to the absorbing 0)
                out[key] = (
                    int(round(val)) if isinstance(config[key], int) else val
                )
        return out

    def on_trial_complete(self, trial_id: str) -> None:
        self._complete.add(trial_id)


class MedianStoppingRule:
    """Median stopping (reference
    ``tune/schedulers/median_stopping_rule.py:19``): stop a trial at
    iteration t if its best metric so far is worse than the MEDIAN of
    the other trials' running averages at comparable progress."""

    def __init__(
        self,
        *,
        metric: str | None = None,
        mode: str | None = None,
        grace_period: int = 4,
        min_samples_required: int = 3,
    ):
        self.metric = metric
        self.mode = mode
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        #: trial_id -> list of signed metric values per report
        self._histories: Dict[str, List[float]] = {}

    def on_result(self, trial_id: str, iteration: int, metric_value: float) -> str:
        v = -metric_value if self.mode == "min" else metric_value
        hist = self._histories.setdefault(trial_id, [])
        hist.append(v)
        if iteration < self.grace_period:
            return CONTINUE
        # running averages of OTHER trials truncated to this progress
        others = [
            sum(h[:iteration]) / min(len(h), iteration)
            for t, h in self._histories.items()
            if t != trial_id and h
        ]
        if len(others) < self.min_samples:
            return CONTINUE
        others.sort()
        median = others[len(others) // 2]
        if max(hist) < median:
            return STOP
        return CONTINUE

    def on_trial_complete(self, trial_id: str) -> None:
        pass
