"""Trial schedulers: FIFO + ASHA early stopping.

Reference: ``tune/schedulers/async_hyperband.py:19`` — Async Successive
Halving (ASHA): rungs at ``grace_period * reduction_factor^k``; when a
trial reports at a rung milestone it continues only if its metric is in
the top ``1/reduction_factor`` quantile of everything that has reached
that rung; everyone else stops. Asynchronous: no waiting for a full
bracket — decisions use whatever peers have arrived."""

from __future__ import annotations

from typing import Dict, List

CONTINUE = "CONTINUE"
STOP = "STOP"


class FIFOScheduler:
    """No early stopping: every trial runs to completion."""

    def on_result(self, trial_id: str, iteration: int, metric_value: float) -> str:
        return CONTINUE

    def on_trial_complete(self, trial_id: str) -> None:
        pass


class ASHAScheduler:
    def __init__(
        self,
        *,
        metric: str | None = None,
        mode: str | None = None,
        max_t: int = 100,
        grace_period: int = 1,
        reduction_factor: int = 4,
    ):
        if mode not in (None, "min", "max"):
            raise ValueError(f"mode must be min|max, got {mode!r}")
        #: metric/mode may be left None and inherited from TuneConfig —
        #: the Tuner resolves them before the first on_result call.
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.grace_period = grace_period
        self.rf = reduction_factor
        # rung milestone -> {trial_id: best metric recorded at this rung}
        self._rungs: Dict[int, Dict[str, float]] = {}
        milestone = grace_period
        while milestone < max_t:
            self._rungs[milestone] = {}
            milestone *= reduction_factor

    def _milestones(self) -> List[int]:
        return sorted(self._rungs)

    def on_result(self, trial_id: str, iteration: int, metric_value: float) -> str:
        v = -metric_value if self.mode == "min" else metric_value
        for milestone in self._milestones():
            if iteration < milestone:
                break
            rung = self._rungs[milestone]
            if trial_id in rung:
                continue  # already judged at this rung
            rung[trial_id] = v
            # top-1/rf cutoff among peers that reached the rung
            values = sorted(rung.values(), reverse=True)
            k = max(1, len(values) // self.rf)
            cutoff = values[k - 1]
            if v < cutoff:
                return STOP
        return CONTINUE

    def on_trial_complete(self, trial_id: str) -> None:
        pass
