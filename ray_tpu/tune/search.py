"""Search: param-space resolution + trial variant generation.

Reference: ``python/ray/tune/search/basic_variant.py`` (grid + random
sampling) and ``tune/search/variant_generator.py``. Search spaces are
plain dicts whose leaves may be ``grid_search([...])``, ``choice``,
``uniform``, ``loguniform``, ``randint`` or callables; grids expand to
the cross product, sampled leaves draw ``num_samples`` times."""

from __future__ import annotations

import itertools
import math
import random
from typing import Any, Callable, Dict, Iterator, List, Tuple


class _Grid:
    def __init__(self, values):
        self.values = list(values)


class _Sampler:
    """A sampled leaf. ``kind``/``low``/``high``/``options`` carry the
    DOMAIN so model-based searchers (TPE/optuna) can reason about it —
    an opaque lambda would limit them to random sampling."""

    def __init__(self, fn: Callable[[random.Random], Any], *, kind: str = "opaque",
                 low: float = 0.0, high: float = 1.0, options=None, q: int = 1):
        self.fn = fn
        self.kind = kind
        self.low = low
        self.high = high
        self.options = options
        self.q = q  # quantization step (randint only)

    def sample(self, rng: random.Random) -> Any:
        return self.fn(rng)


def grid_search(values) -> _Grid:
    return _Grid(values)


def choice(options) -> _Sampler:
    opts = list(options)
    return _Sampler(lambda rng: rng.choice(opts), kind="choice", options=opts)


def uniform(low: float, high: float) -> _Sampler:
    return _Sampler(lambda rng: rng.uniform(low, high), kind="uniform", low=low, high=high)


def loguniform(low: float, high: float) -> _Sampler:
    lo, hi = math.log(low), math.log(high)
    return _Sampler(
        lambda rng: math.exp(rng.uniform(lo, hi)),
        kind="loguniform", low=low, high=high,
    )


def randint(low: int, high: int) -> _Sampler:
    return _Sampler(lambda rng: rng.randrange(low, high), kind="randint", low=low, high=high)


def qrandint(low: int, high: int, q: int = 1) -> _Sampler:
    # clamp after quantizing — floor division can otherwise dip below low
    return _Sampler(
        lambda rng: max(low, (rng.randrange(low, high) // q) * q),
        kind="randint", low=low, high=high, q=q,
    )


def _walk(space: Dict[str, Any], path=()) -> Iterator[Tuple[Tuple[str, ...], Any]]:
    for k, v in space.items():
        p = path + (k,)
        if isinstance(v, dict):
            yield from _walk(v, p)
        else:
            yield p, v


def _set_path(d: Dict[str, Any], path: Tuple[str, ...], value: Any) -> None:
    for k in path[:-1]:
        d = d.setdefault(k, {})
    d[path[-1]] = value


def generate_variants(
    param_space: Dict[str, Any],
    num_samples: int = 1,
    seed: int | None = None,
) -> List[Dict[str, Any]]:
    """Expand grids (cross product) × draw samples ``num_samples`` times."""
    import copy

    rng = random.Random(seed)
    grid_paths: List[Tuple[Tuple[str, ...], _Grid]] = []
    sample_paths: List[Tuple[Tuple[str, ...], _Sampler]] = []
    const_paths: List[Tuple[Tuple[str, ...], Any]] = []
    for path, leaf in _walk(param_space):
        if isinstance(leaf, _Grid):
            grid_paths.append((path, leaf))
        elif isinstance(leaf, _Sampler):
            sample_paths.append((path, leaf))
        elif callable(leaf):
            sample_paths.append((path, _Sampler(lambda rng, f=leaf: f())))
        else:
            const_paths.append((path, leaf))

    grid_combos = (
        list(itertools.product(*[g.values for _, g in grid_paths]))
        if grid_paths
        else [()]
    )
    variants: List[Dict[str, Any]] = []
    for _ in range(max(1, num_samples)):
        for combo in grid_combos:
            cfg: Dict[str, Any] = {}
            for path, value in const_paths:
                _set_path(cfg, path, copy.deepcopy(value))
            for (path, _g), value in zip(grid_paths, combo):
                _set_path(cfg, path, value)
            for path, sampler in sample_paths:
                _set_path(cfg, path, sampler.sample(rng))
            variants.append(cfg)
    return variants


# ---------------------------------------------------------------------------
# Search algorithms (reference tune/search/searcher.py + adapters)


_MISSING = object()
_restored_seq = itertools.count()


class Searcher:
    """Sequential search-algorithm ABC (reference ``Searcher``): the
    Tuner asks ``suggest`` for each new trial's config and feeds final
    results back through ``on_trial_complete``."""

    def set_search_properties(self, metric: str, mode: str, param_space: Dict[str, Any]) -> None:
        self.metric = metric
        self.mode = mode
        self.param_space = param_space

    def suggest(self, trial_id: str) -> Dict[str, Any]:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str, result: Dict[str, Any]) -> None:
        pass

    def add_evaluated_point(self, config: Dict[str, Any], result: Dict[str, Any]) -> None:
        """Feed an ALREADY-EVALUATED (config, result) pair into the
        searcher's model — the resume/warm-start path (reference
        ``Searcher.add_evaluated_point``). Unlike ``on_trial_complete``
        this takes the config itself, not a trial id: restored trials
        were never ``suggest``-ed in this process, so id-keyed completion
        is a silent no-op for model-based searchers (TPE/Optuna override
        this). Default: replay through ``on_trial_complete`` with a
        synthetic id so subclasses that key their model off the result
        alone still warm-start; for id-keyed subclasses that don't
        override, the unknown id makes this a no-op — identical to the
        pre-``add_evaluated_point`` resume behavior, never worse."""
        self.on_trial_complete(f"__restored_{next(_restored_seq)}", result)


class RandomSearch(Searcher):
    """Independent random sampling through the Searcher interface (the
    baseline model-based searchers must beat)."""

    def __init__(self, seed: int | None = None):
        self._rng = random.Random(seed)

    def suggest(self, trial_id: str) -> Dict[str, Any]:
        import copy

        cfg: Dict[str, Any] = {}
        for path, leaf in _walk(self.param_space):
            if isinstance(leaf, _Grid):
                _set_path(cfg, path, self._rng.choice(leaf.values))
            elif isinstance(leaf, _Sampler):
                _set_path(cfg, path, leaf.sample(self._rng))
            elif callable(leaf):
                _set_path(cfg, path, leaf())
            else:
                _set_path(cfg, path, copy.deepcopy(leaf))
        return cfg


class TPESearcher(Searcher):
    """Native Tree-structured Parzen Estimator (the reference reaches
    TPE through the hyperopt/optuna adapters; this build carries its own
    so model-based search works with zero extra deps — ``OptunaSearch``
    below adapts the real library when it's installed).

    Per-dimension independent TPE: completed trials split into the top
    ``gamma`` fraction (good) and the rest; candidates sample from a
    Parzen (Gaussian-kernel) estimate of the GOOD distribution and are
    ranked by the density ratio good/bad; categorical dims use smoothed
    frequency ratios. Sampling happens in log space for loguniform."""

    def __init__(
        self,
        *,
        n_startup_trials: int = 10,
        n_candidates: int = 32,
        gamma: float = 0.25,
        seed: int | None = None,
    ):
        self.n_startup = n_startup_trials
        self.n_candidates = n_candidates
        self.gamma = gamma
        self._rng = random.Random(seed)
        self._history: List[Tuple[Dict[Tuple[str, ...], Any], float]] = []
        self._live: Dict[str, Dict[Tuple[str, ...], Any]] = {}

    # -- internals -------------------------------------------------------
    def _flat_sample_dims(self):
        return [
            (path, leaf)
            for path, leaf in _walk(self.param_space)
            if isinstance(leaf, (_Sampler, _Grid))
        ]

    def _random_flat(self) -> Dict[Tuple[str, ...], Any]:
        flat = {}
        for path, leaf in self._flat_sample_dims():
            if isinstance(leaf, _Grid):
                flat[path] = self._rng.choice(leaf.values)
            else:
                flat[path] = leaf.sample(self._rng)
        return flat

    @staticmethod
    def _to_unit(leaf: _Sampler, v: float) -> float:
        if leaf.kind == "loguniform":
            lo, hi = math.log(leaf.low), math.log(leaf.high)
            return (math.log(max(v, 1e-300)) - lo) / max(hi - lo, 1e-12)
        lo, hi = leaf.low, leaf.high
        return (float(v) - lo) / max(hi - lo, 1e-12)

    @staticmethod
    def _from_unit(leaf: _Sampler, u: float):
        u = min(1.0, max(0.0, u))
        if leaf.kind == "loguniform":
            lo, hi = math.log(leaf.low), math.log(leaf.high)
            return math.exp(lo + u * (hi - lo))
        value = leaf.low + u * (leaf.high - leaf.low)
        if leaf.kind == "randint":
            v = int(round(value))
            q = getattr(leaf, "q", 1) or 1
            if q > 1:
                v = (v // q) * q  # honor the declared quantization grid
            return min(int(leaf.high) - 1, max(int(leaf.low), v))
        return value

    @staticmethod
    def _kde(us: List[float], u: float, bw: float) -> float:
        return sum(
            math.exp(-0.5 * ((u - x) / bw) ** 2) for x in us
        ) / (len(us) * bw) + 1e-12

    def _suggest_dim(self, path, leaf, good, bad):
        if isinstance(leaf, _Grid) or leaf.kind in ("choice", "opaque"):
            opts = leaf.values if isinstance(leaf, _Grid) else leaf.options
            if opts is None:  # opaque sampler: nothing to model
                return leaf.sample(self._rng)
            counts_g = {o: 1.0 for o in range(len(opts))}
            counts_b = {o: 1.0 for o in range(len(opts))}
            for flat in good:
                i = next((i for i, o in enumerate(opts) if o == flat.get(path)), None)
                if i is not None:
                    counts_g[i] += 1
            for flat in bad:
                i = next((i for i, o in enumerate(opts) if o == flat.get(path)), None)
                if i is not None:
                    counts_b[i] += 1
            # SAMPLE proportional to the good/bad ratio — an argmax here
            # permanently locks in whichever option the startup phase
            # happened to favor (no exploration of the other arms)
            weights = [counts_g[i] / counts_b[i] for i in range(len(opts))]
            total = sum(weights)
            r = self._rng.random() * total
            acc = 0.0
            for i, w in enumerate(weights):
                acc += w
                if r <= acc:
                    return opts[i]
            return opts[-1]
        # numeric: Parzen estimate in unit space
        us_g = [self._to_unit(leaf, flat[path]) for flat in good if path in flat]
        us_b = [self._to_unit(leaf, flat[path]) for flat in bad if path in flat]
        if not us_g:
            return leaf.sample(self._rng)
        # bandwidth shrinks as evidence accumulates (tuned on the test
        # surrogate: ^0.75 beat ^0.5 10/12 vs 6/12 seeds against random)
        bw = max(0.03, 1.0 / (len(us_g) + 1) ** 0.75)
        best_u, best_score = None, -1.0
        for _ in range(self.n_candidates):
            center = self._rng.choice(us_g)
            u = center + self._rng.gauss(0.0, bw)
            u = min(1.0, max(0.0, u))
            l_good = self._kde(us_g, u, bw)
            l_bad = self._kde(us_b, u, bw) if us_b else 1.0
            score = l_good / l_bad
            if score > best_score:
                best_u, best_score = u, score
        return self._from_unit(leaf, best_u)

    # -- Searcher API ----------------------------------------------------
    def suggest(self, trial_id: str) -> Dict[str, Any]:
        import copy

        if len(self._history) < self.n_startup:
            flat = self._random_flat()
        else:
            ranked = sorted(self._history, key=lambda e: e[1], reverse=True)
            k = max(1, int(len(ranked) * self.gamma))
            good = [f for f, _s in ranked[:k]]
            bad = [f for f, _s in ranked[k:]] or good
            flat = {
                path: self._suggest_dim(path, leaf, good, bad)
                for path, leaf in self._flat_sample_dims()
            }
        self._live[trial_id] = flat
        cfg: Dict[str, Any] = {}
        for path, leaf in _walk(self.param_space):
            if path in flat:
                _set_path(cfg, path, flat[path])
            elif callable(leaf) and not isinstance(leaf, (_Sampler, _Grid)):
                _set_path(cfg, path, leaf())
            else:
                _set_path(cfg, path, copy.deepcopy(leaf))
        return cfg

    def on_trial_complete(self, trial_id: str, result: Dict[str, Any]) -> None:
        flat = self._live.pop(trial_id, None)
        if flat is None or self.metric not in result:
            return
        v = float(result[self.metric])
        if self.mode == "min":
            v = -v
        self._history.append((flat, v))

    def add_evaluated_point(self, config: Dict[str, Any], result: Dict[str, Any]) -> None:
        """Warm-start from a (config, result) pair that was evaluated
        elsewhere (a restored trial): flatten the config along this
        space's sampled dims and append straight to the TPE history —
        equivalent to suggest + on_trial_complete without a live entry."""
        if not result or self.metric not in result:
            return
        flat: Dict[Tuple[str, ...], Any] = {}
        for path, _leaf in self._flat_sample_dims():
            node: Any = config
            for part in path:
                if not isinstance(node, dict) or part not in node:
                    node = _MISSING
                    break
                node = node[part]
            if node is not _MISSING:
                flat[path] = node
        if not flat:
            return
        v = float(result[self.metric])
        if self.mode == "min":
            v = -v
        self._history.append((flat, v))


class ConcurrencyLimiter(Searcher):
    """Caps in-flight suggestions (reference
    ``tune/search/ConcurrencyLimiter``): model-based searchers degrade
    when many trials launch before any results arrive."""

    def __init__(self, searcher: Searcher, max_concurrent: int):
        self.searcher = searcher
        self.max_concurrent = max(1, max_concurrent)
        self._live: set = set()

    def set_search_properties(self, metric, mode, param_space) -> None:
        super().set_search_properties(metric, mode, param_space)
        self.searcher.set_search_properties(metric, mode, param_space)

    def suggest(self, trial_id: str):
        if len(self._live) >= self.max_concurrent:
            return None  # Tuner retries when a slot frees
        self._live.add(trial_id)
        return self.searcher.suggest(trial_id)

    def on_trial_complete(self, trial_id: str, result: Dict[str, Any]) -> None:
        self._live.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, result)

    def add_evaluated_point(self, config: Dict[str, Any], result: Dict[str, Any]) -> None:
        self.searcher.add_evaluated_point(config, result)


class OptunaSearch(Searcher):
    """Adapter over the optuna library's TPE (reference
    ``tune/search/optuna``). Gated: raises ImportError with a pointer to
    the built-in ``TPESearcher`` when optuna isn't installed."""

    def __init__(self, *, seed: int | None = None, sampler=None):
        try:
            import optuna
        except ImportError as e:
            raise ImportError(
                "optuna is not installed — use ray_tpu.tune.TPESearcher "
                "(built-in TPE) instead"
            ) from e
        self._optuna = optuna
        self._sampler = sampler or optuna.samplers.TPESampler(seed=seed)
        self._study = None
        self._trials: Dict[str, Any] = {}

    def set_search_properties(self, metric, mode, param_space) -> None:
        super().set_search_properties(metric, mode, param_space)
        self._study = self._optuna.create_study(
            direction="maximize" if mode == "max" else "minimize",
            sampler=self._sampler,
        )

    def suggest(self, trial_id: str) -> Dict[str, Any]:
        import copy

        ot = self._study.ask()
        self._trials[trial_id] = ot
        cfg: Dict[str, Any] = {}
        for path, leaf in _walk(self.param_space):
            name = ".".join(path)
            if isinstance(leaf, _Grid):
                _set_path(cfg, path, ot.suggest_categorical(name, leaf.values))
            elif isinstance(leaf, _Sampler) and leaf.kind == "uniform":
                _set_path(cfg, path, ot.suggest_float(name, leaf.low, leaf.high))
            elif isinstance(leaf, _Sampler) and leaf.kind == "loguniform":
                _set_path(cfg, path, ot.suggest_float(name, leaf.low, leaf.high, log=True))
            elif isinstance(leaf, _Sampler) and leaf.kind == "randint":
                q = getattr(leaf, "q", 1) or 1
                lo = int(leaf.low)
                hi = lo + ((int(leaf.high) - 1 - lo) // q) * q  # step-aligned
                _set_path(cfg, path, ot.suggest_int(name, lo, hi, step=q))
            elif isinstance(leaf, _Sampler) and leaf.kind == "choice":
                _set_path(cfg, path, ot.suggest_categorical(name, leaf.options))
            elif isinstance(leaf, _Sampler) or callable(leaf):
                _set_path(cfg, path, leaf.sample(random.Random()) if isinstance(leaf, _Sampler) else leaf())
            else:
                _set_path(cfg, path, copy.deepcopy(leaf))
        return cfg

    def on_trial_complete(self, trial_id: str, result: Dict[str, Any]) -> None:
        ot = self._trials.pop(trial_id, None)
        if ot is None or self.metric not in result:
            return
        self._study.tell(ot, float(result[self.metric]))

    def add_evaluated_point(self, config: Dict[str, Any], result: Dict[str, Any]) -> None:
        """Warm-start the study with a finished trial: rebuild the
        param/distribution maps from the search space and
        ``study.add_trial`` a COMPLETE optuna trial (the documented
        optuna resume recipe). Dims the study can't express (opaque
        samplers, callables) are skipped."""
        if not result or self.metric not in result or self._study is None:
            return
        optuna = self._optuna
        params: Dict[str, Any] = {}
        dists: Dict[str, Any] = {}
        for path, leaf in _walk(self.param_space):
            name = ".".join(path)
            node: Any = config
            for part in path:
                if not isinstance(node, dict) or part not in node:
                    node = _MISSING  # None is a legitimate sampled value
                    break
                node = node[part]
            if node is _MISSING:
                continue
            if isinstance(leaf, _Grid):
                dists[name] = optuna.distributions.CategoricalDistribution(leaf.values)
            elif isinstance(leaf, _Sampler) and leaf.kind in ("uniform", "loguniform"):
                dists[name] = optuna.distributions.FloatDistribution(
                    leaf.low, leaf.high, log=leaf.kind == "loguniform"
                )
            elif isinstance(leaf, _Sampler) and leaf.kind == "randint":
                q = getattr(leaf, "q", 1) or 1
                lo = int(leaf.low)
                hi = lo + ((int(leaf.high) - 1 - lo) // q) * q
                dists[name] = optuna.distributions.IntDistribution(lo, hi, step=q)
            elif isinstance(leaf, _Sampler) and leaf.kind == "choice" and leaf.options:
                dists[name] = optuna.distributions.CategoricalDistribution(
                    list(leaf.options)
                )
            else:
                continue
            params[name] = node
        if not params:
            return
        try:
            self._study.add_trial(
                optuna.trial.create_trial(
                    params=params,
                    distributions=dists,
                    value=float(result[self.metric]),
                )
            )
        except Exception:
            # a malformed restored config must not kill the resume
            pass
