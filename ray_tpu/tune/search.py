"""Search: param-space resolution + trial variant generation.

Reference: ``python/ray/tune/search/basic_variant.py`` (grid + random
sampling) and ``tune/search/variant_generator.py``. Search spaces are
plain dicts whose leaves may be ``grid_search([...])``, ``choice``,
``uniform``, ``loguniform``, ``randint`` or callables; grids expand to
the cross product, sampled leaves draw ``num_samples`` times."""

from __future__ import annotations

import itertools
import math
import random
from typing import Any, Callable, Dict, Iterator, List, Tuple


class _Grid:
    def __init__(self, values):
        self.values = list(values)


class _Sampler:
    def __init__(self, fn: Callable[[random.Random], Any]):
        self.fn = fn

    def sample(self, rng: random.Random) -> Any:
        return self.fn(rng)


def grid_search(values) -> _Grid:
    return _Grid(values)


def choice(options) -> _Sampler:
    opts = list(options)
    return _Sampler(lambda rng: rng.choice(opts))


def uniform(low: float, high: float) -> _Sampler:
    return _Sampler(lambda rng: rng.uniform(low, high))


def loguniform(low: float, high: float) -> _Sampler:
    lo, hi = math.log(low), math.log(high)
    return _Sampler(lambda rng: math.exp(rng.uniform(lo, hi)))


def randint(low: int, high: int) -> _Sampler:
    return _Sampler(lambda rng: rng.randrange(low, high))


def qrandint(low: int, high: int, q: int = 1) -> _Sampler:
    # clamp after quantizing — floor division can otherwise dip below low
    return _Sampler(lambda rng: max(low, (rng.randrange(low, high) // q) * q))


def _walk(space: Dict[str, Any], path=()) -> Iterator[Tuple[Tuple[str, ...], Any]]:
    for k, v in space.items():
        p = path + (k,)
        if isinstance(v, dict):
            yield from _walk(v, p)
        else:
            yield p, v


def _set_path(d: Dict[str, Any], path: Tuple[str, ...], value: Any) -> None:
    for k in path[:-1]:
        d = d.setdefault(k, {})
    d[path[-1]] = value


def generate_variants(
    param_space: Dict[str, Any],
    num_samples: int = 1,
    seed: int | None = None,
) -> List[Dict[str, Any]]:
    """Expand grids (cross product) × draw samples ``num_samples`` times."""
    import copy

    rng = random.Random(seed)
    grid_paths: List[Tuple[Tuple[str, ...], _Grid]] = []
    sample_paths: List[Tuple[Tuple[str, ...], _Sampler]] = []
    const_paths: List[Tuple[Tuple[str, ...], Any]] = []
    for path, leaf in _walk(param_space):
        if isinstance(leaf, _Grid):
            grid_paths.append((path, leaf))
        elif isinstance(leaf, _Sampler):
            sample_paths.append((path, leaf))
        elif callable(leaf):
            sample_paths.append((path, _Sampler(lambda rng, f=leaf: f())))
        else:
            const_paths.append((path, leaf))

    grid_combos = (
        list(itertools.product(*[g.values for _, g in grid_paths]))
        if grid_paths
        else [()]
    )
    variants: List[Dict[str, Any]] = []
    for _ in range(max(1, num_samples)):
        for combo in grid_combos:
            cfg: Dict[str, Any] = {}
            for path, value in const_paths:
                _set_path(cfg, path, copy.deepcopy(value))
            for (path, _g), value in zip(grid_paths, combo):
                _set_path(cfg, path, value)
            for path, sampler in sample_paths:
                _set_path(cfg, path, sampler.sample(rng))
            variants.append(cfg)
    return variants
