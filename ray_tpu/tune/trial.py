"""Trial state + the trial-runner actor.

Reference: ``tune/experiment/trial.py:248`` (Trial FSM) and the
function-trainable session (``tune/trainable/function_trainable.py``):
the user function runs in a thread inside a per-trial actor, streaming
``tune.report(...)`` metrics through a buffered queue the controller
polls."""

from __future__ import annotations

import threading
import traceback
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import ray_tpu

PENDING = "PENDING"
RUNNING = "RUNNING"
TERMINATED = "TERMINATED"  # ran to completion
STOPPED = "STOPPED"  # early-stopped by the scheduler
ERRORED = "ERRORED"


@dataclass
class Trial:
    trial_id: str
    config: Dict[str, Any]
    status: str = PENDING
    last_metrics: Dict[str, Any] = field(default_factory=dict)
    metrics_history: List[Dict[str, Any]] = field(default_factory=list)
    iterations: int = 0
    error: Optional[str] = None
    actor: Any = None
    #: latest checkpoint payload reported by the trainable (PBT exploit
    #: source + experiment-resume restore point)
    last_checkpoint: Any = None


# ---- in-trial session (set inside the trial actor process) -------------
_session_lock = threading.Lock()
_session: Optional["_TrialSession"] = None


class _TrialSession:
    def __init__(self, config: Dict[str, Any], trial_id: str = "", checkpoint: Any = None):
        self.config = config
        self.trial_id = trial_id
        self.start_checkpoint = checkpoint
        self._reports: List[Dict[str, Any]] = []
        self._checkpoints: List[Any] = []  # aligned with reports (or None)
        self._lock = threading.Lock()

    def report(self, metrics: Dict[str, Any], checkpoint: Any = None) -> None:
        with self._lock:
            self._reports.append(dict(metrics))
            self._checkpoints.append(checkpoint)

    def drain(self):
        with self._lock:
            out, self._reports = self._reports, []
            cks, self._checkpoints = self._checkpoints, []
            return out, cks


def report(metrics: Dict[str, Any] = None, *, checkpoint: Any = None, **kwargs) -> None:
    """Report trial metrics (``ray.tune.report``). Accepts a dict and/or
    keyword metrics; one report = one iteration for the scheduler.
    ``checkpoint`` is any picklable payload — it becomes the trial's
    restore point for PBT exploits and experiment resume."""
    merged = dict(metrics or {})
    merged.update(kwargs)
    with _session_lock:
        s = _session
    if s is None:
        raise RuntimeError("tune.report() called outside a trial")
    s.report(merged, checkpoint)


def get_checkpoint() -> Any:
    """The checkpoint this trial was (re)started with — None on a fresh
    start (reference ``tune.get_checkpoint``). A PBT exploit restarts the
    trial with the exploited peer's checkpoint here."""
    with _session_lock:
        s = _session
    if s is None:
        raise RuntimeError("tune.get_checkpoint() called outside a trial")
    return s.start_checkpoint


def get_config() -> Dict[str, Any]:
    with _session_lock:
        s = _session
    if s is None:
        raise RuntimeError("tune.get_config() called outside a trial")
    return s.config


def get_trial_id() -> str:
    """Unique id of the running trial (``tune.get_context().get_trial_id``
    in the reference) — e.g. for per-trial output directories."""
    with _session_lock:
        s = _session
    if s is None:
        raise RuntimeError("tune.get_trial_id() called outside a trial")
    return s.trial_id


class _TrialRunner:
    """One trial: runs the trainable function in a thread; the controller
    polls buffered reports (mirrors the Train worker session shape).

    Defined undecorated so cloudpickle exports it by module reference
    (the decorator would rebind the name to the ActorClass wrapper,
    forcing by-value pickling that drags in the module's session lock)."""

    def __init__(self):
        self._thread: Optional[threading.Thread] = None
        self._session: Optional[_TrialSession] = None
        self._done = threading.Event()
        self._error: Optional[str] = None

    def run(self, trainable, config: Dict[str, Any], trial_id: str = "", checkpoint: Any = None) -> bool:
        global _session
        self._session = _TrialSession(config, trial_id, checkpoint)
        with _session_lock:
            _session = self._session

        def _run():
            try:
                result = trainable(config)
                # A returned dict counts as a final report (reference
                # function-trainable semantics).
                if isinstance(result, dict):
                    self._session.report(result)
            except BaseException:  # noqa: BLE001
                self._error = traceback.format_exc()
            finally:
                self._done.set()

        self._thread = threading.Thread(target=_run, daemon=True, name="trial")
        self._thread.start()
        return True

    def poll(self) -> Dict[str, Any]:
        done = self._done.is_set()  # snapshot BEFORE drain (see train)
        error = self._error
        reports, checkpoints = (
            self._session.drain() if self._session else ([], [])
        )
        return {
            "reports": reports,
            "checkpoints": checkpoints,
            "done": done,
            "error": error,
        }


TrialRunner = ray_tpu.remote(_TrialRunner)
