"""Tuner: the experiment controller.

Reference: ``tune/tuner.py:344`` (Tuner.fit) driving
``tune/execution/tune_controller.py:68,666`` — an event loop that
launches trial actors up to the concurrency limit, polls their result
queues, feeds each report to the scheduler (ASHA may STOP a trial), and
collects everything into a ResultGrid.

TPU-first notes: trials reserve resources through the normal scheduling
path (``resources_per_trial`` may include TPU or a placement-group
strategy), and a JaxTrainer ``fit()`` can be the trainable — the trial
actor is control-plane only, the gang runs under it."""

from __future__ import annotations

import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.tune.schedulers import CONTINUE, STOP, FIFOScheduler
from ray_tpu.tune.search import generate_variants
from ray_tpu.tune.trial import (
    ERRORED,
    PENDING,
    RUNNING,
    STOPPED,
    TERMINATED,
    Trial,
    TrialRunner,
)


@dataclass
class TuneConfig:
    metric: Optional[str] = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: int = 4
    scheduler: Any = None  # FIFOScheduler | ASHAScheduler
    seed: Optional[int] = None


@dataclass
class TrialResult:
    trial_id: str
    config: Dict[str, Any]
    metrics: Dict[str, Any]
    metrics_history: List[Dict[str, Any]]
    status: str
    error: Optional[str] = None


class ResultGrid:
    def __init__(self, results: List[TrialResult], metric: Optional[str], mode: str):
        self._results = results
        self._metric = metric
        self._mode = mode

    def __len__(self) -> int:
        return len(self._results)

    def __iter__(self):
        return iter(self._results)

    def __getitem__(self, i: int) -> TrialResult:
        return self._results[i]

    @property
    def errors(self) -> List[TrialResult]:
        return [r for r in self._results if r.status == ERRORED]

    def get_best_result(
        self, metric: Optional[str] = None, mode: Optional[str] = None
    ) -> TrialResult:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("no metric given (set TuneConfig.metric)")
        scored = [r for r in self._results if metric in r.metrics]
        if not scored:
            raise ValueError(f"no trial reported metric {metric!r}")
        key = lambda r: r.metrics[metric]
        return max(scored, key=key) if mode == "max" else min(scored, key=key)

    def get_dataframe(self):
        import pandas as pd

        rows = []
        for r in self._results:
            row = {"trial_id": r.trial_id, "status": r.status}
            row.update({f"config/{k}": v for k, v in r.config.items() if not isinstance(v, dict)})
            row.update(r.metrics)
            rows.append(row)
        return pd.DataFrame(rows)


class Tuner:
    """``Tuner(trainable, param_space=..., tune_config=...).fit()``.

    ``trainable`` is a function ``fn(config) -> None|dict`` reporting via
    ``ray_tpu.tune.report`` — or an object with ``.fit()`` and a
    ``train_loop_config`` attribute (e.g. JaxTrainer), run per-trial with
    the variant config merged into its loop config."""

    def __init__(
        self,
        trainable: Any,
        *,
        param_space: Optional[Dict[str, Any]] = None,
        tune_config: Optional[TuneConfig] = None,
        resources_per_trial: Optional[Dict[str, float]] = None,
    ):
        self._trainable = self._as_function(trainable)
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.resources_per_trial = resources_per_trial or {"CPU": 1.0}

    @staticmethod
    def _as_function(trainable: Any) -> Callable[[Dict[str, Any]], Any]:
        if callable(trainable):
            return trainable
        if hasattr(trainable, "fit"):
            # Trainer-as-trainable (reference train/base_trainer.py:608):
            # merge the variant into train_loop_config, run the gang, and
            # report the final metrics.
            import copy
            import dataclasses

            def run_trainer(config: Dict[str, Any]):
                from ray_tpu.tune.trial import get_trial_id

                trainer = copy.copy(trainable)
                merged = dict(getattr(trainer, "_train_config", None) or {})
                merged.update(config)
                trainer._train_config = merged
                # Per-trial run dir: trials must not share checkpoint
                # state (a shared dir would make trial 2 silently resume
                # trial 1's checkpoint with different hyperparameters).
                rc = getattr(trainer, "run_config", None)
                if rc is not None:
                    trainer.run_config = dataclasses.replace(
                        rc, name=f"{rc.name or 'tune'}-{get_trial_id()}"
                    )
                result = trainer.fit()
                return dict(result.metrics)

            return run_trainer
        raise TypeError(f"trainable must be callable or have .fit(): {trainable!r}")

    def fit(self) -> ResultGrid:
        cfg = self.tune_config
        scheduler = cfg.scheduler or FIFOScheduler()
        # Resolve scheduler metric/mode from TuneConfig (reference: Tuner
        # owns them unless the scheduler explicitly overrides) — a default
        # ASHAScheduler() in a min-mode experiment must rank by min.
        if getattr(scheduler, "mode", "x") is None:
            scheduler.mode = cfg.mode
        metric = getattr(scheduler, "metric", None) or cfg.metric
        variants = generate_variants(
            self.param_space, num_samples=cfg.num_samples, seed=cfg.seed
        )
        trials = [
            Trial(trial_id=f"trial_{i:04d}_{uuid.uuid4().hex[:6]}", config=v)
            for i, v in enumerate(variants)
        ]
        pending = list(trials)
        launching: List[tuple] = []  # (trial, run_ref): actor may be queued
        running: List[Trial] = []
        opts = dict(self.resources_per_trial)
        num_cpus = opts.pop("CPU", 1.0)

        while pending or launching or running:
            while pending and len(launching) + len(running) < cfg.max_concurrent_trials:
                t = pending.pop(0)
                t.actor = TrialRunner.options(
                    num_cpus=num_cpus, resources=opts or None
                ).remote()
                # Fire-and-track: the actor may wait arbitrarily long for
                # cluster capacity — a blocking get() here would stall the
                # poll loop (frozen ASHA decisions) and crash the sweep on
                # an oversubscribed cluster.
                launching.append((t, t.actor.run.remote(self._trainable, t.config, t.trial_id)))

            still_launching: List[tuple] = []
            for t, run_ref in launching:
                done, _ = ray_tpu.wait([run_ref], num_returns=1, timeout=0)
                if not done:
                    still_launching.append((t, run_ref))
                    continue
                try:
                    ray_tpu.get(run_ref, timeout=10)
                    t.status = RUNNING
                    running.append(t)
                except Exception as e:  # noqa: BLE001
                    t.status = ERRORED
                    t.error = f"trial actor failed to start: {e!r}"
                    scheduler.on_trial_complete(t.trial_id)
                    try:
                        ray_tpu.kill(t.actor)  # release its reservation
                    except Exception:
                        pass
            launching = still_launching

            still_running: List[Trial] = []
            for t in running:
                # Per-trial poll: one actor dying (worker OOM/crash) must
                # mark THAT trial errored, not blow up the whole sweep.
                try:
                    poll = ray_tpu.get(t.actor.poll.remote(), timeout=120)
                except Exception as e:  # noqa: BLE001
                    t.status = ERRORED
                    t.error = f"trial actor died: {e!r}"
                    scheduler.on_trial_complete(t.trial_id)
                    continue
                stop = False
                for report in poll["reports"]:
                    t.iterations += 1
                    t.last_metrics = report
                    t.metrics_history.append(report)
                    value = report.get(metric) if metric else None
                    if value is not None:
                        decision = scheduler.on_result(
                            t.trial_id, t.iterations, float(value)
                        )
                        if decision == STOP:
                            stop = True
                            break
                if stop:
                    t.status = STOPPED
                    scheduler.on_trial_complete(t.trial_id)
                    ray_tpu.kill(t.actor)
                elif poll["error"] is not None and not poll["reports"]:
                    t.status = ERRORED
                    t.error = poll["error"]
                    scheduler.on_trial_complete(t.trial_id)
                    ray_tpu.kill(t.actor)
                elif poll["done"] and not poll["reports"]:
                    t.status = TERMINATED
                    scheduler.on_trial_complete(t.trial_id)
                    ray_tpu.kill(t.actor)
                else:
                    still_running.append(t)
            running = still_running
            if pending or launching or running:
                time.sleep(0.02)

        return ResultGrid(
            [
                TrialResult(
                    trial_id=t.trial_id,
                    config=t.config,
                    metrics=t.last_metrics,
                    metrics_history=t.metrics_history,
                    status=t.status,
                    error=t.error,
                )
                for t in trials
            ],
            cfg.metric,
            cfg.mode,
        )
