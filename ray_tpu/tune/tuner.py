"""Tuner: the experiment controller.

Reference: ``tune/tuner.py:344`` (Tuner.fit) driving
``tune/execution/tune_controller.py:68,666`` — an event loop that
launches trial actors up to the concurrency limit, polls their result
queues, feeds each report to the scheduler (ASHA may STOP a trial), and
collects everything into a ResultGrid.

TPU-first notes: trials reserve resources through the normal scheduling
path (``resources_per_trial`` may include TPU or a placement-group
strategy), and a JaxTrainer ``fit()`` can be the trainable — the trial
actor is control-plane only, the gang runs under it."""

from __future__ import annotations

import os
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.tune.schedulers import CONTINUE, EXPLOIT, STOP, FIFOScheduler
from ray_tpu.tune.search import generate_variants
from ray_tpu.tune.trial import (
    ERRORED,
    PENDING,
    RUNNING,
    STOPPED,
    TERMINATED,
    Trial,
    TrialRunner,
)


@dataclass
class TuneConfig:
    metric: Optional[str] = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: int = 4
    scheduler: Any = None  # FIFOScheduler | ASHAScheduler | PBT | MedianStopping
    #: a Searcher (TPESearcher/OptunaSearch/RandomSearch, optionally
    #: wrapped in ConcurrencyLimiter): trial configs come from
    #: suggest() sequentially instead of up-front variant expansion
    search_alg: Any = None
    seed: Optional[int] = None


@dataclass
class TrialResult:
    trial_id: str
    config: Dict[str, Any]
    metrics: Dict[str, Any]
    metrics_history: List[Dict[str, Any]]
    status: str
    error: Optional[str] = None


class ResultGrid:
    def __init__(self, results: List[TrialResult], metric: Optional[str], mode: str):
        self._results = results
        self._metric = metric
        self._mode = mode

    def __len__(self) -> int:
        return len(self._results)

    def __iter__(self):
        return iter(self._results)

    def __getitem__(self, i: int) -> TrialResult:
        return self._results[i]

    @property
    def errors(self) -> List[TrialResult]:
        return [r for r in self._results if r.status == ERRORED]

    def get_best_result(
        self, metric: Optional[str] = None, mode: Optional[str] = None
    ) -> TrialResult:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("no metric given (set TuneConfig.metric)")
        scored = [r for r in self._results if metric in r.metrics]
        if not scored:
            raise ValueError(f"no trial reported metric {metric!r}")
        key = lambda r: r.metrics[metric]
        return max(scored, key=key) if mode == "max" else min(scored, key=key)

    def get_dataframe(self):
        import pandas as pd

        rows = []
        for r in self._results:
            row = {"trial_id": r.trial_id, "status": r.status}
            row.update({f"config/{k}": v for k, v in r.config.items() if not isinstance(v, dict)})
            row.update(r.metrics)
            rows.append(row)
        return pd.DataFrame(rows)


class Tuner:
    """``Tuner(trainable, param_space=..., tune_config=...).fit()``.

    ``trainable`` is a function ``fn(config) -> None|dict`` reporting via
    ``ray_tpu.tune.report`` — or an object with ``.fit()`` and a
    ``train_loop_config`` attribute (e.g. JaxTrainer), run per-trial with
    the variant config merged into its loop config."""

    def __init__(
        self,
        trainable: Any,
        *,
        param_space: Optional[Dict[str, Any]] = None,
        tune_config: Optional[TuneConfig] = None,
        resources_per_trial: Optional[Dict[str, float]] = None,
        run_config: Any = None,  # train.RunConfig: name + storage_path
    ):
        self._trainable = self._as_function(trainable)
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.resources_per_trial = resources_per_trial or {"CPU": 1.0}
        self.run_config = run_config
        self._restored_trials: Optional[List[Trial]] = None

    # -- experiment snapshot/resume (reference experiment_state.py) -----
    def _snapshot_path(self) -> Optional[str]:
        rc = self.run_config
        if rc is None or not getattr(rc, "storage_path", None):
            return None
        name = getattr(rc, "name", None) or "tune_experiment"
        return os.path.join(rc.storage_path, name, "tuner.pkl")

    def _save_snapshot(self, trials: List[Trial]) -> None:
        path = self._snapshot_path()
        if path is None:
            return
        os.makedirs(os.path.dirname(path), exist_ok=True)
        state = {
            "param_space": self.param_space,
            "tune_config": self.tune_config,
            "resources_per_trial": self.resources_per_trial,
            "run_config": self.run_config,
            "trials": [
                Trial(
                    trial_id=t.trial_id,
                    config=t.config,
                    status=t.status,
                    last_metrics=t.last_metrics,
                    metrics_history=list(t.metrics_history),
                    iterations=t.iterations,
                    error=t.error,
                    last_checkpoint=t.last_checkpoint,
                )
                for t in trials
            ],
        }
        tmp = path + ".tmp"
        try:
            import cloudpickle  # schedulers may hold lambdas (PBT mutations)

            with open(tmp, "wb") as f:
                cloudpickle.dump(state, f)
            os.replace(tmp, path)
        except Exception:  # noqa: BLE001 — a snapshot must never kill a sweep
            import logging

            logging.getLogger(__name__).exception("experiment snapshot failed")
            try:
                os.remove(tmp)
            except OSError:
                pass

    @classmethod
    def restore(cls, path: str, trainable: Any) -> "Tuner":
        """Resume a killed/crashed sweep from its snapshot (reference
        ``Tuner.restore``): finished trials keep their results;
        unfinished ones restart from their latest reported checkpoint."""
        if os.path.isdir(path):
            path = os.path.join(path, "tuner.pkl")
        import cloudpickle

        with open(path, "rb") as f:
            state = cloudpickle.load(f)
        tuner = cls(
            trainable,
            param_space=state["param_space"],
            tune_config=state["tune_config"],
            resources_per_trial=state["resources_per_trial"],
            run_config=state["run_config"],
        )
        tuner._restored_trials = state["trials"]
        return tuner

    @staticmethod
    def _as_function(trainable: Any) -> Callable[[Dict[str, Any]], Any]:
        if callable(trainable):
            return trainable
        if hasattr(trainable, "fit"):
            # Trainer-as-trainable (reference train/base_trainer.py:608):
            # merge the variant into train_loop_config, run the gang, and
            # report the final metrics.
            import copy
            import dataclasses

            def run_trainer(config: Dict[str, Any]):
                from ray_tpu.tune.trial import get_trial_id

                trainer = copy.copy(trainable)
                merged = dict(getattr(trainer, "_train_config", None) or {})
                merged.update(config)
                trainer._train_config = merged
                # Per-trial run dir: trials must not share checkpoint
                # state (a shared dir would make trial 2 silently resume
                # trial 1's checkpoint with different hyperparameters).
                rc = getattr(trainer, "run_config", None)
                if rc is not None:
                    trainer.run_config = dataclasses.replace(
                        rc, name=f"{rc.name or 'tune'}-{get_trial_id()}"
                    )
                result = trainer.fit()
                return dict(result.metrics)

            return run_trainer
        raise TypeError(f"trainable must be callable or have .fit(): {trainable!r}")

    def fit(self) -> ResultGrid:
        cfg = self.tune_config
        scheduler = cfg.scheduler or FIFOScheduler()
        # Resolve scheduler metric/mode from TuneConfig (reference: Tuner
        # owns them unless the scheduler explicitly overrides) — a default
        # ASHAScheduler() in a min-mode experiment must rank by min.
        if getattr(scheduler, "mode", "x") is None:
            scheduler.mode = cfg.mode
        metric = getattr(scheduler, "metric", None) or cfg.metric
        search = cfg.search_alg
        if search is not None:
            search.set_search_properties(cfg.metric, cfg.mode, self.param_space)
        callbacks = list(getattr(self.run_config, "callbacks", None) or [])
        exp_dir = None
        rc = self.run_config
        if rc is not None and getattr(rc, "storage_path", None):
            exp_dir = os.path.join(
                rc.storage_path, getattr(rc, "name", None) or "tune_experiment"
            )
        for cb in callbacks:
            setup = getattr(cb, "setup", None)
            if setup is not None:
                setup(exp_dir)

        def notify_complete(t: Trial) -> None:
            scheduler.on_trial_complete(t.trial_id)
            if search is not None:
                search.on_trial_complete(t.trial_id, t.last_metrics or {})
            for cb in callbacks:
                try:
                    cb.on_trial_complete(t)
                except Exception:
                    pass

        if self._restored_trials is not None:
            trials = self._restored_trials
            # unfinished trials restart (from their latest checkpoint)
            pending = []
            for t in trials:
                if t.status in (PENDING, RUNNING):
                    t.status = PENDING
                    t.actor = None
                    pending.append(t)
        elif search is not None:
            # sequential suggestion: trials materialize as slots open so
            # the searcher can condition on completed results
            trials = []
            pending = []
        else:
            variants = generate_variants(
                self.param_space, num_samples=cfg.num_samples, seed=cfg.seed
            )
            trials = [
                Trial(trial_id=f"trial_{i:04d}_{uuid.uuid4().hex[:6]}", config=v)
                for i, v in enumerate(variants)
            ]
            pending = list(trials)
        to_suggest = 0
        if search is not None:
            if self._restored_trials is None:
                to_suggest = cfg.num_samples
            else:
                # resume: replay completed trials into the searcher so
                # its model warm-starts, then suggest the REMAINING
                # budget (not zero — that would silently truncate).
                # add_evaluated_point, NOT on_trial_complete: restored
                # trial ids were never suggest()-ed in this process, so
                # id-keyed completion is a silent no-op for TPE/Optuna
                # (their live-trial maps are empty after a restart).
                for t in trials:
                    if t.status in (TERMINATED, STOPPED, ERRORED) and t.last_metrics:
                        search.add_evaluated_point(t.config, t.last_metrics)
                to_suggest = max(0, cfg.num_samples - len(trials))
        trials_by_id = {t.trial_id: t for t in trials}
        launching: List[tuple] = []  # (trial, run_ref): actor may be queued
        running: List[Trial] = []
        opts = dict(self.resources_per_trial)
        num_cpus = opts.pop("CPU", 1.0)
        last_snapshot = 0.0

        def launch(t: Trial) -> None:
            t.actor = TrialRunner.options(
                num_cpus=num_cpus, resources=opts or None
            ).remote()
            # Fire-and-track: the actor may wait arbitrarily long for
            # cluster capacity — a blocking get() here would stall the
            # poll loop (frozen ASHA decisions) and crash the sweep on
            # an oversubscribed cluster.
            launching.append(
                (
                    t,
                    t.actor.run.remote(
                        self._trainable, t.config, t.trial_id, t.last_checkpoint
                    ),
                )
            )

        while pending or launching or running or to_suggest > 0:
            now = time.monotonic()
            if now - last_snapshot > 2.0:
                last_snapshot = now
                self._save_snapshot(trials)
            while pending and len(launching) + len(running) < cfg.max_concurrent_trials:
                launch(pending.pop(0))
            while (
                to_suggest > 0
                and len(launching) + len(running) < cfg.max_concurrent_trials
            ):
                tid = f"trial_{len(trials):04d}_{uuid.uuid4().hex[:6]}"
                config = search.suggest(tid)
                if config is None:
                    break  # ConcurrencyLimiter: wait for a completion
                t = Trial(trial_id=tid, config=config)
                trials.append(t)
                trials_by_id[tid] = t
                to_suggest -= 1
                launch(t)

            still_launching: List[tuple] = []
            for t, run_ref in launching:
                done, _ = ray_tpu.wait([run_ref], num_returns=1, timeout=0)
                if not done:
                    still_launching.append((t, run_ref))
                    continue
                try:
                    ray_tpu.get(run_ref, timeout=10)
                    t.status = RUNNING
                    running.append(t)
                except Exception as e:  # noqa: BLE001
                    t.status = ERRORED
                    t.error = f"trial actor failed to start: {e!r}"
                    notify_complete(t)
                    try:
                        ray_tpu.kill(t.actor)  # release its reservation
                    except Exception:
                        pass
            launching = still_launching

            still_running: List[Trial] = []
            for t in running:
                # Per-trial poll: one actor dying (worker OOM/crash) must
                # mark THAT trial errored, not blow up the whole sweep.
                try:
                    poll = ray_tpu.get(t.actor.poll.remote(), timeout=120)
                except Exception as e:  # noqa: BLE001
                    t.status = ERRORED
                    t.error = f"trial actor died: {e!r}"
                    notify_complete(t)
                    continue
                stop = False
                exploit_src: Optional[str] = None
                checkpoints = poll.get("checkpoints") or [None] * len(poll["reports"])
                for report, ck in zip(poll["reports"], checkpoints):
                    # every drained report is recorded and fed to the
                    # scheduler even after a decision fires — a batch must
                    # never silently truncate history/checkpoints
                    t.iterations += 1
                    t.last_metrics = report
                    t.metrics_history.append(report)
                    if ck is not None:
                        t.last_checkpoint = ck
                    for cb in callbacks:
                        try:
                            cb.on_trial_result(t, report)
                        except Exception:
                            pass
                    value = report.get(metric) if metric else None
                    if value is not None and not stop and exploit_src is None:
                        decision = scheduler.on_result(
                            t.trial_id, t.iterations, float(value)
                        )
                        if decision == STOP:
                            stop = True
                        elif (
                            isinstance(decision, tuple)
                            and decision[0] == EXPLOIT
                        ):
                            exploit_src = decision[1]
                if exploit_src is not None and not stop:
                    # PBT exploit/explore: restart from the top peer's
                    # checkpoint with a mutated copy of its config
                    src = trials_by_id.get(exploit_src)
                    if src is not None and src.last_checkpoint is not None:
                        ray_tpu.kill(t.actor)
                        t.config = scheduler.explore(dict(src.config))
                        t.last_checkpoint = src.last_checkpoint
                        t.status = PENDING
                        pending.append(t)
                        continue
                    # source has nothing to exploit yet: keep running
                    still_running.append(t)
                    continue
                if stop:
                    t.status = STOPPED
                    notify_complete(t)
                    ray_tpu.kill(t.actor)
                elif poll["error"] is not None and not poll["reports"]:
                    t.status = ERRORED
                    t.error = poll["error"]
                    notify_complete(t)
                    ray_tpu.kill(t.actor)
                elif poll["done"] and not poll["reports"]:
                    t.status = TERMINATED
                    notify_complete(t)
                    ray_tpu.kill(t.actor)
                else:
                    still_running.append(t)
            running = still_running
            if pending or launching or running:
                time.sleep(0.02)

        self._save_snapshot(trials)
        return ResultGrid(
            [
                TrialResult(
                    trial_id=t.trial_id,
                    config=t.config,
                    metrics=t.last_metrics,
                    metrics_history=t.metrics_history,
                    status=t.status,
                    error=t.error,
                )
                for t in trials
            ],
            cfg.metric,
            cfg.mode,
        )
