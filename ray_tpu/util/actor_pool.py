"""ActorPool: load-balanced work submission over a fixed actor set.

Reference: ``python/ray/util/actor_pool.py:13`` — same surface:
``submit``, ``get_next`` / ``get_next_unordered``, ``map`` /
``map_unordered``, ``has_next``, ``push``/``pop_idle``.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, List

import ray_tpu


class ActorPool:
    def __init__(self, actors: Iterable[Any]):
        self._idle: List[Any] = list(actors)
        #: ref -> (actor, submit order index)
        self._inflight: dict = {}
        self._index_to_ref: dict = {}
        self._next_submit = 0
        self._next_return = 0

    def submit(self, fn: Callable[[Any, Any], Any], value: Any) -> None:
        """``fn(actor, value) -> ObjectRef``, e.g.
        ``pool.submit(lambda a, v: a.work.remote(v), item)``."""
        if not self._idle:
            raise ValueError("no idle actors — call get_next* first")
        actor = self._idle.pop(0)
        ref = fn(actor, value)
        self._inflight[ref] = (actor, self._next_submit)
        self._index_to_ref[self._next_submit] = ref
        self._next_submit += 1

    def has_next(self) -> bool:
        return bool(self._inflight)

    def has_free(self) -> bool:
        return bool(self._idle)

    def get_next(self, timeout: float | None = None) -> Any:
        """Next result in SUBMISSION order. On timeout the task stays
        pending (retry later); the actor re-idles only once its result
        (or task error) is actually consumed — a timed-out task's actor
        is still busy and must not be double-booked."""
        from ray_tpu.core.exceptions import GetTimeoutError

        # skip indices consumed out-of-order by get_next_unordered
        while (
            self._next_return not in self._index_to_ref
            and self._next_return < self._next_submit
        ):
            self._next_return += 1
        if self._next_return not in self._index_to_ref:
            raise StopIteration("no pending result")
        ref = self._index_to_ref[self._next_return]
        try:
            result = ray_tpu.get(ref, timeout=timeout)
        except GetTimeoutError:
            raise  # state untouched: caller can retry
        except Exception:
            self._consume(ref, self._next_return)
            raise  # task error = delivered result
        self._consume(ref, self._next_return)
        return result

    def _consume(self, ref, idx: int) -> None:
        self._index_to_ref.pop(idx, None)
        actor, _ = self._inflight.pop(ref)
        self._next_return = idx + 1
        self._idle.append(actor)

    def get_next_unordered(self, timeout: float | None = None) -> Any:
        """Whichever pending result finishes first."""
        from ray_tpu.core.exceptions import GetTimeoutError

        if not self._inflight:
            raise StopIteration("no pending result")
        done, _ = ray_tpu.wait(
            list(self._inflight), num_returns=1, timeout=timeout
        )
        if not done:
            raise TimeoutError("no result ready in time")
        ref = done[0]
        _actor, idx = self._inflight[ref]
        try:
            result = ray_tpu.get(ref, timeout=timeout)
        except GetTimeoutError:
            raise
        except Exception:
            self._consume_unordered(ref, idx)
            raise
        self._consume_unordered(ref, idx)
        return result

    def _consume_unordered(self, ref, idx: int) -> None:
        actor, _ = self._inflight.pop(ref)
        self._index_to_ref.pop(idx, None)
        self._idle.append(actor)

    def map(self, fn: Callable, values: Iterable[Any]) -> Iterator[Any]:
        values = list(values)
        sent = 0
        for v in values:
            if not self.has_free():
                break
            self.submit(fn, v)
            sent += 1
        for v in values[sent:]:
            yield self.get_next()
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: Iterable[Any]) -> Iterator[Any]:
        values = list(values)
        sent = 0
        for v in values:
            if not self.has_free():
                break
            self.submit(fn, v)
            sent += 1
        for v in values[sent:]:
            yield self.get_next_unordered()
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    def push(self, actor: Any) -> None:
        self._idle.append(actor)

    def pop_idle(self) -> Any:
        if not self._idle:
            raise ValueError("no idle actors")
        return self._idle.pop()
