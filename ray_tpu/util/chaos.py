"""Chaos-injection harness for tests: random worker / node killers.

Reference: ``python/ray/_private/test_utils.py:1496-1740``
(``ResourceKillerActor`` / ``WorkerKillerActor`` / ``NodeKillerBase`` +
``start_resource_killer``): background killers take out workers or
whole nodes at random intervals while a workload runs; the workload
must still complete CORRECTLY (retries, actor restarts, lineage
reconstruction). This is the test class the reference's fault-tolerance
reputation rests on.

Worker identification: workers run ``-m ray_tpu.core.worker_main`` with
``RAY_TPU_CONTROLLER_ADDR`` in their env — scanning ``/proc`` for that
pair scopes kills to ONE test cluster even with several running.
"""

from __future__ import annotations

import os
import random
import signal
import threading
import time
from typing import List, Optional


def find_worker_pids(controller_addr: str) -> List[int]:
    """PIDs of worker_main processes attached to ``controller_addr``
    (shared /proc scan: ``util/reaper.py::find_runtime_pids``)."""
    from ray_tpu.util.reaper import find_runtime_pids

    return find_runtime_pids(
        patterns=("ray_tpu.core.worker_main",), controller_addr=controller_addr
    )


class WorkerKiller:
    """Kills a random session worker every ``interval_s`` until stopped
    (reference ``WorkerKillerActor``). Run alongside a workload; the
    workload's correctness under SIGKILLed workers is the assertion."""

    def __init__(
        self,
        controller_addr: str,
        *,
        interval_s: float = 1.0,
        max_kills: Optional[int] = None,
        seed: Optional[int] = None,
    ):
        self.controller_addr = controller_addr
        self.interval_s = interval_s
        self.max_kills = max_kills
        self.kills: List[int] = []
        self._rng = random.Random(seed)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "WorkerKiller":
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="chaos-worker-killer"
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            if self.max_kills is not None and len(self.kills) >= self.max_kills:
                return
            pids = find_worker_pids(self.controller_addr)
            if not pids:
                continue
            pid = self._rng.choice(pids)
            try:
                os.kill(pid, signal.SIGKILL)
                self.kills.append(pid)
            except OSError:
                pass  # already gone

    def stop(self) -> List[int]:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        return self.kills


class NodeKiller:
    """Periodically hard-kills a random non-head node of a
    ``cluster_utils.Cluster`` and (optionally) replaces it — the
    elastic-membership half of the reference's ``NodeKillerBase``."""

    def __init__(
        self,
        cluster,
        *,
        interval_s: float = 3.0,
        replace: bool = True,
        node_resources: Optional[dict] = None,
        num_cpus: float = 1,
        max_kills: Optional[int] = None,
        seed: Optional[int] = None,
    ):
        self.cluster = cluster
        self.interval_s = interval_s
        self.replace = replace
        self.node_resources = node_resources
        self.num_cpus = num_cpus
        self.max_kills = max_kills
        self.kills = 0
        self._rng = random.Random(seed)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "NodeKiller":
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="chaos-node-killer"
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            if self.max_kills is not None and self.kills >= self.max_kills:
                return
            nodes = list(self.cluster.nodes)
            if not nodes:
                continue
            node = self._rng.choice(nodes)
            try:
                self.cluster.remove_node(node)
                self.kills += 1
            except Exception:
                continue
            if self.replace:
                self.cluster.add_node(
                    num_cpus=self.num_cpus, resources=self.node_resources
                )

    def stop(self) -> int:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        return self.kills
