"""Chaos-injection harness for tests: random worker / node killers.

Reference: ``python/ray/_private/test_utils.py:1496-1740``
(``ResourceKillerActor`` / ``WorkerKillerActor`` / ``NodeKillerBase`` +
``start_resource_killer``): background killers take out workers or
whole nodes at random intervals while a workload runs; the workload
must still complete CORRECTLY (retries, actor restarts, lineage
reconstruction). This is the test class the reference's fault-tolerance
reputation rests on.

Worker identification: workers run ``-m ray_tpu.core.worker_main`` with
``RAY_TPU_CONTROLLER_ADDR`` in their env — scanning ``/proc`` for that
pair scopes kills to ONE test cluster even with several running.
"""

from __future__ import annotations

import hashlib
import os
import random
import signal
import threading
import time
from typing import List, Optional, Tuple

#: RPC chaos fault modes (see ``core/rpc.py`` injection points).
#: request_drop — fails before the handler runs (a retry is trivially
#:   safe; the reference rpc_chaos contract).
#: reply_drop — the handler RUNS, then the reply is lost: the
#:   duplicate-execution trap that request-id dedup exists to defuse.
#: delay — latency injection before the handler (exercises client
#:   timeouts racing in-flight executions).
#: disconnect — hard connection reset mid-call (exercises reconnect +
#:   cross-connection dedup).
RPC_FAULT_MODES = ("request_drop", "reply_drop", "delay", "disconnect")


class RpcFaultPlan:
    """Seeded, per-method RPC fault plan (the post-execution upgrade of
    the reference's ``rpc_chaos.h`` pre-handler-only injection).

    Spec grammar (``RAY_TPU_testing_rpc_chaos``)::

        "<method|*>:<mode>:<prob>[:<param>][, ...]"

    e.g. ``"kv_put:reply_drop:0.4,*:delay:0.05:0.1"``. The first rule
    whose method matches wins; ``param`` is the delay seconds for
    ``delay`` (default 0.05) and ignored otherwise.

    DETERMINISM CONTRACT: exactly one RNG draw per :meth:`next_fault`
    consult, whether or not any rule matches — so the full injection
    sequence is a pure function of (seed, the ordered sequence of
    consulted method names). A failure log carrying the seed plus the
    spec reproduces the exact fault schedule.
    """

    def __init__(self, spec: str, seed: int):
        self.spec = spec
        self.seed = seed
        self.rules: List[Tuple[str, str, float, float]] = []
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            fields = part.split(":")
            if len(fields) < 3:
                raise ValueError(f"bad rpc chaos rule {part!r} (need method:mode:prob)")
            method, mode, prob = fields[0], fields[1], float(fields[2])
            if mode not in RPC_FAULT_MODES:
                raise ValueError(f"unknown rpc chaos mode {mode!r} (one of {RPC_FAULT_MODES})")
            param = float(fields[3]) if len(fields) > 3 else 0.05
            self.rules.append((method, mode, prob, param))
        self._rng = random.Random(seed)
        self.consults = 0
        self.injections = 0

    def next_fault(self, method: str) -> Optional[Tuple[str, float]]:
        """One deterministic consult: ``(mode, param)`` to inject a fault
        for this dispatch of ``method``, else None."""
        draw = self._rng.random()  # ALWAYS one draw (see class docstring)
        self.consults += 1
        for rule_method, mode, prob, param in self.rules:
            if rule_method == "*" or rule_method == method:
                if draw < prob:
                    self.injections += 1
                    return (mode, param)
                return None
        return None


#: Data-plane chaos fault modes (consulted by the PULL manager once per
#: chunk attempt — receiver-side, so the whole schedule lives in one
#: process and replays from one seed; see ``core/pull_manager.py``).
#: chunk_drop — the chunk fetch fails before any data lands (retry path).
#: chunk_corrupt — the chunk arrives with flipped bytes; the per-chunk
#:   crc MUST catch it before the data reaches the destination segment.
#: chunk_stall — the fetch stalls ``param`` seconds then times out
#:   (exercises the per-chunk timeout machinery).
#: source_die_mid_transfer — the current source becomes unreachable for
#:   the rest of this transfer: the pull must fail over to another
#:   source and RESUME from the last verified offset.
DATA_FAULT_MODES = (
    "chunk_drop", "chunk_corrupt", "chunk_stall", "source_die_mid_transfer",
)


class DataFaultPlan:
    """Seeded data-plane fault plan for object transfer
    (``RAY_TPU_testing_pull_chaos``).

    Spec grammar::

        "<mode>:<prob>[:<param>][, ...]"

    e.g. ``"chunk_corrupt:0.2,chunk_stall:0.05:0.3"``. Rules are
    consulted in order; the FIRST rule whose probability fires wins.
    ``param`` is the stall seconds for ``chunk_stall`` (default 0.05)
    and ignored otherwise.

    DETERMINISM CONTRACT (same as :class:`RpcFaultPlan`): exactly one
    RNG draw per rule per :meth:`next_fault` consult, in rule order —
    the full injection sequence is a pure function of (seed, number of
    consults). A failure log carrying the seed plus the spec reproduces
    the exact fault schedule.
    """

    def __init__(self, spec: str, seed: int):
        self.spec = spec
        self.seed = seed
        self.rules: List[Tuple[str, float, float]] = []
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            fields = part.split(":")
            if len(fields) < 2:
                raise ValueError(f"bad pull chaos rule {part!r} (need mode:prob)")
            mode, prob = fields[0], float(fields[1])
            if mode not in DATA_FAULT_MODES:
                raise ValueError(
                    f"unknown pull chaos mode {mode!r} (one of {DATA_FAULT_MODES})"
                )
            param = float(fields[2]) if len(fields) > 2 else 0.05
            self.rules.append((mode, prob, param))
        self._rng = random.Random(seed)
        self.consults = 0
        self.injections = 0

    def next_fault(self) -> Optional[Tuple[str, float]]:
        """One deterministic consult: ``(mode, param)`` to inject into
        this chunk attempt, else None. A fixed number of draws happens
        regardless of outcome (one per rule) — see the class docstring."""
        self.consults += 1
        hit: Optional[Tuple[str, float]] = None
        for mode, prob, param in self.rules:
            draw = self._rng.random()  # ALWAYS drawn, even after a hit
            if hit is None and draw < prob:
                hit = (mode, param)
        if hit is not None:
            self.injections += 1
        return hit


def derive_plan_seed(master_seed: int, label: str) -> int:
    """Per-plan seed derived from the MASTER chaos seed
    (``RAY_TPU_testing_chaos_seed``): keyed blake2b of the plan label so
    the three plans (rpc / pull / replica) get distinct but fully
    deterministic streams from one logged number. Forced odd (never 0 —
    0 means "generate" in the config grammar)."""
    digest = hashlib.blake2b(
        f"{int(master_seed)}:{label}".encode(), digest_size=4
    ).digest()
    return int.from_bytes(digest, "little") | 1


class SeededPlanCache:
    """Process-wide lazy singleton for one env/config-driven seeded
    fault plan (the shared shape behind ``rpc.active_fault_plan``,
    ``pull_manager.active_pull_fault_plan`` and
    ``engine.active_replica_fault_plan``): built once per (spec, seed)
    config value, rebuilt when either changes, seed generated-and-LOGGED
    at activation so any failure reproduces from the log alone."""

    def __init__(self, plan_cls, label: str, spec_attr: str, seed_attr: str, logger):
        self._plan_cls = plan_cls
        self._label = label
        self._spec_attr = spec_attr
        self._seed_attr = seed_attr
        self._logger = logger
        self._lock = threading.Lock()
        self._key: Optional[Tuple[str, int, int]] = None
        self._plan = None

    def active(self):
        """The current plan, or None when the spec knob is empty."""
        from ray_tpu.core.config import GLOBAL_CONFIG

        spec = getattr(GLOBAL_CONFIG, self._spec_attr)
        if not spec:
            return None
        master = int(getattr(GLOBAL_CONFIG, "testing_chaos_seed", 0) or 0)
        key = (spec, getattr(GLOBAL_CONFIG, self._seed_attr), master)
        if self._key == key:
            return self._plan
        with self._lock:
            if self._key == key:
                return self._plan
            # explicit per-plan seed > master-derived > generated: an
            # armed master seed makes the whole composite chaos run
            # reproduce from ONE logged number
            if key[1]:
                seed, origin = key[1], ""
            elif master:
                seed = derive_plan_seed(master, self._label)
                origin = f" [derived from RAY_TPU_testing_chaos_seed={master}]"
            else:
                seed = int.from_bytes(os.urandom(4), "little") | 1
                origin = ""
            plan = self._plan_cls(spec, seed)
            self._logger.warning(
                "%s chaos plan ACTIVE: spec=%r seed=%d "
                "(reproduce: RAY_TPU_%s=%r RAY_TPU_%s=%d)%s",
                self._label, spec, seed,
                self._spec_attr, spec, self._seed_attr, seed, origin,
            )
            self._plan, self._key = plan, key
            return plan


#: Replica/engine chaos fault modes (consulted by the LLM engine's step
#: loop once per executed step phase — see ``inference/engine.py``).
#: kill_mid_decode — SIGKILL the replica process right before a planned
#:   decode batch runs: the last emitted token reached (or is in flight
#:   to) the owner, the next one never samples — the exact boundary the
#:   router's seq-numbered resume protocol exists for.
#: kill_mid_prefill — SIGKILL before a planned prefill chunk runs
#:   (exercises resume before/while the first token is produced).
#: kill_mid_export — SIGKILL while a prefill replica is gathering a
#:   request's KV blocks for migration (disaggregated serving): the
#:   exported segment never publishes, the router's handoff dispatch
#:   fails, and the request must degrade to plain generation.
#: kill_mid_import — SIGKILL while a decode replica is scattering
#:   migrated KV into its cache: the stream dies before its first token
#:   and the resumable-stream machinery replays it (without the
#:   descriptor) on a survivor.
#: stall — the step loop sleeps ``param`` seconds mid-step: the actor's
#:   async loop keeps answering RPCs while the engine wedges, which is
#:   exactly what the serve controller's health poll (not liveness
#:   checks) must catch and restart.
REPLICA_FAULT_MODES = (
    "kill_mid_decode",
    "kill_mid_prefill",
    "kill_mid_export",
    "kill_mid_import",
    "stall",
)


class ReplicaFaultPlan:
    """Seeded replica-death fault plan for LLM serving
    (``RAY_TPU_testing_replica_chaos``).

    Spec grammar::

        "<mode>:<prob>[:<param>][:<max>][, ...]"

    e.g. ``"kill_mid_decode:1.0:8"`` (deterministically kill on the 9th
    decode-phase consult) or ``"stall:0.2:5.0:1"``. Fields:

    * ``param`` — for ``stall``: seconds to sleep (default 1.0); for the
      kill modes: number of matching-phase consults to SKIP before the
      rule becomes eligible (default 0) — what lets a test land the kill
      mid-stream instead of on the first token.
    * ``max`` — injection cap per process (default 1). The plan is
      usually installed via env/system-config, so EVERY replica —
      including every controller-spawned replacement — runs the same
      schedule; an uncapped stall rule would wedge each incarnation
      forever and the deployment would never converge. (A kill ends the
      process anyway; the cap matters for ``stall``.)

    Consults happen once per engine-step phase that has work: the engine
    calls ``consult("prefill")`` when the step runs prefill chunks and
    ``consult("decode")`` when it runs a decode batch.

    DETERMINISM CONTRACT (same as :class:`RpcFaultPlan`): exactly one
    RNG draw per consult, whether or not any rule matches — the full
    injection schedule is a pure function of (seed, the ordered sequence
    of consulted phases). A failure log carrying the seed plus the spec
    reproduces the exact fault schedule.
    """

    def __init__(self, spec: str, seed: int):
        self.spec = spec
        self.seed = seed
        #: [mode, prob, param, max_injections]
        self.rules: List[List[float]] = []
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            fields = part.split(":")
            if len(fields) < 2:
                raise ValueError(
                    f"bad replica chaos rule {part!r} (need mode:prob)"
                )
            mode, prob = fields[0], float(fields[1])
            if mode not in REPLICA_FAULT_MODES:
                raise ValueError(
                    f"unknown replica chaos mode {mode!r} "
                    f"(one of {REPLICA_FAULT_MODES})"
                )
            param = float(fields[2]) if len(fields) > 2 else (
                1.0 if mode == "stall" else 0.0
            )
            cap = int(fields[3]) if len(fields) > 3 else 1
            self.rules.append([mode, prob, param, cap])
        self._rng = random.Random(seed)
        self.consults = 0
        self.injections = 0
        #: matching-phase consults seen per rule (the kill-mode skip
        #: window counts these, not global consults)
        self._phase_consults = [0] * len(self.rules)
        self._injected = [0] * len(self.rules)

    @staticmethod
    def _matches(mode: str, phase: str) -> bool:
        if mode == "stall":
            return True
        return mode == f"kill_mid_{phase}"

    def consult(self, phase: str) -> Optional[Tuple[str, float]]:
        """One deterministic consult for an engine-step phase
        (``"prefill"`` | ``"decode"``): ``(mode, param)`` to inject,
        else None. Exactly one RNG draw regardless of outcome."""
        draw = self._rng.random()  # ALWAYS one draw (see class docstring)
        self.consults += 1
        for i, (mode, prob, param, cap) in enumerate(self.rules):
            if not self._matches(mode, phase):
                continue
            self._phase_consults[i] += 1
            if mode != "stall" and self._phase_consults[i] <= param:
                return None  # inside the skip window
            if self._injected[i] >= cap:
                return None
            if draw < prob:
                self._injected[i] += 1
                self.injections += 1
                return (mode, param)
            return None  # first matching rule owns the draw
        return None


#: KV-tier chaos fault modes (consulted by the tier fault-in path in
#: ``inference/kv_transfer.py`` / ``inference/serve_llm.py`` once per
#: executed tier phase).
#: missing_block — a tier block a router's directory promised is gone
#:   when the replica tries to fault it in (reaped between advert and
#:   fetch): the fetch is skipped, the fallback ladder engages.
#: corrupt_block — the faulted-in payload is bit-flipped before the
#:   digest-before-attach gate, which must REJECT it (the tier never
#:   silently serves wrong KV; fallback ladder engages).
#: stale_advert — the holder's tier entry is deleted right before the
#:   fetch, modeling an advert the retraction hasn't reached the router
#:   yet: the pull fails fast with no source and falls through in one
#:   hop, not a timeout.
#: kill_mid_migration — SIGKILL the importing replica while it is
#:   scattering faulted-in tier KV: the resumable-stream machinery must
#:   carry the request to yet another survivor.
KV_TIER_FAULT_MODES = (
    "missing_block",
    "corrupt_block",
    "stale_advert",
    "kill_mid_migration",
)


class KvTierFaultPlan:
    """Seeded KV-tier fault plan (``RAY_TPU_testing_kv_tier_chaos``).

    Spec grammar (same shape as :class:`ReplicaFaultPlan`)::

        "<mode>:<prob>[:<param>][:<max>][, ...]"

    ``param`` is the number of matching-phase consults to SKIP before
    the rule becomes eligible (default 0); ``max`` is the per-process
    injection cap (default 1 — env-installed plans re-arm in every
    replacement replica, so an uncapped rule would starve the fallback
    ladder's terminal rung forever).

    Consults happen once per tier phase that executes: ``"fault_in"``
    when a replica starts pulling an advertised block and
    ``"migration"`` when faulted-in KV is being scattered into the
    cache. Modes match phases: the three block faults match
    ``fault_in``; ``kill_mid_migration`` matches ``migration``.

    DETERMINISM CONTRACT (same as :class:`RpcFaultPlan`): exactly one
    RNG draw per consult, whether or not any rule matches — the full
    injection schedule is a pure function of (seed, ordered consulted
    phases), so a failure log carrying seed + spec replays exactly.
    """

    def __init__(self, spec: str, seed: int):
        self.spec = spec
        self.seed = seed
        #: [mode, prob, param, max_injections]
        self.rules: List[List[float]] = []
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            fields = part.split(":")
            if len(fields) < 2:
                raise ValueError(
                    f"bad kv-tier chaos rule {part!r} (need mode:prob)"
                )
            mode, prob = fields[0], float(fields[1])
            if mode not in KV_TIER_FAULT_MODES:
                raise ValueError(
                    f"unknown kv-tier chaos mode {mode!r} "
                    f"(one of {KV_TIER_FAULT_MODES})"
                )
            param = float(fields[2]) if len(fields) > 2 else 0.0
            cap = int(fields[3]) if len(fields) > 3 else 1
            self.rules.append([mode, prob, param, cap])
        self._rng = random.Random(seed)
        self.consults = 0
        self.injections = 0
        self._phase_consults = [0] * len(self.rules)
        self._injected = [0] * len(self.rules)

    @staticmethod
    def _matches(mode: str, phase: str) -> bool:
        if mode == "kill_mid_migration":
            return phase == "migration"
        return phase == "fault_in"

    def consult(self, phase: str) -> Optional[Tuple[str, float]]:
        """One deterministic consult for a tier phase (``"fault_in"`` |
        ``"migration"``): ``(mode, param)`` to inject, else None.
        Exactly one RNG draw regardless of outcome."""
        draw = self._rng.random()  # ALWAYS one draw (see class docstring)
        self.consults += 1
        for i, (mode, prob, param, cap) in enumerate(self.rules):
            if not self._matches(mode, phase):
                continue
            self._phase_consults[i] += 1
            if self._phase_consults[i] <= param:
                return None  # inside the skip window
            if self._injected[i] >= cap:
                return None
            if draw < prob:
                self._injected[i] += 1
                self.injections += 1
                return (mode, param)
            return None  # first matching rule owns the draw
        return None


#: Controller (control-plane) chaos fault modes, consulted by
#: ``core/controller.py`` once per executed control-plane phase.
#: kill_mid_mutation — SIGKILL the controller right after a WAL append
#:   commits but before the RPC reply is sent: the mutation is logged
#:   but unacked — recovery must surface it (replay) and the client's
#:   retry must dedup against the re-seeded reply cache.
#: kill_mid_snapshot — SIGKILL between the snapshot tmp write and the
#:   rename-commit: recovery must use the LAST GOOD snapshot plus the
#:   full (untruncated) WAL.
#: partition — the active controller stops lease heartbeats for
#:   ``param`` seconds (partitioned from the shared session dir): a hot
#:   standby takes over; on resume the old active self-fences via the
#:   lease file and exits without touching the WAL.
#: zombie_resurrect — like ``partition``, but on resume the deposed
#:   controller first attempts a daemon write (``controller_hello``)
#:   with its stale epoch: daemons must reject it with
#:   ``stale_controller`` (counted in
#:   ``raytpu_controller_fenced_writes_total``), then it exits.
CONTROLLER_FAULT_MODES = (
    "kill_mid_mutation",
    "kill_mid_snapshot",
    "partition",
    "zombie_resurrect",
)


class ControllerFaultPlan:
    """Seeded control-plane fault plan (``RAY_TPU_testing_controller_chaos``).

    Spec grammar (same shape as :class:`ReplicaFaultPlan`)::

        "<mode>:<prob>[:<param>][:<max>][, ...]"

    ``param`` — for the kill modes: matching-phase consults to SKIP
    before the rule becomes eligible (default 0), which lets a test land
    the kill mid-burst instead of on the first mutation; for
    ``partition``/``zombie_resurrect``: seconds of lease silence
    (default 2.0). ``max`` — per-process injection cap (default 1).

    Consults happen once per control-plane phase that executes:
    ``consult("mutation")`` per WAL append, ``consult("snapshot")`` per
    snapshot write, ``consult("lease")`` per lease heartbeat tick.

    DETERMINISM CONTRACT (same as :class:`RpcFaultPlan`): exactly one
    RNG draw per consult, whether or not any rule matches — the full
    injection schedule is a pure function of (seed, ordered consulted
    phases), so a failure log carrying seed + spec replays exactly.
    """

    #: which phase each mode fires in
    _PHASE = {
        "kill_mid_mutation": "mutation",
        "kill_mid_snapshot": "snapshot",
        "partition": "lease",
        "zombie_resurrect": "lease",
    }

    def __init__(self, spec: str, seed: int):
        self.spec = spec
        self.seed = seed
        #: [mode, prob, param, max_injections]
        self.rules: List[List[float]] = []
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            fields = part.split(":")
            if len(fields) < 2:
                raise ValueError(
                    f"bad controller chaos rule {part!r} (need mode:prob)"
                )
            mode, prob = fields[0], float(fields[1])
            if mode not in CONTROLLER_FAULT_MODES:
                raise ValueError(
                    f"unknown controller chaos mode {mode!r} "
                    f"(one of {CONTROLLER_FAULT_MODES})"
                )
            param = float(fields[2]) if len(fields) > 2 else (
                0.0 if mode.startswith("kill_") else 2.0
            )
            cap = int(fields[3]) if len(fields) > 3 else 1
            self.rules.append([mode, prob, param, cap])
        self._rng = random.Random(seed)
        self.consults = 0
        self.injections = 0
        self._phase_consults = [0] * len(self.rules)
        self._injected = [0] * len(self.rules)

    @classmethod
    def _matches(cls, mode: str, phase: str) -> bool:
        return cls._PHASE[mode] == phase

    def consult(self, phase: str) -> Optional[Tuple[str, float]]:
        """One deterministic consult for a control-plane phase
        (``"mutation"`` | ``"snapshot"`` | ``"lease"``): ``(mode,
        param)`` to inject, else None. Exactly one RNG draw regardless
        of outcome."""
        draw = self._rng.random()  # ALWAYS one draw (see class docstring)
        self.consults += 1
        for i, (mode, prob, param, cap) in enumerate(self.rules):
            if not self._matches(mode, phase):
                continue
            self._phase_consults[i] += 1
            if mode.startswith("kill_") and self._phase_consults[i] <= param:
                return None  # inside the skip window
            if self._injected[i] >= cap:
                return None
            if draw < prob:
                self._injected[i] += 1
                self.injections += 1
                return (mode, param)
            return None  # first matching rule owns the draw
        return None


def find_worker_pids(controller_addr: str) -> List[int]:
    """PIDs of worker_main processes attached to ``controller_addr``
    (shared /proc scan: ``util/reaper.py::find_runtime_pids``)."""
    from ray_tpu.util.reaper import find_runtime_pids

    return find_runtime_pids(
        patterns=("ray_tpu.core.worker_main",), controller_addr=controller_addr
    )


class WorkerKiller:
    """Kills a random session worker every ``interval_s`` until stopped
    (reference ``WorkerKillerActor``). Run alongside a workload; the
    workload's correctness under SIGKILLed workers is the assertion."""

    def __init__(
        self,
        controller_addr: str,
        *,
        interval_s: float = 1.0,
        max_kills: Optional[int] = None,
        seed: Optional[int] = None,
    ):
        self.controller_addr = controller_addr
        self.interval_s = interval_s
        self.max_kills = max_kills
        self.kills: List[int] = []
        self._rng = random.Random(seed)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "WorkerKiller":
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="chaos-worker-killer"
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            if self.max_kills is not None and len(self.kills) >= self.max_kills:
                return
            pids = find_worker_pids(self.controller_addr)
            if not pids:
                continue
            pid = self._rng.choice(pids)
            try:
                os.kill(pid, signal.SIGKILL)
                self.kills.append(pid)
            except OSError:
                pass  # already gone

    def stop(self) -> List[int]:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        return self.kills


class PreemptionKiller:
    """Simulates a spot/maintenance preemption against a
    ``cluster_utils.Cluster`` node: deliver the warning (SIGTERM to the
    node daemon — which enters the drain protocol), wait ``grace_s``,
    then SIGKILL the whole process group — exactly the contract real TPU
    capacity gives you. A clean drain finishes before the grace and the
    SIGKILL hits a corpse; a too-slow drain is cut off mid-flight, which
    is the abrupt-death fallback path under test.

    ``preempt(node)`` fires once synchronously; ``start()`` runs a
    background loop preempting a random non-head node every
    ``interval_s`` (replacing it when ``replace=True``)."""

    def __init__(
        self,
        cluster,
        *,
        grace_s: float = 5.0,
        interval_s: float = 5.0,
        replace: bool = False,
        node_resources: Optional[dict] = None,
        num_cpus: float = 1,
        max_kills: Optional[int] = None,
        seed: Optional[int] = None,
    ):
        self.cluster = cluster
        self.grace_s = grace_s
        self.interval_s = interval_s
        self.replace = replace
        self.node_resources = node_resources
        self.num_cpus = num_cpus
        self.max_kills = max_kills
        self.kills = 0
        self._rng = random.Random(seed)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def preempt(self, node) -> None:
        """Warning now, SIGKILL after the grace. Blocks for ``grace_s``."""
        try:
            os.kill(node.pid, signal.SIGTERM)  # the preemption warning
        except OSError:
            return
        deadline = time.monotonic() + self.grace_s
        while time.monotonic() < deadline:
            if node.poll() is not None:
                break  # drained and exited before the axe fell
            time.sleep(0.1)
        try:
            os.killpg(os.getpgid(node.pid), signal.SIGKILL)
        except OSError:
            pass
        try:
            node.wait(timeout=10)
        except Exception:
            pass
        if node in self.cluster.nodes:
            self.cluster.nodes.remove(node)
        self.kills += 1

    def start(self) -> "PreemptionKiller":
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="chaos-preemption-killer"
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            if self.max_kills is not None and self.kills >= self.max_kills:
                return
            nodes = list(self.cluster.nodes)
            if not nodes:
                continue
            self.preempt(self._rng.choice(nodes))
            if self.replace:
                self.cluster.add_node(
                    num_cpus=self.num_cpus, resources=self.node_resources
                )

    def stop(self) -> int:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
        return self.kills


class NodeKiller:
    """Periodically hard-kills a random non-head node of a
    ``cluster_utils.Cluster`` and (optionally) replaces it — the
    elastic-membership half of the reference's ``NodeKillerBase``."""

    def __init__(
        self,
        cluster,
        *,
        interval_s: float = 3.0,
        replace: bool = True,
        node_resources: Optional[dict] = None,
        num_cpus: float = 1,
        max_kills: Optional[int] = None,
        seed: Optional[int] = None,
    ):
        self.cluster = cluster
        self.interval_s = interval_s
        self.replace = replace
        self.node_resources = node_resources
        self.num_cpus = num_cpus
        self.max_kills = max_kills
        self.kills = 0
        self._rng = random.Random(seed)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "NodeKiller":
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="chaos-node-killer"
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            if self.max_kills is not None and self.kills >= self.max_kills:
                return
            nodes = list(self.cluster.nodes)
            if not nodes:
                continue
            node = self._rng.choice(nodes)
            try:
                self.cluster.remove_node(node)
                self.kills += 1
            except Exception:
                continue
            if self.replace:
                self.cluster.add_node(
                    num_cpus=self.num_cpus, resources=self.node_resources
                )

    def stop(self) -> int:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        return self.kills
