"""crc32 combination (zlib's ``crc32_combine``) for the zero-copy pull.

The receive path needs the running whole-object crc AND per-chunk
verification. Computing both naively costs two full passes over every
received byte (``crc32(view)`` to verify, ``crc32(view, running)`` to
fold) — at ~1 GB/s per pass that is a material fraction of the transfer
budget on the bench box. CRC-32 is linear over GF(2), so the fold can
instead be DERIVED from the already-verified chunk crc:

    crc(A || B) = M(len(B)) · crc(A)  ^  crc(B)

where ``M(n)`` is a 32×32 GF(2) matrix depending only on ``n``. This
module ports zlib's ``crc32_combine`` with one twist: the whole
operator-matrix product for a given length is built once and LRU-cached
(a transfer sees at most two distinct chunk lengths — the chunk size
and the tail), so the per-chunk cost is one 32-step matrix·vector
multiply (~µs) instead of a megabytes-long data pass.
"""

from __future__ import annotations

from functools import lru_cache
from typing import List, Tuple

_POLY = 0xEDB88320  # reflected CRC-32 polynomial (zlib/binascii)


def _gf2_matrix_times(mat: Tuple[int, ...], vec: int) -> int:
    s = 0
    i = 0
    while vec:
        if vec & 1:
            s ^= mat[i]
        vec >>= 1
        i += 1
    return s


def _gf2_matrix_square(mat: List[int]) -> List[int]:
    return [_gf2_matrix_times(mat, mat[n]) for n in range(32)]


def _gf2_matrix_mul(a: List[int], b: List[int]) -> List[int]:
    """Column-wise product a·b (columns of b mapped through a)."""
    return [_gf2_matrix_times(a, b[n]) for n in range(32)]


@lru_cache(maxsize=64)
def _combine_op(len2: int) -> Tuple[int, ...]:
    """The cached operator M(len2): crc(A||B) = M·crc(A) ^ crc(B).

    Port of zlib crc32_combine's matrix walk, accumulating the product
    into one matrix instead of mutating the crc — built once per
    distinct length, applied per chunk in ~32 bit-ops."""
    # odd = operator for one zero bit fed into the crc shift register
    odd = [_POLY] + [1 << (n - 1) for n in range(1, 32)]
    even = _gf2_matrix_square(odd)
    odd = _gf2_matrix_square(even)
    op = [1 << n for n in range(32)]  # identity
    n = len2
    while True:
        even = _gf2_matrix_square(odd)
        if n & 1:
            op = _gf2_matrix_mul(even, op)
        n >>= 1
        if not n:
            break
        odd = _gf2_matrix_square(even)
        if n & 1:
            op = _gf2_matrix_mul(odd, op)
        n >>= 1
        if not n:
            break
    return tuple(op)


def crc32_combine(crc1: int, crc2: int, len2: int) -> int:
    """crc32 of ``A || B`` given ``crc1 = crc32(A)``, ``crc2 = crc32(B)``
    and ``len2 = len(B)`` — no pass over either buffer."""
    if len2 <= 0:
        return crc1 & 0xFFFFFFFF
    return (_gf2_matrix_times(_combine_op(len2), crc1) ^ crc2) & 0xFFFFFFFF
