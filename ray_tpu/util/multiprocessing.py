"""multiprocessing.Pool shim over the runtime.

Reference: ``python/ray/util/multiprocessing/pool.py`` — drop-in
``Pool`` with map/imap/imap_unordered/starmap/apply/apply_async so code
written for the stdlib scales across the cluster unchanged.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, List, Optional

import ray_tpu


class AsyncResult:
    def __init__(self, ref):
        self._ref = ref

    def get(self, timeout: Optional[float] = None) -> Any:
        return ray_tpu.get(self._ref, timeout=timeout)

    def wait(self, timeout: Optional[float] = None) -> None:
        ray_tpu.wait([self._ref], num_returns=1, timeout=timeout)

    def ready(self) -> bool:
        done, _ = ray_tpu.wait([self._ref], num_returns=1, timeout=0)
        return bool(done)


class Pool:
    def __init__(self, processes: Optional[int] = None, *, ray_remote_args: Optional[dict] = None):
        self._n = processes or 4
        args = dict(ray_remote_args or {})
        args.setdefault("num_cpus", 1)
        self._remote_cache: dict = {}
        self._remote_args = args
        self._closed = False

    def _remote(self, fn: Callable):
        r = self._remote_cache.get(fn)
        if r is None:
            r = self._remote_cache[fn] = ray_tpu.remote(**self._remote_args)(fn)
        return r

    def apply(self, fn: Callable, args: tuple = (), kwargs: Optional[dict] = None) -> Any:
        return ray_tpu.get(
            self._remote(fn).remote(*args, **(kwargs or {})), timeout=None
        )

    def apply_async(self, fn: Callable, args: tuple = (), kwargs: Optional[dict] = None) -> AsyncResult:
        return AsyncResult(self._remote(fn).remote(*args, **(kwargs or {})))

    def map(self, fn: Callable, iterable: Iterable[Any], chunksize: Optional[int] = None) -> List[Any]:
        r = self._remote(fn)
        # bounded in-flight window: a huge iterable must not flood the
        # scheduler (the reference chunks for the same reason)
        window = max(self._n * 4, 16)
        items = list(iterable)
        out: List[Any] = []
        refs = []
        for it in items:
            refs.append(r.remote(it))
            if len(refs) >= window:
                out.extend(ray_tpu.get(refs, timeout=None))
                refs = []
        if refs:
            out.extend(ray_tpu.get(refs, timeout=None))
        return out

    def starmap(self, fn: Callable, iterable: Iterable[tuple]) -> List[Any]:
        r = self._remote(fn)
        return ray_tpu.get([r.remote(*args) for args in iterable], timeout=None)

    def imap(self, fn: Callable, iterable: Iterable[Any]):
        r = self._remote(fn)
        refs = [r.remote(it) for it in iterable]
        for ref in refs:
            yield ray_tpu.get(ref, timeout=None)

    def imap_unordered(self, fn: Callable, iterable: Iterable[Any]):
        r = self._remote(fn)
        refs = [r.remote(it) for it in iterable]
        while refs:
            done, refs = ray_tpu.wait(refs, num_returns=1, timeout=None)
            yield ray_tpu.get(done[0], timeout=None)

    def close(self) -> None:
        self._closed = True

    def terminate(self) -> None:
        self._closed = True

    def join(self) -> None:
        pass

    def __enter__(self) -> "Pool":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
