"""Placement groups: gang reservation of resource bundles.

Reference: ``python/ray/util/placement_group.py:41,145`` — a placement
group atomically reserves N resource bundles across the cluster with a
bundle policy (PACK / SPREAD / STRICT_PACK / STRICT_SPREAD, reference
``bundle_scheduling_policy.h:82-106``); tasks/actors then target bundles
via ``PlacementGroupSchedulingStrategy``.

TPU-first: gang semantics are *the* TPU requirement — a partial slice is
useless — so ``tpu_slice_bundles`` builds the canonical bundle list for an
N-host pod slice (one ``TPU`` bundle per host plus the slice-head marker
resource, cf. reference ``_private/accelerators/tpu.py`` pod-slice head).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from ray_tpu.core.api import _global_worker
from ray_tpu.core.exceptions import GetTimeoutError, RayTpuError
from ray_tpu.core.ids import PlacementGroupID
from ray_tpu.core.resources import tpu_slice_head_resource

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID, bundles: List[Dict[str, float]]):
        self.id = pg_id
        self.bundle_specs = bundles

    @property
    def bundle_count(self) -> int:
        return len(self.bundle_specs)

    def ready(self, timeout: Optional[float] = None) -> "PlacementGroup":
        """Block until all bundles are reserved (reference ``pg.ready()``)."""
        state = _global_worker().backend.wait_pg_ready(self.id.binary(), timeout)
        if state == "CREATED":
            return self
        if state == "INFEASIBLE":
            raise RayTpuError(
                f"placement group {self.id.hex()} is infeasible: no node set "
                f"can host bundles {self.bundle_specs}"
            )
        raise GetTimeoutError(f"placement group {self.id.hex()} not ready (state={state})")

    def wait(self, timeout_seconds: Optional[float] = None) -> bool:
        state = _global_worker().backend.wait_pg_ready(self.id.binary(), timeout_seconds)
        return state == "CREATED"

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundle_specs))

    def __repr__(self) -> str:
        return f"PlacementGroup({self.id.hex()}, bundles={self.bundle_specs})"


def placement_group(
    bundles: List[Dict[str, float]],
    strategy: str = "PACK",
    name: str = "",
    lifetime: Optional[str] = None,
) -> PlacementGroup:
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"strategy must be one of {VALID_STRATEGIES}, got {strategy!r}")
    if not bundles or any(not b for b in bundles):
        raise ValueError("bundles must be a non-empty list of non-empty dicts")
    for b in bundles:
        if any(v < 0 for v in b.values()):
            raise ValueError(f"negative resource in bundle {b}")
    pg_id = PlacementGroupID.from_random()
    worker = _global_worker()
    worker.backend.create_pg(pg_id.binary(), [dict(b) for b in bundles], strategy, name)
    return PlacementGroup(pg_id, [dict(b) for b in bundles])


def remove_placement_group(pg: PlacementGroup) -> None:
    _global_worker().backend.remove_pg(pg.id.binary())


def get_placement_group(name: str) -> PlacementGroup:
    info = _global_worker().backend.get_named_pg(name)
    if info is None:
        raise ValueError(f"no placement group named {name!r}")
    return PlacementGroup(PlacementGroupID(info["pg_id"]), info["bundles"])


def placement_group_table() -> Dict[str, Dict]:
    return _global_worker().backend.pg_table()


def tpu_slice_bundles(num_hosts: int, chips_per_host: int = 4, topology: str = "") -> List[Dict[str, float]]:
    """Bundle list for gang-scheduling one pod slice: one bundle per host;
    bundle 0 additionally claims the slice-head marker resource."""
    bundles: List[Dict[str, float]] = []
    for i in range(num_hosts):
        b: Dict[str, float] = {"TPU": float(chips_per_host)}
        if i == 0 and topology:
            b[tpu_slice_head_resource(topology)] = 1.0
        bundles.append(b)
    return bundles


def tpu_slice_placement_group(
    pod_type: str,
    *,
    strategy: str = "STRICT_SPREAD",
    name: str = "",
) -> PlacementGroup:
    """Gang-reserve one whole pod slice from its type string (e.g.
    ``tpu_slice_placement_group("v4-32")`` → 4 STRICT_SPREAD bundles of 4
    chips, bundle 0 holding the ``TPU-v4-32-head`` marker). The canonical
    way to place one trainer worker per slice host."""
    from ray_tpu.accelerators import (
        pod_type_chips_per_host,
        pod_type_num_chips,
        pod_type_num_hosts,
        slice_head_resource_name,
    )

    hosts = pod_type_num_hosts(pod_type)
    per_host = pod_type_chips_per_host(pod_type)
    total = pod_type_num_chips(pod_type)
    bundles: List[Dict[str, float]] = []
    for i in range(hosts):
        chips = per_host if i < hosts - 1 else total - per_host * (hosts - 1)
        b: Dict[str, float] = {"TPU": float(min(chips, per_host))}
        if i == 0:
            b[slice_head_resource_name(pod_type)] = 1.0
        bundles.append(b)
    return placement_group(bundles, strategy=strategy, name=name)
