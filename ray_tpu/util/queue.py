"""Distributed FIFO queue backed by an actor.

Reference: ``python/ray/util/queue.py`` — ``Queue`` with
put/get/put_nowait/get_nowait/size/empty/full, usable from any worker
(the handle pickles; the state lives in one queue actor).
"""

from __future__ import annotations

from typing import Any, List, Optional

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    def __init__(self, maxsize: int):
        import asyncio
        from collections import deque

        self._maxsize = maxsize
        self._q = deque()
        self._not_empty = asyncio.Condition()
        self._not_full = asyncio.Condition()

    async def put(self, item, timeout: Optional[float] = None) -> bool:
        import asyncio

        async with self._not_full:
            if self._maxsize > 0:
                try:
                    await asyncio.wait_for(
                        self._not_full.wait_for(
                            lambda: len(self._q) < self._maxsize
                        ),
                        timeout,
                    )
                except asyncio.TimeoutError:
                    return False
            self._q.append(item)
        async with self._not_empty:
            self._not_empty.notify()
        return True

    async def get(self, timeout: Optional[float] = None):
        import asyncio

        async with self._not_empty:
            try:
                await asyncio.wait_for(
                    self._not_empty.wait_for(lambda: len(self._q) > 0), timeout
                )
            except asyncio.TimeoutError:
                return (False, None)
            item = self._q.popleft()
        async with self._not_full:
            self._not_full.notify()
        return (True, item)

    async def qsize(self) -> int:
        return len(self._q)


QueueActor = ray_tpu.remote(_QueueActor)


class Queue:
    def __init__(self, maxsize: int = 0, *, actor_options: Optional[dict] = None):
        self.maxsize = maxsize
        opts = dict(actor_options or {})
        opts.setdefault("num_cpus", 0)
        opts.setdefault("max_concurrency", 16)
        self._actor = QueueActor.options(**opts).remote(maxsize)

    def put(self, item: Any, timeout: Optional[float] = None) -> None:
        ok = ray_tpu.get(self._actor.put.remote(item, timeout), timeout=None)
        if not ok:
            raise Full("queue full")

    def put_nowait(self, item: Any) -> None:
        self.put(item, timeout=0.001)

    def get(self, timeout: Optional[float] = None) -> Any:
        ok, item = ray_tpu.get(self._actor.get.remote(timeout), timeout=None)
        if not ok:
            raise Empty("queue empty")
        return item

    def get_nowait(self) -> Any:
        return self.get(timeout=0.001)

    def qsize(self) -> int:
        return ray_tpu.get(self._actor.qsize.remote(), timeout=30)

    def empty(self) -> bool:
        return self.qsize() == 0

    def full(self) -> bool:
        return self.maxsize > 0 and self.qsize() >= self.maxsize

    def get_batch(self, n: int, timeout: Optional[float] = None) -> List[Any]:
        return [self.get(timeout) for _ in range(n)]

    def shutdown(self) -> None:
        try:
            ray_tpu.kill(self._actor)
        except Exception:
            pass

    def __reduce__(self):
        return (_rebuild_queue, (self.maxsize, self._actor))


def _rebuild_queue(maxsize, actor):
    q = object.__new__(Queue)
    q.maxsize = maxsize
    q._actor = actor
    return q
