"""Escalating process reaping + runtime-process discovery.

The failure mode this closes (round-5 verdict): suite runs wedging on
leaked ``worker_main``/``node_main``/``head_main`` processes — a child
that ignores SIGTERM (or whose parent died before waiting) survives
teardown, holds ports/shm/CPU, and poisons every later test. Reaping
here is *escalating* and *bounded*: SIGTERM → wait ``reap_term_grace_s``
→ SIGKILL → wait ``reap_kill_grace_s`` → report. Nothing in this module
ever blocks indefinitely.

Discovery (``find_runtime_pids``) generalizes the ``/proc`` scan that
``util/chaos.py::find_worker_pids`` pioneered: match runtime entrypoint
cmdlines, optionally scoped to one cluster via the
``RAY_TPU_CONTROLLER_ADDR`` env var — so a leak check (or a chaos
killer) never touches another session's processes.
"""

from __future__ import annotations

import os
import signal
import subprocess
import time
from typing import Iterable, List, Optional, Sequence, Union

from ray_tpu.core.config import GLOBAL_CONFIG

#: cmdline markers of every process the runtime spawns
RUNTIME_ENTRYPOINTS = (
    "ray_tpu.core.worker_main",
    "ray_tpu.core.node_main",
    "ray_tpu.core.head_main",
    "ray_tpu.core.controller_main",
)

ProcOrPid = Union[subprocess.Popen, int]


def _pid_of(target: ProcOrPid) -> int:
    return target.pid if isinstance(target, subprocess.Popen) else int(target)


def _alive(target: ProcOrPid) -> bool:
    if isinstance(target, subprocess.Popen):
        return target.poll() is None
    try:
        os.kill(int(target), 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True


def _signal(target: ProcOrPid, sig: int, *, group: bool) -> None:
    pid = _pid_of(target)
    try:
        if group:
            os.killpg(os.getpgid(pid), sig)
        else:
            os.kill(pid, sig)
    except (ProcessLookupError, PermissionError, OSError):
        pass


def _wait(target: ProcOrPid, grace_s: float) -> bool:
    """Wait (bounded) for death; reaps the zombie when we're the parent.
    Returns True when the process is gone."""
    if isinstance(target, subprocess.Popen):
        try:
            target.wait(timeout=grace_s)
            return True
        except Exception:
            return target.poll() is not None
    deadline = time.monotonic() + grace_s
    pid = int(target)
    while time.monotonic() < deadline:
        try:  # collect the zombie if it is our child
            os.waitpid(pid, os.WNOHANG)
        except (ChildProcessError, OSError):
            pass
        if not _alive(pid):
            return True
        time.sleep(0.05)
    return not _alive(pid)


def _group_pgid(target: ProcOrPid) -> Optional[int]:
    try:
        return os.getpgid(_pid_of(target))
    except (ProcessLookupError, PermissionError, OSError):
        return None


def _group_alive(pgid: int) -> bool:
    try:
        os.killpg(pgid, 0)
        return True
    except ProcessLookupError:
        return False
    except (PermissionError, OSError):
        return True


def _sweep_group_stragglers(pgid: Optional[int], kill_grace_s: float) -> bool:
    """A group leader's clean exit does not prove its group is empty: a
    worker spawned in the shutdown race window (or one that missed the
    group SIGTERM) survives the leader. SIGKILL whatever remains in the
    group and wait, bounded. Returns True when the group is empty."""
    if pgid is None or not _group_alive(pgid):
        return True
    try:
        os.killpg(pgid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError, OSError):
        pass
    deadline = time.monotonic() + kill_grace_s
    while time.monotonic() < deadline:
        if not _group_alive(pgid):
            return True
        time.sleep(0.05)
    return not _group_alive(pgid)


def reap_process(
    target: ProcOrPid,
    *,
    term_grace_s: Optional[float] = None,
    kill_grace_s: Optional[float] = None,
    group: bool = False,
) -> bool:
    """SIGTERM → wait → SIGKILL → wait. Returns True when the process is
    verifiably gone. ``group=True`` escalates the whole process group
    (node daemons own their workers' group) and verifies the GROUP is
    empty, not just the leader — stragglers are SIGKILLed."""
    term_grace = term_grace_s if term_grace_s is not None else GLOBAL_CONFIG.reap_term_grace_s
    kill_grace = kill_grace_s if kill_grace_s is not None else GLOBAL_CONFIG.reap_kill_grace_s
    pgid = _group_pgid(target) if group else None
    if not _alive(target):
        _wait(target, 0.0)  # collect a zombie child
        return _sweep_group_stragglers(pgid, kill_grace) if group else True
    _signal(target, signal.SIGTERM, group=group)
    gone = _wait(target, term_grace)
    if not gone:
        _signal(target, signal.SIGKILL, group=group)
        gone = _wait(target, kill_grace)
    if group:
        gone = _sweep_group_stragglers(pgid, kill_grace) and gone
    return gone


def reap_all(
    targets: Iterable[ProcOrPid],
    *,
    term_grace_s: Optional[float] = None,
    kill_grace_s: Optional[float] = None,
    group: bool = False,
) -> List[int]:
    """Escalate a set of processes CONCURRENTLY: one shared SIGTERM grace
    (not N sequential ones), then SIGKILL the survivors. Returns pids
    that still refused to die (should be empty; SIGKILL is not
    ignorable, only D-state survives it)."""
    targets = list(targets)
    term_grace = term_grace_s if term_grace_s is not None else GLOBAL_CONFIG.reap_term_grace_s
    kill_grace = kill_grace_s if kill_grace_s is not None else GLOBAL_CONFIG.reap_kill_grace_s
    pgids = [_group_pgid(t) for t in targets] if group else []
    live = [t for t in targets if _alive(t)]
    for t in targets:
        if t not in live:
            _wait(t, 0.0)  # collect zombies
    for t in live:
        _signal(t, signal.SIGTERM, group=group)
    deadline = time.monotonic() + term_grace
    while live and time.monotonic() < deadline:
        for t in live:
            _wait(t, 0.0)  # collect zombies as they die
        live = [t for t in live if _alive(t)]
        if live:
            time.sleep(0.05)
    for t in live:
        _signal(t, signal.SIGKILL, group=group)
    survivors: List[int] = []
    for t in live:
        if not _wait(t, kill_grace):
            survivors.append(_pid_of(t))
    if group:
        # leaders are gone; their groups may not be (shutdown-race spawns)
        for pgid in pgids:
            _sweep_group_stragglers(pgid, kill_grace)
    return survivors


def pid_alive(pid: int) -> bool:
    """Liveness probe shared with the test-side leak guards."""
    return _alive(int(pid))


def find_runtime_pids(
    patterns: Sequence[str] = RUNTIME_ENTRYPOINTS,
    controller_addr: Optional[str] = None,
    spawner_pid: Optional[int] = None,
) -> List[int]:
    """PIDs of runtime processes, by ``/proc`` cmdline scan. With
    ``controller_addr``, only processes bound to that cluster match —
    workers carry it in env (``RAY_TPU_CONTROLLER_ADDR``), node daemons
    in their ``--controller`` cmdline arg. Full-value matching: ':812'
    must not claim another cluster's ':8123' processes. With
    ``spawner_pid``, only processes whose env stamps that spawning driver
    (``RAY_TPU_SPAWNER_PID``, inherited daemon→worker) match — what lets
    a leak guard ignore a sibling session's clusters entirely."""
    me = os.getpid()
    out: List[int] = []
    for pid_s in os.listdir("/proc"):
        if not pid_s.isdigit():
            continue
        pid = int(pid_s)
        if pid == me:
            continue
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                raw_cmd = f.read()
            cmd = raw_cmd.decode(errors="replace")
            if not any(p in cmd for p in patterns):
                continue
            env = None
            if controller_addr is not None:
                # cmdline args are NUL-separated — exact-arg match
                if f"\x00{controller_addr}\x00".encode() not in raw_cmd:
                    with open(f"/proc/{pid}/environ", "rb") as f:
                        env = f.read().decode(errors="replace")
                    if f"RAY_TPU_CONTROLLER_ADDR={controller_addr}\x00" not in env:
                        continue
            if spawner_pid is not None:
                if env is None:
                    with open(f"/proc/{pid}/environ", "rb") as f:
                        env = f.read().decode(errors="replace")
                if f"{SPAWNER_PID_ENV}={spawner_pid}\x00" not in env:
                    continue
            out.append(pid)
        except (OSError, PermissionError):
            continue  # raced process exit
    return out


#: set by driver-owned spawn paths (``cluster_backend._subprocess_env``):
#: daemons spawned FOR a driver/test exit when that driver dies. The CLI
#: (``ray_tpu start``) deliberately does not set it — a detached cluster
#: must survive its starter.
EXIT_ON_DRIVER_EXIT_ENV = "RAY_TPU_EXIT_ON_DRIVER_EXIT"

#: pid of the spawning driver, stamped by ``_subprocess_env`` — the
#: orphan watch compares against THIS, not a boot-time ``os.getppid()``
#: (the driver can die while the child is still importing, which would
#: memorize the already-reparented value and never trigger)
SPAWNER_PID_ENV = "RAY_TPU_SPAWNER_PID"


def start_orphan_watch(on_orphan, *, hard_exit_after_s: float = 10.0):
    """Watch for reparenting (our spawner died) and fire ``on_orphan``
    for a graceful stop; hard-exit if the process is still alive after
    ``hard_exit_after_s``. No-op unless ``RAY_TPU_EXIT_ON_DRIVER_EXIT=1``
    in this process's env. Returns the watcher thread (or None).

    This is the defense against the "orphaned head_main" leak class: a
    driver killed without running shutdown (SIGKILLed pytest, crashed
    bench script) leaves its cluster running forever otherwise."""
    import threading

    if os.environ.get(EXIT_ON_DRIVER_EXIT_ENV) != "1":
        return None
    expected_ppid = int(os.environ.get(SPAWNER_PID_ENV, 0)) or os.getppid()

    def _watch() -> None:
        while True:
            if os.getppid() != expected_ppid:
                try:
                    on_orphan()
                except Exception:
                    pass
                time.sleep(hard_exit_after_s)  # graceful-stop window
                os._exit(0)
            time.sleep(1.0)

    t = threading.Thread(target=_watch, daemon=True, name="driver-orphan-watch")
    t.start()
    return t
