"""User-facing scheduling strategies (reference
``python/ray/util/scheduling_strategies.py``). These normalize to the
internal ``task_spec`` strategy dataclasses at ``.options()`` time."""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Union

from ray_tpu.core.task_spec import (
    NodeAffinityScheduling,
    NodeLabelScheduling,
    PlacementGroupScheduling,
)


def PlacementGroupSchedulingStrategy(
    placement_group,
    placement_group_bundle_index: int = -1,
    placement_group_capture_child_tasks: bool = False,
) -> PlacementGroupScheduling:
    return PlacementGroupScheduling(
        pg_id=placement_group.id.binary(),
        bundle_index=placement_group_bundle_index,
        capture_child_tasks=placement_group_capture_child_tasks,
    )


def NodeAffinitySchedulingStrategy(node_id: Union[str, bytes], soft: bool = False) -> NodeAffinityScheduling:
    if isinstance(node_id, str):
        node_id = bytes.fromhex(node_id)
    return NodeAffinityScheduling(node_id=node_id, soft=soft)


def NodeLabelSchedulingStrategy(
    hard: Optional[Dict[str, Sequence[str]]] = None,
    soft: Optional[Dict[str, Sequence[str]]] = None,
) -> NodeLabelScheduling:
    def norm(d):
        return tuple((k, tuple(v)) for k, v in (d or {}).items())

    return NodeLabelScheduling(hard=norm(hard), soft=norm(soft))
