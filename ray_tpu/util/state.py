"""State API: cluster introspection (reference ``ray list ...``).

Reference: ``python/ray/util/state/api.py:110,781`` — `list
tasks/actors/objects/nodes/placement_groups` served from the control
plane (``GcsTaskManager`` task events + controller tables + daemon
object stores)."""

from __future__ import annotations

from typing import Any, Dict, List

from ray_tpu.core.api import _global_worker


def _call(method: str, payload: Dict[str, Any] = None):
    core = _global_worker().backend
    return core.io.run(core.controller.call(method, payload or {}))


def list_nodes() -> List[Dict[str, Any]]:
    return _call("nodes")


def list_actors() -> List[Dict[str, Any]]:
    return _call("list_actors")


def list_tasks(limit: int = 1000) -> List[Dict[str, Any]]:
    """Recent task lifecycle states (bounded ring; latest state wins)."""
    return _call("list_tasks", {"limit": limit})


def list_objects() -> List[Dict[str, Any]]:
    """Cluster-wide shm objects, aggregated across node daemons."""
    return _call("list_objects")


def list_placement_groups() -> List[Dict[str, Any]]:
    table = _call("pg_table")
    return [dict(info, pg_id=pg_id) for pg_id, info in table.items()]


def summarize_tasks() -> Dict[str, int]:
    out: Dict[str, int] = {}
    for t in list_tasks():
        out[t["state"]] = out.get(t["state"], 0) + 1
    return out


def attach_serve_slo(out: Dict[str, Any]) -> Dict[str, Any]:
    """Best-effort ``serve_slo`` section for a cluster-status dict: the
    per-deployment SLO summary (TTFT/ITL/e2e p50/p99/p99.9, goodput
    fraction, book balance) from ``serve.slo_report()`` with a trimmed
    flight-recorder dump. Absent when serving isn't up (plain clusters
    must not pay a fan-out) or the controller is mid-failover."""
    try:
        import ray_tpu
        from ray_tpu.serve.controller import CONTROLLER_NAME

        ray_tpu.get_actor(CONTROLLER_NAME)  # raises when serving is down
        from ray_tpu import serve

        out["serve_slo"] = serve.slo_report(flight_limit=20, timeout=10)
    except Exception:  # noqa: BLE001 — no serve tier, or it is mid-failover
        pass
    return out


def cluster_status(serve_slo: bool = True) -> Dict[str, Any]:
    """One-call live cluster view (``ray_tpu.cluster_status()``). When a
    serve controller is running a ``serve_slo`` section rides along (see
    :func:`attach_serve_slo`); that is a per-replica fan-out, so
    high-frequency monitoring loops that only want the control-plane
    tables should pass ``serve_slo=False``."""
    out = _call("cluster_status")
    return attach_serve_slo(out) if serve_slo else out


def cluster_telemetry() -> Dict[str, Any]:
    """Federated metrics: ``{"controller": text, "nodes": {node_hex:
    text}, "federate_port"}`` — raw Prometheus exposition per source;
    the merged node-labeled view is served at the controller's
    ``/federate`` HTTP path."""
    return _call("cluster_telemetry")
