"""State API: cluster introspection (reference ``ray list ...``).

Reference: ``python/ray/util/state/api.py:110,781`` — `list
tasks/actors/objects/nodes/placement_groups` served from the control
plane (``GcsTaskManager`` task events + controller tables + daemon
object stores)."""

from __future__ import annotations

from typing import Any, Dict, List

from ray_tpu.core.api import _global_worker


def _call(method: str, payload: Dict[str, Any] = None):
    core = _global_worker().backend
    return core.io.run(core.controller.call(method, payload or {}))


def list_nodes() -> List[Dict[str, Any]]:
    return _call("nodes")


def list_actors() -> List[Dict[str, Any]]:
    return _call("list_actors")


def list_tasks(limit: int = 1000) -> List[Dict[str, Any]]:
    """Recent task lifecycle states (bounded ring; latest state wins)."""
    return _call("list_tasks", {"limit": limit})


def list_objects() -> List[Dict[str, Any]]:
    """Cluster-wide shm objects, aggregated across node daemons."""
    return _call("list_objects")


def list_placement_groups() -> List[Dict[str, Any]]:
    table = _call("pg_table")
    return [dict(info, pg_id=pg_id) for pg_id, info in table.items()]


def summarize_tasks() -> Dict[str, int]:
    out: Dict[str, int] = {}
    for t in list_tasks():
        out[t["state"]] = out.get(t["state"], 0) + 1
    return out


def cluster_status() -> Dict[str, Any]:
    """One-call live cluster view (``ray_tpu.cluster_status()``)."""
    return _call("cluster_status")


def cluster_telemetry() -> Dict[str, Any]:
    """Federated metrics: ``{"controller": text, "nodes": {node_hex:
    text}, "federate_port"}`` — raw Prometheus exposition per source;
    the merged node-labeled view is served at the controller's
    ``/federate`` HTTP path."""
    return _call("cluster_telemetry")
