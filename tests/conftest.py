"""Test fixtures.

Mirrors the reference's load-bearing fixtures
(``python/ray/tests/conftest.py``): ``ray_start_local`` (eager in-process),
``ray_start_regular`` (real single-node runtime), and the simulated
multi-node ``cluster`` fixture (``python/ray/cluster_utils.py:135``).

JAX-dependent tests run on a virtual 8-device CPU mesh: the env vars below
must be set before jax initializes, which this conftest guarantees because
pytest imports it before any test module.

Hang defense (see ``ray_tpu/observability/event_stats.py`` and
``ray_tpu/util/reaper.py``):

* every test runs under a HARD timeout enforced by stdlib
  ``faulthandler.dump_traceback_later(..., exit=True)`` — a wedged test
  dumps every thread's stack and aborts the run instead of freezing the
  suite (and the box) indefinitely;
* spawned runtime processes run with ``watchdog_abort_after_s`` set, so a
  daemon/worker whose event loop stalls hard-exits (code 70) after dumping
  its stacks rather than holding ports/shm forever;
* an autouse leak guard snapshots runtime pids around each test and FAILS
  the test that leaked ``worker_main``/``node_main``/``head_main``
  processes — "suite wedged 25 minutes" becomes a named failure.
"""

import os

# NOTE: a sitecustomize hook in this environment imports the axon TPU
# plugin at interpreter startup, BEFORE this conftest runs — so setting
# platform env vars here is too late for this process (they still matter
# for subprocesses, which see them as real process env). For this process,
# update the jax config directly before any backend initializes.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("JAX_PLATFORM_NAME", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# Test-mode hang defense: runtime processes spawned by tests inherit this
# env, so a process whose event loop stalls past the threshold dumps
# stacks and hard-exits instead of silently wedging the suite. Set before
# importing ray_tpu (GLOBAL_CONFIG reads env at import).
os.environ.setdefault("RAY_TPU_watchdog_abort_after_s", "120")

# One chaos seed per SESSION, chosen here (before ray_tpu imports config)
# and printed in the report header: every chaos-enabled test in this run
# draws its fault plan from this seed, and spawned runtime processes
# inherit it through env + system-config — so a chaos-test failure in a
# tier-1 log is reproducible from the log alone by re-exporting the
# printed RAY_TPU_testing_rpc_chaos_seed value.
if not os.environ.get("RAY_TPU_testing_rpc_chaos_seed"):
    os.environ["RAY_TPU_testing_rpc_chaos_seed"] = str(
        int.from_bytes(os.urandom(3), "little") | 1
    )

# One MASTER chaos seed per session too (util/chaos.py::derive_plan_seed):
# any fault plan armed without its own seed knob derives deterministically
# from this value, so a multi-plan chaos failure replays from ONE number
# instead of three. Explicit per-plan seeds (like the rpc one above) win.
if not os.environ.get("RAY_TPU_testing_chaos_seed"):
    os.environ["RAY_TPU_testing_chaos_seed"] = str(
        int.from_bytes(os.urandom(3), "little") | 1
    )

import faulthandler  # noqa: E402

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

import ray_tpu  # noqa: E402
from ray_tpu.observability import event_stats as _event_stats  # noqa: E402
from ray_tpu.util.reaper import find_runtime_pids, pid_alive, reap_all  # noqa: E402

# The pytest process itself must never watchdog-ABORT (that kills the
# whole suite; its wedges are bounded by the per-test faulthandler timer
# below) — it still detects and DUMPS loop stalls. Spawned runtime
# processes don't import this conftest and keep the 120s hard abort.
_event_stats.ABORT_DISABLED_IN_PROCESS = True

# faulthandler output must survive pytest's fd-level capture. A dup of
# fd 2 here does NOT work: tests/conftest.py imports during collection,
# AFTER the capture plugin has already swapped fd 2 for its tempfile, so
# the dup points into the capture buffer and _exit(1) discards it — the
# hard-timeout abort then looks like a silent exit-code-1 with zero
# output (exactly the unattributable wedge this timer exists to avoid).
# Dump to a well-known file instead; truncated each session, announced in
# pytest's report header (the one place guaranteed visible in the log even
# when the abort itself prints nothing), overridable for parallel runs.
_DUMP_PATH = os.environ.get(
    "RAY_TPU_TEST_DUMP_FILE", "/tmp/raytpu_test_timeout_dump.log"
)
try:
    _DUMP_FILE = open(_DUMP_PATH, "w")
    _DUMP_FILE.write(
        "armed: a per-test hard-timeout stack dump will land here "
        "(tests/conftest.py raytpu_test_timeout); an empty-but-armed file "
        "means no test overran its timer\n"
    )
    _DUMP_FILE.flush()
except OSError:
    _DUMP_FILE = None


def pytest_report_header(config):
    # a hard-timeout abort is exit-code-1 with ZERO terminal output (fd 2
    # is pytest's capture tempfile by dump time) — this header line is how
    # an operator staring at a silent crash finds the stacks
    if _DUMP_FILE is None:
        lines = ["hard-timeout stack dumps: DISABLED (could not open dump file)"]
    else:
        lines = [
            f"hard-timeout stack dumps land in {_DUMP_PATH} "
            "(silent exit-1 run? look there; last '[armed]' line names the test)"
        ]
    # chaos reproducibility: any chaos-test failure in this log replays
    # with these two env vars (tests that pin their own seed say so)
    from ray_tpu.core.config import GLOBAL_CONFIG as _CFG

    plan = _CFG.testing_rpc_chaos or "(none; chaos tests set per-test specs)"
    lines.append(
        f"rpc chaos: seed={_CFG.testing_rpc_chaos_seed} plan={plan} — "
        "reproduce a chaos failure with "
        f"RAY_TPU_testing_rpc_chaos_seed={_CFG.testing_rpc_chaos_seed}"
    )
    lines.append(
        f"master chaos seed: RAY_TPU_testing_chaos_seed="
        f"{_CFG.testing_chaos_seed} (derives every plan seed not pinned "
        "explicitly — one number replays the whole composite schedule)"
    )
    return lines


# ---------------------------------------------------------------------------
# chaos repro helper: a failure under ANY seeded fault plan prints ONE
# copy-pasteable env line reproducing that session's full chaos schedule.
# The seeds already print (report header + activation logs), but the
# operator had to assemble the env by hand from three knob pairs.

def _activated_plans():
    """(spec_key, spec, seed_key, seed) for every fault plan that was
    ACTIVATED in this (driver) process — read from the SeededPlanCache
    singletons, not GLOBAL_CONFIG: chaos tests restore their config in
    their own ``finally`` BEFORE the report hook runs, which made the
    config-only version print nothing for exactly the failures it was
    built for. The cache keeps the last-activated plan's spec+seed."""
    out = []
    probes = (
        ("ray_tpu.core.rpc", "testing_rpc_chaos"),
        ("ray_tpu.core.pull_manager", "testing_pull_chaos"),
        ("ray_tpu.inference.engine", "testing_replica_chaos"),
        ("ray_tpu.inference.kv_transfer", "testing_kv_tier_chaos"),
        ("ray_tpu.core.controller", "testing_controller_chaos"),
    )
    import importlib
    import sys as _sys

    for mod_name, spec_key in probes:
        mod = _sys.modules.get(mod_name)  # never IMPORT here (engine pulls jax)
        if mod is None:
            continue
        cache = getattr(mod, "_PLAN_CACHE", None) or getattr(mod, "_RPLAN_CACHE", None)
        plan = getattr(cache, "_plan", None)
        if plan is not None:
            out.append((spec_key, plan.spec, spec_key + "_seed", plan.seed))
    return out


def _chaos_repro_line(nodeid: str):
    from ray_tpu.core.config import GLOBAL_CONFIG as cfg

    entries = {k: (spec, sk, seed) for k, spec, sk, seed in _activated_plans()}
    # config still carries a plan the driver never consulted (e.g. env
    # chaos that only child processes run): include it too
    for spec_key, seed_key in (
        ("testing_rpc_chaos", "testing_rpc_chaos_seed"),
        ("testing_pull_chaos", "testing_pull_chaos_seed"),
        ("testing_replica_chaos", "testing_replica_chaos_seed"),
        ("testing_kv_tier_chaos", "testing_kv_tier_chaos_seed"),
        ("testing_controller_chaos", "testing_controller_chaos_seed"),
    ):
        spec = getattr(cfg, spec_key)
        if spec and spec_key not in entries:
            entries[spec_key] = (spec, seed_key, getattr(cfg, seed_key))
        # env-armed plans (the ingress/stream-resume E2E pattern: the
        # test exports RAY_TPU_testing_* so CHILD processes inherit the
        # plan while the driver's GLOBAL_CONFIG stays clean — env is
        # only read at import). Without this probe exactly those
        # failures printed no repro line.
        env_spec = os.environ.get("RAY_TPU_" + spec_key)
        if env_spec and spec_key not in entries:
            entries[spec_key] = (
                env_spec,
                seed_key,
                os.environ.get("RAY_TPU_" + seed_key) or 0,
            )
    if not entries:
        return None
    # composite-chaos compression: per-plan seeds that are (or will be)
    # DERIVED from the session's master seed collapse into the one
    # master knob — a three-plan schedule replays from a single number
    from ray_tpu.util.chaos import derive_plan_seed as _derive

    _labels = {
        "testing_rpc_chaos": "rpc",
        "testing_pull_chaos": "pull",
        "testing_replica_chaos": "replica",
        "testing_kv_tier_chaos": "kv_tier",
        "testing_controller_chaos": "controller",
    }
    try:
        master = int(
            os.environ.get("RAY_TPU_testing_chaos_seed")
            or getattr(cfg, "testing_chaos_seed", 0)
            or 0
        )
    except ValueError:
        master = 0
    parts = []
    master_covers = False
    for spec_key, (spec, seed_key, seed) in entries.items():
        parts.append(f"RAY_TPU_{spec_key}={spec!r}")
        try:
            seed_i = int(seed)
        except (TypeError, ValueError):
            seed_i = 0
        if master and (
            not seed_i or seed_i == _derive(master, _labels[spec_key])
        ):
            master_covers = True
        elif seed_i:
            parts.append(f"RAY_TPU_{seed_key}={seed_i}")
    if master_covers:
        parts.append(f"RAY_TPU_testing_chaos_seed={master}")
    return (
        " ".join(parts)
        + f" python -m pytest '{nodeid}'"
        + "  # replays this session's seeded fault schedule"
        + " (a child process that GENERATED its own seed logs it at"
        + " plan activation — substitute that value)"
    )


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_makereport(item, call):
    outcome = yield
    report = outcome.get_result()
    if report.when == "call" and report.failed:
        try:
            line = _chaos_repro_line(item.nodeid)
        except Exception:
            line = None
        if line:
            report.sections.append(("chaos repro", line))


@pytest.fixture
def ray_start_local():
    ray_tpu.init(local_mode=True)
    yield
    ray_tpu.shutdown()


@pytest.fixture
def ray_start_regular():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


@pytest.fixture
def shutdown_only():
    yield
    ray_tpu.shutdown()


#: shared capability gate (import as ``from conftest import ...``):
#: jaxlib < 0.5 CPU backend has no cross-process collectives — a 2-proc
#: allgather/psum dies with "Multiprocess computations aren't implemented
#: on the CPU backend". The rendezvous itself (process_count) still works.
multiprocess_cpu_collectives = pytest.mark.skipif(
    tuple(int(x) for x in jax.__version__.split(".")[:2]) < (0, 5),
    reason="jaxlib <0.5 CPU backend lacks multiprocess collectives",
)


def wait_for_node_resource(name, *, exclude=(), timeout=20.0):
    """Block until an ALIVE node carrying resource ``name`` (and not in
    ``exclude`` node-ids) is registered — the condition-based replacement
    for the blind ``sleep(1.0)`` after ``cluster.add_node`` (suite-time
    CAUTION: fixed sleeps were ~10s of pure waiting across the cluster
    modules). Returns the node_id."""
    import time as _time

    import ray_tpu as _rt

    deadline = _time.time() + timeout
    while _time.time() < deadline:
        for n in _rt.nodes():
            if (
                n.get("Alive")
                and name in (n.get("Resources") or {})
                and n.get("node_id") not in exclude
            ):
                return n["node_id"]
        _time.sleep(0.05)
    raise TimeoutError(f"no alive node with resource {name!r} within {timeout}s")


# ---------------------------------------------------------------------------
# per-test hard timeout (stdlib faulthandler, no plugin dependency)

def pytest_addoption(parser):
    parser.addini(
        "raytpu_test_timeout",
        "per-test hard timeout in seconds; on expiry every thread's stack is "
        "dumped and the run aborts (faulthandler.dump_traceback_later). "
        "0 disables. Env override: RAY_TPU_TEST_TIMEOUT_S.",
        default="180",
    )


def _test_timeout(config) -> float:
    try:
        return float(
            os.environ.get(
                "RAY_TPU_TEST_TIMEOUT_S", config.getini("raytpu_test_timeout")
            )
        )
    except (TypeError, ValueError):
        return 180.0


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_protocol(item, nextitem):
    timeout = _test_timeout(item.config)
    armed = timeout > 0 and hasattr(faulthandler, "dump_traceback_later")
    if armed:
        # exit=True: a test that outlives the timer is unrecoverably wedged
        # (futex/GIL/asyncio) — dump all stacks and kill the process so the
        # outer harness sees a crash named by these stacks, not a freeze.
        # The dump goes to _DUMP_FILE (see above); record WHICH test armed
        # the timer so the abort is attributable even mid-dump.
        if _DUMP_FILE is not None:
            _DUMP_FILE.write(f"[armed] {item.nodeid}\n")
            _DUMP_FILE.flush()
        kwargs = {"file": _DUMP_FILE} if _DUMP_FILE is not None else {}
        faulthandler.dump_traceback_later(timeout, exit=True, **kwargs)
    try:
        yield
    finally:
        if armed:
            faulthandler.cancel_dump_traceback_later()


# ---------------------------------------------------------------------------
# leaked-process guard: the test that orphans runtime processes FAILS

#: grace for asynchronous child teardown after a test's fixtures finish
_LEAK_GRACE_S = 5.0


def _wait_for_drain(candidates, grace_s):
    import time as _time

    deadline = _time.monotonic() + grace_s
    live = [p for p in candidates if pid_alive(p)]
    while live and _time.monotonic() < deadline:
        _time.sleep(0.2)
        live = [p for p in live if pid_alive(p)]
    return live


def _our_runtime_pids():
    """Runtime processes belonging to clusters THIS pytest process
    spawned (RAY_TPU_SPAWNER_PID stamp): a sibling session's (or a dev's
    detached) cluster must never be flagged or reaped by these guards."""
    return find_runtime_pids(spawner_pid=os.getpid())


def _daemon_reachable(host: str, port: int) -> bool:
    import socket as _socket

    try:
        with _socket.create_connection((host, port), timeout=1.0):
            return True
    except OSError:
        return False


def _assert_no_ghost_draining_nodes():
    """PR 2 drain invariant: a drain-exited daemon must have DEREGISTERED
    from the controller — a node row stuck in DRAINING whose daemon
    process is GONE is a protocol leak (the controller would neither
    schedule on it nor fail its actors over). Checked while a shared
    cluster is still up; a node mid-drain (daemon still reachable) is
    legitimate and not flagged."""
    try:
        rows = ray_tpu.nodes()
    except Exception:
        return  # cluster mid-teardown: nothing to assert against
    ghosts = []
    for row in rows:
        if row.get("State") != "DRAINING":
            continue
        import time as _time

        # give an in-flight deregistration a moment to land
        deadline = _time.monotonic() + _LEAK_GRACE_S
        while _time.monotonic() < deadline:
            try:
                fresh = {n["NodeID"]: n for n in ray_tpu.nodes()}
            except Exception:
                return
            cur = fresh.get(row["NodeID"])
            if cur is None or cur.get("State") != "DRAINING":
                break
            if _daemon_reachable(cur["host"], cur["port"]):
                break  # daemon alive: legitimately mid-drain, not a ghost
            _time.sleep(0.2)
        else:
            ghosts.append(f"{row['NodeID'][:12]} ({row.get('DrainReason', '')})")
    if ghosts:
        pytest.fail(
            "test left ghost DRAINING node entries (drain-exited daemons "
            "must deregister):\n  " + "\n  ".join(ghosts),
            pytrace=False,
        )


@pytest.fixture(autouse=True)
def _runtime_leak_guard(request):
    before = set(_our_runtime_pids())
    yield
    if ray_tpu.is_initialized():
        # a module/session-scoped cluster is legitimately still up; its
        # processes are accounted for when that fixture finalizes — but
        # drain protocol state must still be clean between tests
        _assert_no_ghost_draining_nodes()
        return
    leaked = _wait_for_drain(set(_our_runtime_pids()) - before, _LEAK_GRACE_S)
    if leaked:
        details = []
        for pid in leaked:
            try:
                with open(f"/proc/{pid}/cmdline", "rb") as f:
                    cmd = f.read().replace(b"\x00", b" ").decode(errors="replace").strip()
            except OSError:
                cmd = "?"
            details.append(f"pid {pid}: {cmd}")
        reap_all(leaked)  # don't poison the rest of the suite
        pytest.fail(
            "test leaked runtime processes (reaped):\n  " + "\n  ".join(details),
            pytrace=False,
        )


@pytest.fixture(autouse=True, scope="session")
def _session_process_sweep():
    """Backstop for leaks that escape per-test attribution (module-scoped
    fixture teardown after the last test of a module): reap anything left
    at session end so consecutive suite runs start clean. Scoped to OUR
    spawner stamp — a concurrently running sibling pytest session's
    clusters must never be reaped from here."""
    yield
    leftovers = _wait_for_drain(_our_runtime_pids(), _LEAK_GRACE_S)
    if leftovers:
        import warnings

        reap_all(leftovers)
        warnings.warn(
            f"session ended with leaked runtime processes (reaped): {sorted(leftovers)}",
            stacklevel=1,
        )
