"""Test fixtures.

Mirrors the reference's load-bearing fixtures
(``python/ray/tests/conftest.py``): ``ray_start_local`` (eager in-process),
``ray_start_regular`` (real single-node runtime), and the simulated
multi-node ``cluster`` fixture (``python/ray/cluster_utils.py:135``).

JAX-dependent tests run on a virtual 8-device CPU mesh: the env vars below
must be set before jax initializes, which this conftest guarantees because
pytest imports it before any test module.
"""

import os

# NOTE: a sitecustomize hook in this environment imports the axon TPU
# plugin at interpreter startup, BEFORE this conftest runs — so setting
# platform env vars here is too late for this process (they still matter
# for subprocesses, which see them as real process env). For this process,
# update the jax config directly before any backend initializes.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("JAX_PLATFORM_NAME", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

import ray_tpu  # noqa: E402


@pytest.fixture
def ray_start_local():
    ray_tpu.init(local_mode=True)
    yield
    ray_tpu.shutdown()


@pytest.fixture
def ray_start_regular():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


@pytest.fixture
def shutdown_only():
    yield
    ray_tpu.shutdown()
